"""Destination set + consistent-hash routing + per-destination breaker.

Mirrors `proxy/destinations/destinations.go`: Add connects new addresses in
parallel (`Add`, destinations.go:47-81), Get routes a key through the hash
ring (`:129-142`), closed connections self-remove (`ConnectionClosed`,
`:100-126`), Clear tears everything down, and Wait blocks until all
destinations have drained.

On top of the reference semantics, each address carries a CIRCUIT BREAKER:
`breaker_threshold` consecutive failures (abrupt close, failed dial) TRIP
it — the address is removed from the ring, so every key that hashed to it
reroutes to the survivors (consistent-hash route-around), and re-adds are
refused while the breaker is open.  After `breaker_reset_s` (doubling per
consecutive trip, capped at 8x) the next add() for the address becomes the
HALF-OPEN probe: one real dial — success closes the breaker and restores
the member to the ring; failure re-opens it with a longer cooldown.  The
discovery poll (proxy.go:345-387 -> set_members) is the natural probe
driver: every poll re-offers the wanted membership, and the breaker decides
which offers turn into dials.

Membership changes run as a TWO-PHASE ELASTIC RESHARD (set_members):
joiners connect while the old ring still serves, then each leaver drains
its undelivered buffer through the proxy's handoff back onto the new ring
(drain-and-forward) instead of dropping it.  Consistent hashing bounds
movement at ~K/N keys per node joining an N-ring; every reshard commits a
record (epoch, members, sampled keys moved, handoff counts, duration) at
/debug/vars -> reshard.  An engaged (open/half-open) breaker survives the
flap, so a reshard can never resurrect a tripped destination without a
successful probe.
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
import time

from veneur_tpu.proxy.connect import Destination
from veneur_tpu.proxy.consistent import ConsistentHash

logger = logging.getLogger("veneur_tpu.proxy.destinations")


class _Breaker:
    """Per-address failure state.  Guarded by the Destinations lock."""

    __slots__ = ("failures", "trips", "open_until", "half_open")

    def __init__(self):
        self.failures = 0       # consecutive failures since last success
        self.trips = 0          # times the breaker has opened
        self.open_until = 0.0   # monotonic deadline; 0 = not open
        self.half_open = False  # a probe dial is in flight

    def state(self, now: float) -> str:
        if self.half_open:
            return "half_open"
        if self.open_until > now:
            return "open"
        if self.open_until:
            return "probe_due"
        return "closed"


class Destinations:
    # cooldown doubles per consecutive trip, capped at this multiple
    BREAKER_MAX_BACKOFF_X = 8

    def __init__(self, send_buffer_size: int = 1024, grpc_stats=None,
                 n_streams: int = 8, send_timeout_s: float = 30.0,
                 dial_timeout_s: float = 5.0,
                 stream_timeout_s: float = 0.0,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 5.0,
                 handoff=None,
                 handoff_timeout_s: float = 2.0,
                 reshard_sample_keys: int = 2048,
                 recorder=None):
        self.send_buffer_size = send_buffer_size
        self.n_streams = n_streams
        self.grpc_stats = grpc_stats
        self.send_timeout_s = send_timeout_s
        self.dial_timeout_s = dial_timeout_s
        self.stream_timeout_s = stream_timeout_s
        self.breaker_threshold = max(1, breaker_threshold)
        self.breaker_reset_s = breaker_reset_s
        # reshard drain-and-forward: `handoff(metrics)` re-routes a
        # retiring destination's undelivered buffer through the NEW ring
        # (the proxy wires handle_metrics in); None = legacy behavior,
        # swept items stay accounted as dropped
        self.handoff = handoff
        self.handoff_timeout_s = handoff_timeout_s
        self.reshard_sample_keys = reshard_sample_keys
        # flight recorder (trace/recorder.py): breaker transitions and
        # reshard windows become spans on the proxy's /debug/trace ring
        self.recorder = recorder
        self._lock = threading.Lock()
        self._ring = ConsistentHash()
        self._dests: dict[str, Destination] = {}
        self._breakers: dict[str, _Breaker] = {}
        # sent/dropped totals of destinations that have been removed —
        # without this, a dead destination's drop accounting would vanish
        # from stats() with it (silent loss in the chaos arithmetic)
        self._retired_sent = 0
        self._retired_dropped = 0
        self._ring_cache = None   # (hashes, didx, dests); see ring_arrays
        # elastic-reshard bookkeeping: one reshard window at a time
        # (reshard_begin acquires, reshard_commit releases), the last
        # committed record for /debug/vars, and cumulative totals
        self._reshard_serial = threading.Lock()
        self._reshard_epoch = 0
        self._reshard_moved_total = 0
        self._reshard_handoff_total = 0
        self._last_reshard: dict | None = None

    # -- breaker bookkeeping (all under self._lock) ------------------------

    def _record_failure(self, address: str) -> None:
        with self._lock:
            b = self._breakers.setdefault(address, _Breaker())
            b.failures += 1
            b.half_open = False
            if b.failures >= self.breaker_threshold or b.trips:
                # past the threshold (or re-failing a half-open probe):
                # open with exponential cooldown
                b.trips += 1
                backoff = min(2 ** (b.trips - 1), self.BREAKER_MAX_BACKOFF_X)
                b.open_until = time.monotonic() + self.breaker_reset_s * backoff
                logger.warning(
                    "destination %s circuit OPEN (%d consecutive "
                    "failures, trip #%d, retry in %.1fs); routing around "
                    "via the ring", address, b.failures, b.trips,
                    self.breaker_reset_s * backoff)
                from veneur_tpu.trace import recorder as trace_rec
                trace_rec.event_span(
                    self.recorder, "proxy.breaker.open",
                    {"address": address, "failures": b.failures,
                     "trip": b.trips,
                     "retry_in_s": round(
                         self.breaker_reset_s * backoff, 3)})

    def _record_success(self, address: str) -> None:
        """A dial succeeded.  Only a post-trip (half-open) probe closes
        the breaker — a mere successful dial must NOT reset the
        consecutive-failure count, or a half-broken peer that accepts
        dials but kills every RPC would flap connect/fail/reconnect
        forever without ever reaching the threshold."""
        with self._lock:
            b = self._breakers.get(address)
            if b is None:
                return
            if b.trips or b.half_open:
                logger.info("destination %s circuit CLOSED "
                            "(probe succeeded); restored to the ring",
                            address)
                from veneur_tpu.trace import recorder as trace_rec
                trace_rec.event_span(
                    self.recorder, "proxy.breaker.close",
                    {"address": address, "trips": b.trips})
                del self._breakers[address]

    def _admit(self, address: str) -> bool:
        """May we dial this address now?  False while its breaker is
        open; an expired breaker admits ONE dial (the half-open probe)."""
        with self._lock:
            b = self._breakers.get(address)
            if b is None:
                return True
            now = time.monotonic()
            if b.half_open:
                return False            # a probe is already in flight
            if b.open_until > now:
                return False
            if b.open_until:
                b.half_open = True      # this dial is the probe
            return True

    def breaker_stats(self) -> dict[str, dict]:
        now = time.monotonic()
        with self._lock:
            return {a: {"state": b.state(now), "failures": b.failures,
                        "trips": b.trips,
                        "retry_in_s": round(max(0.0, b.open_until - now), 3)}
                    for a, b in self._breakers.items()}

    # -- membership --------------------------------------------------------

    def add(self, addresses: list[str]) -> None:
        """Connect any new addresses in parallel; keep existing ones.
        Open-breaker addresses are skipped (route-around); an expired
        breaker turns its address's dial into the half-open probe."""
        with self._lock:
            new = [a for a in addresses if a not in self._dests]
        new = [a for a in new if self._admit(a)]
        if not new:
            return
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=max(4, len(new))) as pool:
            futures = {pool.submit(self._connect, a): a for a in new}
            for fut in concurrent.futures.as_completed(futures):
                addr = futures[fut]
                try:
                    dest = fut.result()
                except Exception as e:
                    logger.warning("could not connect to %s: %s", addr, e)
                    self._record_failure(addr)
                    continue
                self._record_success(addr)
                duplicate = None
                with self._lock:
                    if addr in self._dests:
                        # a concurrent add() won the race; close the
                        # duplicate connection (destinations.go:90-94)
                        duplicate = dest
                    else:
                        self._dests[addr] = dest
                        self._ring.add(addr)
                        self._ring_cache = None
                if duplicate is not None:
                    threading.Thread(target=duplicate.close,
                                     daemon=True).start()

    def _connect(self, address: str) -> Destination:
        from veneur_tpu import failpoints
        failpoints.inject("destinations.add")
        dest = Destination(address, self.send_buffer_size,
                           on_closed=self._connection_closed,
                           n_streams=self.n_streams,
                           send_timeout_s=self.send_timeout_s,
                           dial_timeout_s=self.dial_timeout_s,
                           stream_timeout_s=self.stream_timeout_s)
        if self.grpc_stats is not None:
            self.grpc_stats.watch_channel(dest.channel)
        return dest

    def _connection_closed(self, dest: Destination) -> None:
        # an ABRUPT close (broken stream / failed RPC) — graceful closes
        # never notify (connect.py _mark_closed).  A connection that
        # DELIVERED traffic before dying is real progress: reset the
        # consecutive-failure history first, so only genuinely
        # back-to-back failures (dials or zero-delivery lives) trip.
        if dest.sent > 0:
            with self._lock:
                b = self._breakers.get(dest.address)
                if b is not None and not b.trips:
                    del self._breakers[dest.address]
        self._record_failure(dest.address)
        self.remove(dest.address, expected=dest)

    def remove(self, address: str, expected=None, handoff=None) -> None:
        """Remove a destination; with `expected`, only if the registered
        object is that same instance (so a stale connection's close
        callback cannot tear down a re-added healthy destination).

        `handoff` (a reshard record) switches to the SYNCHRONOUS
        drain-and-forward retire: the destination's undelivered buffer
        re-routes through the new ring instead of counting as dropped,
        and the record accumulates the handoff accounting."""
        with self._lock:
            dest = self._dests.get(address)
            if dest is None or (expected is not None and dest is not expected):
                return
            del self._dests[address]
            self._ring.remove(address)
            self._ring_cache = None
            # fold the current counts into the retired totals UNDER THE
            # SAME LOCK that removes the destination, so totals() never
            # dips (monotonic for rate() scrapers); the drain may keep
            # counting for seconds, so _retire adds the post-snapshot
            # delta once close() completes
            base = (dest.sent, dest.dropped)
            self._retired_sent += base[0]
            self._retired_dropped += base[1]
        if handoff is not None:
            # synchronous: the reshard record must carry final counts at
            # commit, and set_members' caller (the discovery loop) is
            # the natural place to pay the bounded drain
            self._retire(dest, base, handoff)
        else:
            threading.Thread(target=self._retire, args=(dest, base),
                             daemon=True).start()

    def _retire(self, dest: Destination, base: tuple[int, int],
                handoff: dict | None = None) -> None:
        try:
            # a reshard drain is bounded by the handoff timeout; an
            # ordinary retire keeps the destination's own default
            dest.close(**({"drain_timeout_s": self.handoff_timeout_s}
                          if handoff is not None else {}))
        finally:
            rerouted = 0
            if handoff is not None and self.handoff is not None:
                metrics = dest.take_swept()
                if metrics:
                    handoff["handoff_inflight"] = len(metrics)
                    try:
                        self.handoff(metrics)
                        rerouted = len(metrics)
                    # vnlint: disable=silent-loss (already accounted:
                    #   swept metrics were counted into the retiring
                    #   destination's dropped total at close; rerouted
                    #   SUBTRACTS from it only on success, so a failed
                    #   handoff leaves them visibly dropped)
                    except Exception:
                        logger.exception(
                            "reshard handoff re-route failed; %d "
                            "metrics stay accounted as dropped",
                            len(metrics))
                    handoff["handoff_inflight"] = 0
                    handoff["handoff_metrics"] += rerouted
            with self._lock:
                self._retired_sent += dest.sent - base[0]
                # the close sweep counted the swept items dropped on the
                # destination; the ones the handoff re-routed MOVED, they
                # did not die (any that the NEW owner drops are counted
                # there) — keep the visible totals truthful
                self._retired_dropped += dest.dropped - base[1] - rerouted
                self._reshard_handoff_total += rerouted

    # -- elastic reshard ---------------------------------------------------

    def reshard_begin(self, want: list[str]) -> dict:
        """Open a reshard window (one at a time; pairs with
        reshard_commit — the vnlint resource-pairing contract, so an
        abandoned handoff is a lint error).  Returns the mutable record
        the phases fill in."""
        self._reshard_serial.acquire()
        with self._lock:
            before = sorted(self._ring.members())
            self._reshard_epoch += 1
            epoch = self._reshard_epoch
        return {
            "epoch": epoch,
            "started_unix": time.time(),
            "_t0": time.monotonic(),
            "_start_ns": time.time_ns(),
            "members_before": before,
            "wanted": sorted(want),
            "members_after": None,
            "added": [],
            "removed": [],
            "keys_moved": 0,
            "sample_keys": self.reshard_sample_keys,
            "moved_frac": 0.0,
            "handoff_metrics": 0,
            "handoff_inflight": 0,
            "duration_s": None,
            "committed": False,
        }

    def reshard_commit(self, rec: dict) -> None:
        """Close a reshard window: record the achieved membership, the
        sampled key movement (bounded-movement evidence), and the
        duration; publish as the /debug/vars reshard record."""
        try:
            from veneur_tpu.proxy import consistent
            with self._lock:
                after = sorted(self._ring.members())
            before = rec["members_before"]
            rec["members_after"] = after
            rec["added"] = sorted(set(after) - set(before))
            rec["removed"] = sorted(set(before) - set(after))
            moved, sampled = consistent.moved_keys(
                before, after, self.reshard_sample_keys)
            rec["keys_moved"] = moved
            rec["sample_keys"] = sampled
            rec["moved_frac"] = moved / sampled if sampled else 0.0
            rec["duration_s"] = round(
                time.monotonic() - rec.pop("_t0"), 6)
            rec["committed"] = True
            start_ns = rec.pop("_start_ns")
            if self.recorder is not None:
                # the whole two-phase window as one span on the proxy's
                # flight-recorder ring (begin -> grow -> drain -> commit)
                from veneur_tpu import trace as trace_mod
                span = trace_mod.Span(
                    "proxy.reshard", service="veneur_tpu",
                    client=self.recorder,
                    tags={"epoch": str(rec["epoch"]),
                          "added": ",".join(rec["added"]),
                          "removed": ",".join(rec["removed"]),
                          "keys_moved": str(rec["keys_moved"]),
                          "moved_frac": str(rec["moved_frac"]),
                          "handoff_metrics": str(
                              rec["handoff_metrics"])})
                span.start_ns = start_ns
                span.finish()
            with self._lock:
                self._reshard_moved_total += moved
                self._last_reshard = rec
        finally:
            self._reshard_serial.release()

    def reshard_stats(self) -> dict:
        """Cumulative reshard accounting + the last committed record
        (/debug/vars -> reshard)."""
        with self._lock:
            return {
                "epochs": self._reshard_epoch,
                "moved_total": self._reshard_moved_total,
                "handoff_total": self._reshard_handoff_total,
                "last": (dict(self._last_reshard)
                         if self._last_reshard is not None else None),
            }

    def set_members(self, addresses: list[str]) -> None:
        """Reconcile with a discovery result (proxy.go:345-387
        HandleDiscovery), grown into a TWO-PHASE RESHARD when the ring
        membership actually changes:

          phase 1 (grow)   joiners connect while the old ring still
                           serves — no window where keys have no owner;
          phase 2 (drain)  leavers retire one by one, each draining its
                           undelivered buffer through the handoff back
                           onto the NEW ring (drain-and-forward) so a
                           scale-down moves queued metrics instead of
                           dropping them.

        Consistent hashing bounds the movement to ~K/N keys for one node
        joining an N-ring; the committed record (reshard_stats) carries
        a sampled measurement of exactly that, plus the handoff counts
        and duration.  Breaker and sent/dropped-totals state of
        SURVIVING destinations is untouched.

        Breaker interplay: a LEAVING address sheds its breaker state
        only when the breaker is not engaged (a deliberate removal is
        not a failure) — an OPEN or HALF-OPEN breaker survives the
        membership flap, so a reshard that drops and re-adds a tripped
        destination can never resurrect it without a successful probe.
        Wanted-but-tripped addresses keep being offered to add() every
        poll; the breaker decides which offers become dials."""
        want = set(addresses)
        now = time.monotonic()
        with self._lock:
            have = set(self._dests)
            engaged = set()
            for addr in list(self._breakers):
                b = self._breakers[addr]
                if b.half_open or b.open_until > now:
                    # engaged breaker: state survives even if the
                    # address leaves the wanted set (the satellite fix:
                    # no probe-free resurrection through a reshard)
                    engaged.add(addr)
                    continue
                if addr not in want:
                    del self._breakers[addr]
        to_add = sorted(want - have)
        to_remove = sorted(have - want)
        if not to_remove and not (want - have - engaged):
            # no ring change on offer: every new wanted address is
            # breaker-gated (add() runs anyway — it is the half-open
            # probe driver once cooldowns expire).  No reshard record;
            # a probe restoring a member is breaker telemetry, not an
            # operator reshard.
            self.add(to_add)
            return
        from veneur_tpu import failpoints
        rec = self.reshard_begin(sorted(want))
        try:
            # vnlint: disable=blocking-propagation (the reshard
            #   failpoint edge deliberately sits inside the window —
            #   a chaos delay arm must stall the reshard itself;
            #   _reshard_serial only serializes operator reshards)
            failpoints.inject("destinations.reshard")
            # vnlint: disable=blocking-propagation (phase 1 of the
            #   two-phase reshard: joiner dials are SYNCHRONOUS under
            #   the window so the old ring serves until every joiner
            #   is connected; bounded by dial_timeout_s, and only the
            #   discovery loop ever waits here)
            self.add(to_add)
            for addr in to_remove:
                # vnlint: disable=blocking-propagation (phase 2:
                #   drain-and-forward retire is deliberately
                #   synchronous — the committed record must carry
                #   final handoff counts; bounded by
                #   handoff_timeout_s per leaver)
                self.remove(addr, handoff=rec)
        finally:
            self.reshard_commit(rec)

    def get(self, key: str) -> Destination:
        with self._lock:
            addr = self._ring.get(key)
            return self._dests[addr]

    def all_members(self) -> list:
        """Every live destination in a STABLE order (sorted by
        address): the mesh_fanout path sends each batch to all of them
        identically, so the iteration order must not depend on
        insertion/discovery timing."""
        with self._lock:
            return [self._dests[a] for a in sorted(self._dests)]

    def ring_arrays(self):
        """Snapshot of the ring as flat arrays for the native router
        (vn_route): (sorted uint32 ring hashes, parallel int32
        destination indices, list of Destination objects).  Returns
        None when the ring is empty.  Cached per membership (rebuilt by
        add/remove/clear) — this runs once per inbound payload on the
        routing hot path."""
        import numpy as np

        with self._lock:
            if self._ring_cache is not None:
                return self._ring_cache
            if not self._ring._ring:
                return None
            dests = list(self._dests.values())
            index = {d.address: i for i, d in enumerate(dests)}
            hashes = np.asarray([h for h, _ in self._ring._ring],
                                np.uint32)
            didx = np.asarray([index[m] for _, m in self._ring._ring],
                              np.int32)
            self._ring_cache = (hashes, didx, dests)
            return self._ring_cache

    def size(self) -> int:
        with self._lock:
            return len(self._dests)

    def clear(self) -> None:
        with self._lock:
            dests = list(self._dests.values())
            bases = []
            for d in dests:
                bases.append((d.sent, d.dropped))
                self._retired_sent += d.sent
                self._retired_dropped += d.dropped
            self._dests.clear()
            self._ring = ConsistentHash()
            self._breakers.clear()
            self._ring_cache = None
        for d, base in zip(dests, bases):
            self._retire(d, base)   # close + fold the drain delta in

    def stats(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {a: {"sent": d.sent, "dropped": d.dropped,
                        "queued": d._buffered}
                    for a, d in self._dests.items()}

    def totals(self) -> dict[str, int]:
        """Cumulative sent/dropped including REMOVED destinations, so a
        dead destination's losses stay visible (/debug/vars + the chaos
        matrix's no-silent-loss arithmetic)."""
        with self._lock:
            return {
                "sent": self._retired_sent
                + sum(d.sent for d in self._dests.values()),
                "dropped": self._retired_dropped
                + sum(d.dropped for d in self._dests.values()),
            }
