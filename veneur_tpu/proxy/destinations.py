"""Destination set + consistent-hash routing.

Mirrors `proxy/destinations/destinations.go`: Add connects new addresses in
parallel (`Add`, destinations.go:47-81), Get routes a key through the hash
ring (`:129-142`), closed connections self-remove (`ConnectionClosed`,
`:100-126`), Clear tears everything down, and Wait blocks until all
destinations have drained.
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading

from veneur_tpu.proxy.connect import Destination
from veneur_tpu.proxy.consistent import ConsistentHash

logger = logging.getLogger("veneur_tpu.proxy.destinations")


class Destinations:
    def __init__(self, send_buffer_size: int = 1024, grpc_stats=None,
                 n_streams: int = 8):
        self.send_buffer_size = send_buffer_size
        self.n_streams = n_streams
        self.grpc_stats = grpc_stats
        self._lock = threading.Lock()
        self._ring = ConsistentHash()
        self._dests: dict[str, Destination] = {}
        self._ring_cache = None   # (hashes, didx, dests); see ring_arrays

    def add(self, addresses: list[str]) -> None:
        """Connect any new addresses in parallel; keep existing ones."""
        with self._lock:
            new = [a for a in addresses if a not in self._dests]
        if not new:
            return
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=max(4, len(new))) as pool:
            futures = {pool.submit(self._connect, a): a for a in new}
            for fut in concurrent.futures.as_completed(futures):
                addr = futures[fut]
                try:
                    dest = fut.result()
                except Exception as e:
                    logger.warning("could not connect to %s: %s", addr, e)
                    continue
                duplicate = None
                with self._lock:
                    if addr in self._dests:
                        # a concurrent add() won the race; close the
                        # duplicate connection (destinations.go:90-94)
                        duplicate = dest
                    else:
                        self._dests[addr] = dest
                        self._ring.add(addr)
                        self._ring_cache = None
                if duplicate is not None:
                    threading.Thread(target=duplicate.close,
                                     daemon=True).start()

    def _connect(self, address: str) -> Destination:
        dest = Destination(address, self.send_buffer_size,
                           on_closed=self._connection_closed,
                           n_streams=self.n_streams)
        if self.grpc_stats is not None:
            self.grpc_stats.watch_channel(dest.channel)
        return dest

    def _connection_closed(self, dest: Destination) -> None:
        self.remove(dest.address, expected=dest)

    def remove(self, address: str, expected=None) -> None:
        """Remove a destination; with `expected`, only if the registered
        object is that same instance (so a stale connection's close
        callback cannot tear down a re-added healthy destination)."""
        with self._lock:
            dest = self._dests.get(address)
            if dest is None or (expected is not None and dest is not expected):
                return
            del self._dests[address]
            self._ring.remove(address)
            self._ring_cache = None
        if not dest.closed.is_set():
            threading.Thread(target=dest.close, daemon=True).start()

    def set_members(self, addresses: list[str]) -> None:
        """Reconcile with a discovery result: add new, drop vanished
        (proxy.go:345-387 HandleDiscovery)."""
        want = set(addresses)
        with self._lock:
            have = set(self._dests)
        for addr in have - want:
            self.remove(addr)
        self.add(sorted(want - have))

    def get(self, key: str) -> Destination:
        with self._lock:
            addr = self._ring.get(key)
            return self._dests[addr]

    def ring_arrays(self):
        """Snapshot of the ring as flat arrays for the native router
        (vn_route): (sorted uint32 ring hashes, parallel int32
        destination indices, list of Destination objects).  Returns
        None when the ring is empty.  Cached per membership (rebuilt by
        add/remove/clear) — this runs once per inbound payload on the
        routing hot path."""
        import numpy as np

        with self._lock:
            if self._ring_cache is not None:
                return self._ring_cache
            if not self._ring._ring:
                return None
            dests = list(self._dests.values())
            index = {d.address: i for i, d in enumerate(dests)}
            hashes = np.asarray([h for h, _ in self._ring._ring],
                                np.uint32)
            didx = np.asarray([index[m] for _, m in self._ring._ring],
                              np.int32)
            self._ring_cache = (hashes, didx, dests)
            return self._ring_cache

    def size(self) -> int:
        with self._lock:
            return len(self._dests)

    def clear(self) -> None:
        with self._lock:
            dests = list(self._dests.values())
            self._dests.clear()
            self._ring = ConsistentHash()
            self._ring_cache = None
        for d in dests:
            d.close()

    def stats(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {a: {"sent": d.sent, "dropped": d.dropped,
                        "queued": d._buffered}
                    for a, d in self._dests.items()}
