"""Sequential CPU merging t-digest — the baseline arm.

A faithful re-implementation of the reference's sequential algorithm
(`tdigest/merging_digest.go:115-262`): buffered Adds, sort temps, single
in-order greedy merge pass with the arcsine scale function, shuffled re-Add
on Merge (`merging_digest.go:374-389`).  Used (a) as the accuracy yardstick
for the parallel TPU kernels and (b) as the 32-core-CPU-style baseline arm
of bench.py.  Pure numpy/python — deliberately the "what a CPU global node
does" algorithm, not a TPU design.
"""

from __future__ import annotations

import math

import numpy as np


class SequentialDigest:
    def __init__(self, compression: float = 100.0):
        self.compression = float(compression)
        self.size_bound = int(math.pi * compression / 2 + 0.5)
        tc = min(925.0, max(20.0, compression))
        self.temp_cap = int(7.5 + 0.37 * tc - 2e-4 * tc * tc)
        self.means = np.zeros(self.size_bound + 1, np.float64)
        self.weights = np.zeros(self.size_bound + 1, np.float64)
        self.n = 0
        self.main_weight = 0.0
        self.temp_v: list[float] = []
        self.temp_w: list[float] = []
        self.temp_weight = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.rsum = 0.0

    def add(self, value: float, weight: float = 1.0) -> None:
        if not math.isfinite(value) or weight <= 0:
            raise ValueError("invalid value added")
        if len(self.temp_v) >= self.temp_cap:
            self._merge_temps()
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        # IEEE semantics like the Go reference: weight/0 -> +Inf, no crash.
        self.rsum += weight / value if value != 0 else math.inf
        self.temp_v.append(value)
        self.temp_w.append(weight)
        self.temp_weight += weight

    def add_batch(self, values, weights=None) -> None:
        values = np.asarray(values, np.float64).ravel()
        weights = (np.ones_like(values) if weights is None
                   else np.asarray(weights, np.float64).ravel())
        for v, w in zip(values, weights):
            self.add(float(v), float(w))

    def _k(self, q: float) -> float:
        return self.compression * (math.asin(2 * q - 1) / math.pi + 0.5)

    def _merge_temps(self) -> None:
        if not self.temp_v:
            return
        tv = np.asarray(self.temp_v, np.float64)
        tw = np.asarray(self.temp_w, np.float64)
        order = np.argsort(tv, kind="stable")
        tv, tw = tv[order], tw[order]
        # merge sorted temp stream with sorted main centroids
        am = np.concatenate([self.means[:self.n], tv])
        aw = np.concatenate([self.weights[:self.n], tw])
        order = np.argsort(am, kind="stable")
        am, aw = am[order], aw[order]

        total = self.main_weight + self.temp_weight
        out_m: list[float] = []
        out_w: list[float] = []
        merged = 0.0
        last_idx = 0.0
        for m, w in zip(am, aw):
            next_idx = self._k(min(1.0, (merged + w) / total))
            if next_idx - last_idx > 1 or not out_m:
                out_m.append(m)
                out_w.append(w)
                last_idx = self._k(merged / total)
            else:
                # Welford update: weight before mean
                out_w[-1] += w
                out_m[-1] += (m - out_m[-1]) * w / out_w[-1]
            merged += w
        self.n = len(out_m)
        self.means[:self.n] = out_m
        self.weights[:self.n] = out_w
        self.main_weight = total
        self.temp_v, self.temp_w = [], []
        self.temp_weight = 0.0

    def merge(self, other: "SequentialDigest",
              rng: np.random.Generator | None = None) -> None:
        other._merge_temps()
        rng = rng or np.random.default_rng()
        old_rsum = self.rsum
        for i in rng.permutation(other.n):
            self.add(float(other.means[i]), float(other.weights[i]))
        self.rsum = old_rsum + other.rsum

    def merge_centroids(self, means, weights, cmin, cmax, crsum,
                        rng: np.random.Generator | None = None) -> None:
        """Merge a serialized centroid list (the ImportMetric path,
        worker.go:402-459)."""
        rng = rng or np.random.default_rng()
        old_rsum = self.rsum
        n = len(means)
        for i in rng.permutation(n):
            self.add(float(means[i]), float(weights[i]))
        self.rsum = old_rsum + crsum
        self.min = min(self.min, cmin)
        self.max = max(self.max, cmax)

    def count(self) -> float:
        return self.main_weight + self.temp_weight

    def sum(self) -> float:
        self._merge_temps()
        return float(np.dot(self.means[:self.n], self.weights[:self.n]))

    def reciprocal_sum(self) -> float:
        return self.rsum

    def _bounds(self):
        m = self.means[:self.n]
        upper = np.empty(self.n)
        upper[:-1] = 0.5 * (m[1:] + m[:-1])
        upper[-1] = self.max
        lower = np.empty(self.n)
        lower[0] = self.min
        lower[1:] = upper[:-1]
        return lower, upper

    def quantile(self, q: float) -> float:
        self._merge_temps()
        if self.n == 0:
            return math.nan
        lower, upper = self._bounds()
        w = self.weights[:self.n]
        cum = np.cumsum(w)
        target = q * self.main_weight
        i = int(np.searchsorted(cum, target, side="left"))
        i = min(i, self.n - 1)
        before = cum[i] - w[i]
        prop = min(1.0, max(0.0, (target - before) / w[i]))
        return float(lower[i] + prop * (upper[i] - lower[i]))

    def cdf(self, x: float) -> float:
        self._merge_temps()
        if self.n == 0:
            return math.nan
        if x <= self.min:
            return 0.0
        if x >= self.max:
            return 1.0
        lower, upper = self._bounds()
        w = self.weights[:self.n]
        span = np.maximum(upper - lower, 0.0)
        frac = np.where(span > 0, np.clip((x - lower) / np.where(span > 0, span, 1), 0, 1),
                        (x >= upper).astype(np.float64))
        return float(np.sum(w * frac) / self.main_weight)

    def centroids(self):
        self._merge_temps()
        return self.means[:self.n].copy(), self.weights[:self.n].copy()
