"""Batched HyperLogLog as JAX tensor kernels + host-side hashing.

TPU-native re-design of the reference's Set sampler
(`samplers/samplers.go:236-311`), which wraps axiomhq/hyperloglog (precision
14, LogLog-Beta estimation, metro-hashed inputs).  Here the registers of all
S set-type keys live as one dense uint8 tensor `[S, 2^p]`:

  - host side: members are hashed (blake2b-64) and scattered into numpy
    staging registers with `np.maximum.at` — the equivalent of
    `Sketch.Insert`;
  - device side: union is an elementwise `maximum` (the merge kernel of the
    global-import path, `samplers/samplers.go:299-311`) and cardinality
    estimation is the LogLog-Beta estimator evaluated for all S keys at once
    (constants from the Ertl LogLog-Beta paper, the same estimator family the
    reference uses).

The reference keeps a sparse compressed list for small sets; we keep dense
registers on device (static shapes).  The wire codec IS axiomhq's
MarshalBinary format (vendor hyperloglog.go MarshalBinary/UnmarshalBinary):
we *accept* both dense and sparse forms, and *emit* whichever is smaller —
the sparse compressedList (synthesized pp-precision keys, O(members)
bytes, lossless ranks) for small sets, the dense nibble-packed form past
the ~2k-occupied-register crossover.  Set members are hashed with the
same metro hash (seed 1337), so Set sketches interoperate with a mixed
fleet of real veneur instances in both directions.
The previous fleet-internal "VH" encoding is still accepted on read so a
mixed-version fleet does not *error* during a rolling upgrade — but note
that sketches built with the old blake2b member hash do not union
meaningfully with metro-hashed ones (the same member lands on different
registers), so global set estimates are inflated (up to ~2x for fully
overlapping sets) until the whole fleet is on the metro hash.
"""

from __future__ import annotations

import functools
import struct

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_PRECISION = 14  # matches hyperloglog.New() in the reference

# LogLog-Beta bias-correction polynomial for p=14 (published constants from
# Ertl, "New cardinality estimation algorithms for HyperLogLog sketches" /
# the LogLog-Beta paper; identical family to the reference's estimator).
_BETA14 = (-0.370393911, 0.070471823, 0.17393686, 0.16339839,
           -0.09237745, 0.03738027, -0.005384159, 0.00042419)
# p=16 variant (the reference also ships one).
_BETA16 = (-0.37331876643753059, -1.41704077448122989, 0.40729184796612533,
           1.56152033906584164, -0.99242233534286128, 0.26064681399483092,
           -0.03053811369682807, 0.00155770210179105)

_BETAS = {14: _BETA14, 16: _BETA16}


def _alpha(m: float) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


# ---------------------------------------------------------------------------
# Host-side hashing + register updates (the ingest hot path)
# ---------------------------------------------------------------------------

_M64 = 0xFFFFFFFFFFFFFFFF
_K0, _K1, _K2, _K3 = 0xD6D018F5, 0xA2AA033B, 0x62992FC1, 0x30BC5B29
METRO_SEED = 1337  # the seed axiomhq/hyperloglog hashes members with


def _rotr(x: int, r: int) -> int:
    return ((x >> r) | (x << (64 - r))) & _M64


@functools.lru_cache(maxsize=65536)
def hash64(data: bytes, seed: int = METRO_SEED) -> int:
    """MetroHash64 of a set member with axiomhq's seed, so a member
    inserted here lands on the same register with the same rank as one
    inserted by a real veneur (register-level Set interop; vendor
    go-metro/metro64.go, hyperloglog/utils.go hashFunc).  Cached: set
    members repeat heavily across intervals."""
    h = ((seed + _K2) * _K0) & _M64
    i, n = 0, len(data)
    if n >= 32:
        v = [h, h, h, h]
        while n - i >= 32:
            v[0] = (v[0] + int.from_bytes(data[i:i + 8], "little") * _K0) & _M64
            v[0] = (_rotr(v[0], 29) + v[2]) & _M64
            v[1] = (v[1] + int.from_bytes(data[i + 8:i + 16], "little") * _K1) & _M64
            v[1] = (_rotr(v[1], 29) + v[3]) & _M64
            v[2] = (v[2] + int.from_bytes(data[i + 16:i + 24], "little") * _K2) & _M64
            v[2] = (_rotr(v[2], 29) + v[0]) & _M64
            v[3] = (v[3] + int.from_bytes(data[i + 24:i + 32], "little") * _K3) & _M64
            v[3] = (_rotr(v[3], 29) + v[1]) & _M64
            i += 32
        v[2] ^= (_rotr((((v[0] + v[3]) & _M64) * _K0 + v[1]) & _M64, 37) * _K1) & _M64
        v[3] ^= (_rotr((((v[1] + v[2]) & _M64) * _K1 + v[0]) & _M64, 37) * _K0) & _M64
        v[0] ^= (_rotr((((v[0] + v[2]) & _M64) * _K0 + v[3]) & _M64, 37) * _K1) & _M64
        v[1] ^= (_rotr((((v[1] + v[3]) & _M64) * _K1 + v[2]) & _M64, 37) * _K0) & _M64
        h = (h + (v[0] ^ v[1])) & _M64
    if n - i >= 16:
        v0 = (h + int.from_bytes(data[i:i + 8], "little") * _K2) & _M64
        v0 = (_rotr(v0, 29) * _K3) & _M64
        v1 = (h + int.from_bytes(data[i + 8:i + 16], "little") * _K2) & _M64
        v1 = (_rotr(v1, 29) * _K3) & _M64
        i += 16
        v0 ^= (_rotr((v0 * _K0) & _M64, 21) + v1) & _M64
        v1 ^= (_rotr((v1 * _K3) & _M64, 21) + v0) & _M64
        h = (h + v1) & _M64
    if n - i >= 8:
        h = (h + int.from_bytes(data[i:i + 8], "little") * _K3) & _M64
        i += 8
        h ^= (_rotr(h, 55) * _K1) & _M64
    if n - i >= 4:
        h = (h + int.from_bytes(data[i:i + 4], "little") * _K3) & _M64
        i += 4
        h ^= (_rotr(h, 26) * _K1) & _M64
    if n - i >= 2:
        h = (h + int.from_bytes(data[i:i + 2], "little") * _K3) & _M64
        i += 2
        h ^= (_rotr(h, 48) * _K1) & _M64
    if n - i >= 1:
        h = (h + data[i] * _K3) & _M64
        h ^= (_rotr(h, 37) * _K1) & _M64
    h ^= _rotr(h, 28)
    h = (h * _K0) & _M64
    h ^= _rotr(h, 29)
    return h


def pos_val(h: int, p: int = DEFAULT_PRECISION) -> tuple[int, int]:
    """(register index, rank) from a 64-bit hash; mirrors the reference's
    getPosVal (vendor hyperloglog/utils.go): index = top p bits, rank =
    leading zeros of the remainder (with sentinel) + 1."""
    idx = h >> (64 - p)
    w = ((h << p) | (1 << (p - 1))) & 0xFFFFFFFFFFFFFFFF
    rank = 65 - w.bit_length()
    return idx, rank


def hash_batch(members: list[bytes], p: int = DEFAULT_PRECISION
               ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized (indices, ranks) for a batch of members."""
    hs = np.fromiter(
        (hash64(m) for m in members), dtype=np.uint64, count=len(members))
    return split_hashes(hs, p)


def split_hashes(hs: np.ndarray, p: int = DEFAULT_PRECISION
                 ) -> tuple[np.ndarray, np.ndarray]:
    """(indices, ranks) from precomputed uint64 hashes (numpy, branch-free)."""
    hs = hs.astype(np.uint64, copy=False)
    idx = (hs >> np.uint64(64 - p)).astype(np.int32)
    w = (hs << np.uint64(p)) | np.uint64(1 << (p - 1))
    # clz via bit-smear + popcount
    for s in (1, 2, 4, 8, 16, 32):
        w = w | (w >> np.uint64(s))
    rank = (65 - np.bitwise_count(w)).astype(np.uint8)
    return idx, rank


def update_registers(regs: np.ndarray, rows: np.ndarray, idx: np.ndarray,
                     rank: np.ndarray) -> None:
    """Scatter-max a batch of (set row, register index, rank) into host
    staging registers `[S, m]` (the Insert path)."""
    np.maximum.at(regs, (rows, idx), rank)


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------

def union(a: jax.Array, b: jax.Array) -> jax.Array:
    """HLL merge is register-wise max (`samplers/samplers.go:299-311` →
    vendor Sketch.Merge)."""
    return jnp.maximum(a, b)


def estimate_from_moments(ez: jax.Array, ssum: jax.Array,
                          m: int) -> jax.Array:
    """The estimator tail shared by the XLA and Pallas paths: LogLog-Beta
    (est = alpha*m*(m-ez) / (beta(ez) + sum 2^-r), vendor
    hyperloglog.go:207-228) for precisions with published beta constants
    (14, 16); classic bias-corrected HyperLogLog with linear counting
    otherwise (non-default precisions and small test meshes)."""
    p = int(m).bit_length() - 1
    mf = float(m)
    beta_c = _BETAS.get(p)
    if beta_c is not None:
        zl = jnp.log(ez + 1.0)
        beta = beta_c[0] * ez
        acc = jnp.ones_like(zl)
        for c in beta_c[1:]:
            acc = acc * zl
            beta = beta + c * acc
        est = _alpha(mf) * mf * (mf - ez) / (beta + ssum) + 0.5
    else:
        raw = _alpha(mf) * mf * mf / ssum
        linear = mf * jnp.log(mf / jnp.maximum(ez, 1.0))
        est = jnp.where((raw <= 2.5 * mf) & (ez > 0), linear, raw) + 0.5
    return jnp.floor(est)


@jax.jit
def estimate(regs: jax.Array) -> jax.Array:
    """Batched cardinality estimate for every row of `[S, m]` uint8
    registers; returns [S] f32 (see estimate_from_moments)."""
    r = regs.astype(jnp.float32)
    ez = jnp.sum((regs == 0).astype(jnp.float32), axis=1)          # [S]
    ssum = jnp.sum(jnp.exp2(-r), axis=1)                           # [S]
    return estimate_from_moments(ez, ssum, regs.shape[1])


def estimate_np_rows(regs: np.ndarray) -> np.ndarray:
    """Batched numpy twin of `estimate` for `[S, m]` register rows —
    used by the mesh-less SetArena where a device round-trip per flush
    would cost more than the math (parity-tested against the XLA path)."""
    if regs.shape[0] == 0:
        return np.zeros(0, np.float32)
    r = regs.astype(np.float32)
    ez = (regs == 0).sum(axis=1).astype(np.float32)
    ssum = np.exp2(-r).sum(axis=1, dtype=np.float32)
    m = regs.shape[1]
    p = int(m).bit_length() - 1
    mf = np.float32(m)
    beta_c = _BETAS.get(p)
    if beta_c is not None:
        zl = np.log(ez + np.float32(1.0), dtype=np.float32)
        beta = np.float32(beta_c[0]) * ez
        acc = np.ones_like(zl)
        for c in beta_c[1:]:
            acc = acc * zl
            beta = beta + np.float32(c) * acc
        est = (np.float32(_alpha(mf)) * mf * (mf - ez) / (beta + ssum)
               + np.float32(0.5))
    else:
        raw = np.float32(_alpha(mf)) * mf * mf / ssum
        linear = mf * np.log(mf / np.maximum(ez, np.float32(1.0)),
                             dtype=np.float32)
        est = np.where((raw <= 2.5 * mf) & (ez > 0), linear, raw) \
            + np.float32(0.5)
    return np.floor(est)


def estimate_np(regs: np.ndarray) -> float:
    """Single-row numpy estimate (see estimate_np_rows) — used for the
    host-resident unique-timeseries sketch."""
    return float(estimate_np_rows(regs[None, :])[0])


# ---------------------------------------------------------------------------
# Wire codec: axiomhq/hyperloglog MarshalBinary format
# (vendor hyperloglog.go MarshalBinary/UnmarshalBinary; the Set sampler
# ships these bytes in metricpb SetValue.hyper_log_log,
# samplers/samplers.go:279-311)
# ---------------------------------------------------------------------------

_AXIOMHQ_VERSION = 1
_SPARSE_PP = 25          # sparse precision (vendor hyperloglog.go pp)
_TAILCUT_CAP = 16        # 4-bit register capacity

# legacy fleet-internal encoding, still accepted on read
_VH_MAGIC = b"VH"
_VH_DENSE = 1
_VH_SPARSE = 2


def marshal(regs: np.ndarray) -> bytes:
    """One register row -> axiomhq MarshalBinary bytes, choosing the form
    by size exactly where the break-even sits: the sparse form (~2-4
    bytes per occupied register, lossless ranks) for small sets, the
    dense nibble-packed form (fixed m/2 + 9 bytes, ranks tailcut to 15)
    otherwise.  A 10-member set forwards as ~50 bytes instead of 8 KiB.

    Dense layout: [version=1][p][b=0][sparse=0][sz u32 BE][sz nibble
    bytes], even register indices in the high nibble (vendor
    registers.go reg.set offset 0); ranks tailcut to 15 with base b=0,
    the clamp axiomhq itself applies on insert (hyperloglog.go insert:
    min(r-b, capacity-1)).  Sparse layout: empty tmpSet + the sorted
    delta-varint compressedList of synthesized pp-precision keys
    (vendor MarshalBinary sparse branch, hyperloglog.go:274-299)."""
    regs = np.asarray(regs, np.uint8)
    m = regs.shape[0]
    p = int(m).bit_length() - 1
    occ = np.nonzero(regs)[0]
    # sparse wins while worst-case key bytes (4/key as a raw delta
    # varint) undercut the fixed dense payload
    if len(occ) * 4 + 20 < m // 2 + 9:
        keys = np.sort(_encode_sparse_keys(
            occ.astype(np.uint32), regs[occ], p))
        blob = _encode_varint_list(keys)
        return (struct.pack(">BBBB", _AXIOMHQ_VERSION, p, 0, 1)
                + struct.pack(">I", 0)                    # empty tmpSet
                + struct.pack(">II", len(keys), int(keys[-1]) if
                              len(keys) else 0)
                + struct.pack(">I", len(blob)) + blob)
    clamped = np.minimum(regs, _TAILCUT_CAP - 1)
    packed = (clamped[0::2] << 4) | clamped[1::2]
    return (struct.pack(">BBBB", _AXIOMHQ_VERSION, p, 0, 0)
            + struct.pack(">I", m // 2) + packed.tobytes())


def _encode_sparse_keys(idx: np.ndarray, rank: np.ndarray,
                        p: int) -> np.ndarray:
    """Inverse of `_decode_sparse_keys`: synthesize pp-precision sparse
    keys that decodeHash (vendor sparse.go:24-40) maps back to exactly
    (idx, rank).  The pp-p sub-index bits below p are not recoverable
    from dense registers, so flagged keys zero them and unflagged keys
    carry a single marker bit that reproduces the rank — any real
    axiomhq reader lands the same (register, rank) pairs."""
    idx = idx.astype(np.uint32)
    rank = rank.astype(np.uint32)
    sub_w = np.uint32(_SPARSE_PP - p)
    flagged = rank > sub_w
    k_flag = ((idx << np.uint32(32 - p))
              | ((rank - np.minimum(rank, sub_w)) << np.uint32(1))
              | np.uint32(1))
    sub = np.uint32(1) << (sub_w - np.minimum(rank, sub_w))
    k_plain = ((idx << sub_w) | sub) << np.uint32(1)
    return np.where(flagged, k_flag, k_plain).astype(np.uint32)


def _encode_varint_list(keys: np.ndarray) -> bytes:
    """compressedList delta encoding (vendor compressed.go Append):
    ascending keys -> 7-bit little-endian varints of successive
    deltas."""
    out = bytearray()
    last = 0
    for k in keys.tolist():
        x = k - last
        last = k
        while x & 0xFFFFFF80:
            out.append((x & 0x7F) | 0x80)
            x >>= 7
        out.append(x & 0x7F)
    return bytes(out)


def _decode_sparse_keys(keys: np.ndarray, p: int
                        ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized decodeHash (vendor sparse.go:24-40): sparse keys carry
    either pp-precision index+rank (low bit set) or a raw 25-bit prefix."""
    keys = keys.astype(np.uint32, copy=False)
    flagged = (keys & np.uint32(1)) == 1
    # rank for flagged keys: 6 bits after the flag, plus (pp - p)
    r_flag = ((keys >> np.uint32(1)) & np.uint32(0x3F)).astype(np.int32) \
        + (_SPARSE_PP - p)
    # rank for unflagged: clz32(k << (32-pp+p-1)) + 1
    w = (keys << np.uint32(32 - _SPARSE_PP + p - 1)).astype(np.uint32)
    ww = w.copy()
    for s in (1, 2, 4, 8, 16):
        ww |= ww >> np.uint32(s)
    r_plain = (33 - np.bitwise_count(ww)).astype(np.int32)
    rank = np.where(flagged, r_flag, r_plain).astype(np.uint8)
    idx_flag = (keys >> np.uint32(32 - p)) & np.uint32((1 << p) - 1)
    idx_plain = (keys >> np.uint32(_SPARSE_PP - p + 1)) \
        & np.uint32((1 << p) - 1)
    idx = np.where(flagged, idx_flag, idx_plain).astype(np.int64)
    return idx, rank


def _decode_varint_list(buf: bytes, count: int) -> np.ndarray:
    """compressedList deltas: 7-bit little-endian varints, cumulative
    (vendor compressed.go variableLengthList/compressedList)."""
    out = np.empty(count, np.uint32)
    x = 0
    last = 0
    shift = 0
    k = 0
    for b in buf:
        x |= (b & 0x7F) << shift
        if b & 0x80:
            shift += 7
            continue
        last = (last + x) & 0xFFFFFFFF
        out[k] = last
        k += 1
        x = 0
        shift = 0
        if k == count:
            break
    if k != count:
        raise ValueError(
            f"truncated HLL sparse list: {k} of {count} keys")
    return out


def unmarshal_ex(data: bytes) -> tuple[np.ndarray, bool]:
    """Like `unmarshal`, additionally reporting whether the payload was
    the legacy fleet-internal 'VH' encoding (whose members were hashed
    with blake2b, not metro — see the migration lane in
    core/arena.py SetArena)."""
    legacy = data[:2] == _VH_MAGIC
    return unmarshal(data), legacy


def unmarshal(data: bytes) -> np.ndarray:
    """axiomhq UnmarshalBinary (both dense and sparse forms) -> full
    register row [2^p] uint8.  Dense values are rebased by b (a stored
    zero under base b counts as rank b, vendor registers.go sumAndZeros).
    Also accepts the legacy fleet-internal 'VH' encoding."""
    if data[:2] == _VH_MAGIC:
        return _unmarshal_vh(data)
    if len(data) < 8:
        raise ValueError("short HLL payload")
    version, p, b, sparse = struct.unpack_from(">BBBB", data, 0)
    if version != _AXIOMHQ_VERSION:
        raise ValueError(f"bad HLL version {version}")
    if not 4 <= p <= 18:
        raise ValueError(f"bad HLL precision {p}")
    if sparse not in (0, 1):
        raise ValueError(f"bad HLL sparse flag {sparse}")
    m = 1 << p
    regs = np.zeros(m, np.uint8)
    if sparse == 1:
        (tssz,) = struct.unpack_from(">I", data, 4)
        off = 8
        tmp_keys = np.frombuffer(data, ">u4", tssz, off).astype(np.uint32)
        off += 4 * tssz
        count, _last = struct.unpack_from(">II", data, off)
        off += 8
        (blen,) = struct.unpack_from(">I", data, off)
        off += 4
        list_keys = _decode_varint_list(data[off:off + blen], count)
        keys = np.concatenate([tmp_keys, list_keys]) \
            if tssz else list_keys
        if len(keys):
            idx, rank = _decode_sparse_keys(keys, p)
            np.maximum.at(regs, idx, rank)
        return regs
    (sz,) = struct.unpack_from(">I", data, 4)
    if sz * 2 != m:
        raise ValueError(f"dense size {sz} != m/2 for p={p}")
    packed = np.frombuffer(data, np.uint8, sz, 8)
    regs[0::2] = packed >> 4
    regs[1::2] = packed & 0x0F
    if b:
        # stored value v represents rank b+v; stored 0 represents rank b
        regs = (regs.astype(np.int32) + b).astype(np.uint8)
    return regs


# legacy "VH" payloads seen since process start: mixed-hash fleets
# silently inflate union estimates (module docstring), so readers get a
# metric (listen.legacy_hll_total, reported by the server flush) and a
# one-time runtime warning instead of a comment-only footgun
legacy_vh_total = 0
_vh_warned = False


def _note_legacy_vh() -> None:
    global legacy_vh_total, _vh_warned
    legacy_vh_total += 1
    if not _vh_warned:
        _vh_warned = True
        import logging
        logging.getLogger("veneur_tpu.hll").warning(
            "received a legacy VH-encoded HLL payload: sketches built "
            "with the old member hash do not union meaningfully with "
            "metro-hashed ones, so global set estimates are inflated "
            "(up to ~2x) until the whole fleet is upgraded; counted in "
            "listen.legacy_hll_total")


def _unmarshal_vh(data: bytes) -> np.ndarray:
    _note_legacy_vh()
    kind, p, _ = struct.unpack_from("<BBB", data, 2)
    m = 1 << p
    regs = np.zeros(m, np.uint8)
    if kind == _VH_DENSE:
        regs[:] = np.frombuffer(data, np.uint8, m, 5)
    elif kind == _VH_SPARSE:
        (n,) = struct.unpack_from("<I", data, 5)
        off = 9
        idx = np.frombuffer(data, np.uint32, n, off)
        vals = np.frombuffer(data, np.uint8, n, off + 4 * n)
        regs[idx.astype(np.int64)] = vals
    else:
        raise ValueError(f"bad HLL kind {kind}")
    return regs


# ---------------------------------------------------------------------------
# Scalar convenience wrapper (reference Sketch-shaped; tests + host samplers)
# ---------------------------------------------------------------------------

class HLLSketch:
    """Single-set convenience wrapper, mirroring the reference's
    `hyperloglog.Sketch` usage in the Set sampler."""

    def __init__(self, precision: int = DEFAULT_PRECISION):
        if not 4 <= precision <= 18:
            raise ValueError("precision must be in [4, 18]")
        self.p = precision
        self.m = 1 << precision
        self.regs = np.zeros(self.m, np.uint8)

    def insert(self, member: bytes | str) -> None:
        if isinstance(member, str):
            member = member.encode()
        idx, rank = pos_val(hash64(member), self.p)
        if rank > self.regs[idx]:
            self.regs[idx] = rank

    def insert_batch(self, members: list[bytes]) -> None:
        idx, rank = hash_batch(members, self.p)
        np.maximum.at(self.regs, idx, rank)

    def merge(self, other: "HLLSketch") -> None:
        if other.p != self.p:
            raise ValueError("precisions must be equal")
        np.maximum(self.regs, other.regs, out=self.regs)

    def estimate(self) -> int:
        return int(np.asarray(estimate(jnp.asarray(self.regs[None, :])))[0])

    def marshal(self) -> bytes:
        return marshal(self.regs)

    @classmethod
    def unmarshal(cls, data: bytes) -> "HLLSketch":
        regs = unmarshal(data)
        sk = cls(int(regs.shape[0]).bit_length() - 1)
        sk.regs = regs
        return sk
