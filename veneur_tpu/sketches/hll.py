"""Batched HyperLogLog as JAX tensor kernels + host-side hashing.

TPU-native re-design of the reference's Set sampler
(`samplers/samplers.go:236-311`), which wraps axiomhq/hyperloglog (precision
14, LogLog-Beta estimation, metro-hashed inputs).  Here the registers of all
S set-type keys live as one dense uint8 tensor `[S, 2^p]`:

  - host side: members are hashed (blake2b-64) and scattered into numpy
    staging registers with `np.maximum.at` — the equivalent of
    `Sketch.Insert`;
  - device side: union is an elementwise `maximum` (the merge kernel of the
    global-import path, `samplers/samplers.go:299-311`) and cardinality
    estimation is the LogLog-Beta estimator evaluated for all S keys at once
    (constants from the Ertl LogLog-Beta paper, the same estimator family the
    reference uses).

The reference keeps a sparse compressed list for small sets; we keep dense
registers on device (static shapes) and use a sparse wire encoding only for
serialization (codec below), which preserves the bandwidth win without
dynamic shapes.  Byte-level compatibility with axiomhq's MarshalBinary is
not implemented (documented gap; our own fleet uses the codec below).
"""

from __future__ import annotations

import struct
from typing import NamedTuple

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_PRECISION = 14  # matches hyperloglog.New() in the reference

# LogLog-Beta bias-correction polynomial for p=14 (published constants from
# Ertl, "New cardinality estimation algorithms for HyperLogLog sketches" /
# the LogLog-Beta paper; identical family to the reference's estimator).
_BETA14 = (-0.370393911, 0.070471823, 0.17393686, 0.16339839,
           -0.09237745, 0.03738027, -0.005384159, 0.00042419)
# p=16 variant (the reference also ships one).
_BETA16 = (-0.37331876643753059, -1.41704077448122989, 0.40729184796612533,
           1.56152033906584164, -0.99242233534286128, 0.26064681399483092,
           -0.03053811369682807, 0.00155770210179105)

_BETAS = {14: _BETA14, 16: _BETA16}


def _alpha(m: float) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


# ---------------------------------------------------------------------------
# Host-side hashing + register updates (the ingest hot path)
# ---------------------------------------------------------------------------

def hash64(data: bytes) -> int:
    """Stable 64-bit hash of a set member (blake2b-8; the reference uses
    metro hash — any well-mixed 64-bit hash gives the same estimator
    guarantees)."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


def pos_val(h: int, p: int = DEFAULT_PRECISION) -> tuple[int, int]:
    """(register index, rank) from a 64-bit hash; mirrors the reference's
    getPosVal (vendor hyperloglog/utils.go): index = top p bits, rank =
    leading zeros of the remainder (with sentinel) + 1."""
    idx = h >> (64 - p)
    w = ((h << p) | (1 << (p - 1))) & 0xFFFFFFFFFFFFFFFF
    rank = 65 - w.bit_length()
    return idx, rank


def hash_batch(members: list[bytes], p: int = DEFAULT_PRECISION
               ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized (indices, ranks) for a batch of members."""
    hs = np.fromiter(
        (hash64(m) for m in members), dtype=np.uint64, count=len(members))
    return split_hashes(hs, p)


def split_hashes(hs: np.ndarray, p: int = DEFAULT_PRECISION
                 ) -> tuple[np.ndarray, np.ndarray]:
    """(indices, ranks) from precomputed uint64 hashes (numpy, branch-free)."""
    hs = hs.astype(np.uint64, copy=False)
    idx = (hs >> np.uint64(64 - p)).astype(np.int32)
    w = (hs << np.uint64(p)) | np.uint64(1 << (p - 1))
    # clz via bit-smear + popcount
    for s in (1, 2, 4, 8, 16, 32):
        w = w | (w >> np.uint64(s))
    rank = (65 - np.bitwise_count(w)).astype(np.uint8)
    return idx, rank


def update_registers(regs: np.ndarray, rows: np.ndarray, idx: np.ndarray,
                     rank: np.ndarray) -> None:
    """Scatter-max a batch of (set row, register index, rank) into host
    staging registers `[S, m]` (the Insert path)."""
    np.maximum.at(regs, (rows, idx), rank)


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------

def union(a: jax.Array, b: jax.Array) -> jax.Array:
    """HLL merge is register-wise max (`samplers/samplers.go:299-311` →
    vendor Sketch.Merge)."""
    return jnp.maximum(a, b)


@jax.jit
def estimate(regs: jax.Array) -> jax.Array:
    """Batched cardinality estimate for every row of `[S, m]` uint8
    registers; returns [S] f32.

    Uses LogLog-Beta (est = alpha*m*(m-ez) / (beta(ez) + sum 2^-r), vendor
    hyperloglog.go:207-228) for precisions with published beta constants
    (14, 16); classic bias-corrected HyperLogLog with linear counting
    otherwise (non-default precisions and small test meshes).
    """
    s, m = regs.shape
    p = int(m).bit_length() - 1
    r = regs.astype(jnp.float32)
    ez = jnp.sum((regs == 0).astype(jnp.float32), axis=1)          # [S]
    ssum = jnp.sum(jnp.exp2(-r), axis=1)                           # [S]
    mf = float(m)
    beta_c = _BETAS.get(p)
    if beta_c is not None:
        zl = jnp.log(ez + 1.0)
        beta = beta_c[0] * ez
        acc = jnp.ones_like(zl)
        for c in beta_c[1:]:
            acc = acc * zl
            beta = beta + c * acc
        est = _alpha(mf) * mf * (mf - ez) / (beta + ssum) + 0.5
    else:
        raw = _alpha(mf) * mf * mf / ssum
        linear = mf * jnp.log(mf / jnp.maximum(ez, 1.0))
        est = jnp.where((raw <= 2.5 * mf) & (ez > 0), linear, raw) + 0.5
    return jnp.floor(est)


# ---------------------------------------------------------------------------
# Wire codec (our fleet's format; axiomhq byte-compat is a documented gap)
# ---------------------------------------------------------------------------

_MAGIC = b"VH"
_DENSE = 1
_SPARSE = 2


def marshal(regs: np.ndarray) -> bytes:
    """Serialize one register row.  Sparse when <1/8 occupied."""
    regs = np.asarray(regs, np.uint8)
    m = regs.shape[0]
    p = int(m).bit_length() - 1
    nz = np.nonzero(regs)[0]
    if len(nz) * 5 < m:
        payload = struct.pack("<BBBI", _SPARSE, p, 0, len(nz))
        return (_MAGIC + payload + nz.astype(np.uint32).tobytes()
                + regs[nz].tobytes())
    return _MAGIC + struct.pack("<BBB", _DENSE, p, 0) + regs.tobytes()


def unmarshal(data: bytes) -> np.ndarray:
    if data[:2] != _MAGIC:
        raise ValueError("bad HLL magic")
    kind, p, _ = struct.unpack_from("<BBB", data, 2)
    m = 1 << p
    regs = np.zeros(m, np.uint8)
    if kind == _DENSE:
        regs[:] = np.frombuffer(data, np.uint8, m, 5)
    elif kind == _SPARSE:
        (n,) = struct.unpack_from("<I", data, 5)
        off = 9
        idx = np.frombuffer(data, np.uint32, n, off)
        vals = np.frombuffer(data, np.uint8, n, off + 4 * n)
        regs[idx.astype(np.int64)] = vals
    else:
        raise ValueError(f"bad HLL kind {kind}")
    return regs


# ---------------------------------------------------------------------------
# Scalar convenience wrapper (reference Sketch-shaped; tests + host samplers)
# ---------------------------------------------------------------------------

class HLLSketch:
    """Single-set convenience wrapper, mirroring the reference's
    `hyperloglog.Sketch` usage in the Set sampler."""

    def __init__(self, precision: int = DEFAULT_PRECISION):
        if not 4 <= precision <= 18:
            raise ValueError("precision must be in [4, 18]")
        self.p = precision
        self.m = 1 << precision
        self.regs = np.zeros(self.m, np.uint8)

    def insert(self, member: bytes | str) -> None:
        if isinstance(member, str):
            member = member.encode()
        idx, rank = pos_val(hash64(member), self.p)
        if rank > self.regs[idx]:
            self.regs[idx] = rank

    def insert_batch(self, members: list[bytes]) -> None:
        idx, rank = hash_batch(members, self.p)
        np.maximum.at(self.regs, idx, rank)

    def merge(self, other: "HLLSketch") -> None:
        if other.p != self.p:
            raise ValueError("precisions must be equal")
        np.maximum(self.regs, other.regs, out=self.regs)

    def estimate(self) -> int:
        return int(np.asarray(estimate(jnp.asarray(self.regs[None, :])))[0])

    def marshal(self) -> bytes:
        return marshal(self.regs)

    @classmethod
    def unmarshal(cls, data: bytes) -> "HLLSketch":
        regs = unmarshal(data)
        sk = cls(int(regs.shape[0]).bit_length() - 1)
        sk.regs = regs
        return sk
