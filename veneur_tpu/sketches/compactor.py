"""Relative-error compactor sketch: guaranteed-rank-error quantiles.

The third sketch family (ROADMAP #4; "SplitQuantiles" / relative-error
adaptive compactors, arXiv:2511.17396, in the KLL/ReqSketch lineage of
arXiv:1603.05346 + 2004.01668).  Where the t-digest's tail accuracy is
only ever empirical and the moments family trades accuracy for the
cheapest possible merge, a compactor sketch carries a PROVABLE rank
error: every answer it returns is the value of some element whose rank
is within ``rank_error_bound(n)`` of the requested one — the tier
operators pick by rule for SLA-grade p99s (README "Sketch families").

State is a fixed ladder of ``levels`` buffers of ``cap`` slots each.
An item in level ``l`` stands for ``2**l`` original samples.  New
samples enter level 0; when a level's occupancy exceeds ``cap`` it is
*compacted*: sorted, the upper ``cap // 2`` items held back (the
protected section — this is what concentrates accuracy in the upper
tail), and of the rest every other survivor — offset chosen by a
seeded deterministic coin — is promoted to the next level at double
weight.  A merge is level-wise concatenate (each side carries at most
``cap`` per level, so staging is bounded by ``2 * cap``) followed by
one bottom-up compaction pass; because compaction is sort +
stride-select it is exactly the bitonic machinery ops/sorted_eval.py
already has, and thousands of keys' passes batch into ONE Pallas
launch (ops/compactor_eval.py).

Determinism: the coin for every compaction is ``_coin(seed, level,
comps)`` where ``comps`` is the sketch's cumulative compaction
counter.  Merging two sketches starts from the SUM of their counters
and the level contents are sorted before selection, so ``a.merge(b)``
and ``b.merge(a)`` are bit-identical and a replayed testbed run
reproduces exactly.  The count-dynamics of a pass (``plan_pass``) are
pure integer math shared by the host reference, the XLA twin and the
Pallas kernel: the host plans each pass (which levels compact, each
one's coin offset) and the device replays only the value movement.

Exactness: ``count``/``sum``/``min``/``max`` live in the header and
are exact regardless of compaction — the count-conservation oracle
checks the header, and item mass equals the header count whenever
``clip == 0``.  ``clip`` counts emergency in-place compactions of the
TOP level (total mass beyond ``cap * 2**(levels-1)``): past that the
rank guarantee lapses and the read-off renormalizes item weights to
the exact header count instead of failing.

Wire vector layout (``vector_len(cap, levels)`` doubles)::

    [0] count  [1] sum  [2] rsum  [3] min  [4] max   exact scalars
    [5] cap    [6] levels  [7] seed          self-describing params
    [8] comps  [9] clip                      schedule counters
    [10 .. 10+levels)                        per-level occupancy
    [10+levels .. 10+levels+levels*cap)      level items, level l at
                                             offset l*cap, occupied
                                             prefix, zero padding
"""

from __future__ import annotations

import math

import numpy as np

# cap drives the guarantee (eps ~ 2*log2(n/cap)/cap for a
# deterministic-coin compactor) and levels the mass capacity
# (cap * 2**(levels-1)); the defaults bound rank error by ~19% of n at
# n = 100k worst-case — measured error sits two orders under that
# (analysis/tdigest_accuracy.csv) — while one key's state stays a
# 1.8k-double vector and the kernel buffer (4*cap) a legal bitonic
# depth (<= 1024, ops/sorted_eval.MAX_DEPTH)
DEFAULT_CAP = 128
DEFAULT_LEVELS = 14
DEFAULT_SEED = 2511

# staging width per level: each merge side carries <= cap, and the
# in-pass promotion carry is bounded by 2*cap (see plan_pass), so the
# working buffer per level is 4*cap — pow2 whenever cap is, which is
# what the bitonic schedule in the kernel requires
STAGE_MUL = 2
BUF_MUL = 4
# emergency in-place rounds that bring a top level of 4*cap back under
# cap (ceil(occ/2) per round: 4c -> 2c -> c)
CLIP_ROUNDS = 2

IDX_COUNT = 0
IDX_SUM = 1
IDX_RSUM = 2
IDX_MIN = 3
IDX_MAX = 4
IDX_CAP = 5
IDX_LEVELS = 6
IDX_SEED = 7
IDX_COMPS = 8
IDX_CLIP = 9
HDR = 10

_PAD = np.inf
# non-finite samples would alias the +inf slot padding; clamp instead
# of dropping so the exact header scalars still see every sample
_FCLAMP = float(np.finfo(np.float32).max)


def vector_len(cap: int = DEFAULT_CAP, levels: int = DEFAULT_LEVELS) -> int:
    return HDR + levels + levels * cap


def keep_of(cap: int) -> int:
    """Protected upper-section size: the top half of a compacting
    buffer is never selected from, concentrating accuracy at high
    ranks (the relative-error construction of the source family)."""
    return cap // 2


def empty_vector(cap: int = DEFAULT_CAP,
                 levels: int = DEFAULT_LEVELS,
                 seed: int = DEFAULT_SEED) -> np.ndarray:
    v = np.zeros(vector_len(cap, levels), np.float64)
    v[IDX_MIN] = np.inf
    v[IDX_MAX] = -np.inf
    v[IDX_CAP] = cap
    v[IDX_LEVELS] = levels
    v[IDX_SEED] = seed
    return v


def params_from_vector(vec: np.ndarray):
    """(cap, levels, seed) from a wire vector, validated against its
    length — the self-describing check every import runs."""
    vec = np.asarray(vec, np.float64)
    if vec.ndim != 1 or vec.shape[0] < HDR + 1:
        raise ValueError(f"not a compactor vector: shape {vec.shape}")
    cap, levels, seed = (int(vec[IDX_CAP]), int(vec[IDX_LEVELS]),
                         int(vec[IDX_SEED]))
    if cap < 8 or cap & (cap - 1) or levels < 2:
        raise ValueError(f"bad compactor params cap={cap} levels={levels}")
    if vec.shape[0] != vector_len(cap, levels):
        raise ValueError(
            f"compactor vector length {vec.shape[0]} != "
            f"{vector_len(cap, levels)} for cap={cap} levels={levels}")
    return cap, levels, seed


_U64 = np.uint64
_PHI = _U64(0x9E3779B97F4A7C15)
_MIX1 = _U64(0xBF58476D1CE4E5B9)
_MIX2 = _U64(0x94D049BB133111EB)


def coin_bits(seed: int, level, comps) -> np.ndarray:
    """Deterministic coin for the stride-select offset of a compaction
    at ``level`` when the sketch has performed ``comps`` compactions:
    splitmix64 finalizer over (seed, level, comps).  Vectorized over
    ``comps``/``level``; returns int64 bits in {0, 1}."""
    with np.errstate(over="ignore"):
        x = (_U64(seed & 0xFFFFFFFFFFFFFFFF)
             + (np.asarray(level).astype(np.uint64) + _U64(1)) * _PHI
             + np.asarray(comps).astype(np.uint64) * _MIX2)
        x = (x ^ (x >> _U64(30))) * _MIX1
        x = (x ^ (x >> _U64(27))) * _MIX2
        x = x ^ (x >> _U64(31))
    return ((x >> _U64(17)) & _U64(1)).astype(np.int64)


def plan_pass(stage_n: np.ndarray, comps: np.ndarray, clip: np.ndarray,
              seed: int, cap: int):
    """Count-dynamics of one bottom-up compaction pass over staged
    levels ``stage_n [n, levels]`` (each <= 2*cap).  Pure integer math
    — the single source of truth the host reference, the XLA twin and
    the Pallas kernel all follow.

    Returns ``(off, cnt_out, comps_out, clip_out)`` where ``off
    [n, levels + CLIP_ROUNDS]`` carries the coin offset of every
    compaction event in pass order (levels bottom-up, then the top
    level's emergency clip rounds) and ``cnt_out [n, levels]`` the
    post-pass occupancies (every level <= cap).

    Per level: with carry from below, occupancy ``occ <= 4*cap``; the
    level compacts iff ``occ > cap``; the compacted section is the
    lowest ``occ - keep`` items minus an odd straggler, promoting half
    of it.  The top level cannot promote: CLIP_ROUNDS in-place rounds
    (keep = 0) halve it back under cap, counted in ``clip``."""
    stage_n = np.asarray(stage_n, np.int64)
    n, levels = stage_n.shape
    comps = np.asarray(comps, np.int64).copy()
    clip = np.asarray(clip, np.int64).copy()
    keep = keep_of(cap)
    off = np.zeros((n, levels + CLIP_ROUNDS), np.int64)
    cnt_out = np.zeros_like(stage_n)
    carry = np.zeros(n, np.int64)
    for lvl in range(levels):
        occ = stage_n[:, lvl] + carry
        if lvl < levels - 1:
            do = occ > cap
            sec = occ - keep
            m = np.where(do, sec - (sec & 1), 0)
            off[:, lvl] = np.where(do, coin_bits(seed, lvl, comps), 0)
            comps += do
            cnt_out[:, lvl] = occ - m
            carry = m // 2
        else:
            top = occ
            for r in range(CLIP_ROUNDS):
                do = top > cap
                m = np.where(do, top - (top & 1), 0)
                off[:, levels + r] = np.where(
                    do, coin_bits(seed, levels + r, comps), 0)
                comps += do
                clip += do
                top = top - m // 2
            cnt_out[:, lvl] = top
    return off, cnt_out, comps, clip


def apply_pass(stage_v: np.ndarray, stage_n: np.ndarray, off: np.ndarray,
               cap: int) -> np.ndarray:
    """Value movement of one compaction pass: the host/numpy reference
    the Pallas kernel replays bit-for-bit (ops/compactor_eval.py).

    ``stage_v [n, levels, 2*cap]`` holds each level's staged items in
    an occupied prefix (+inf padding beyond ``stage_n``); returns the
    post-pass state ``[n, levels, cap]``.  Each level buffer is sorted
    ascending (padding sorts to the end), the survivor/retain masks
    are pure functions of occupancy + coin offset, and the scattered
    survivors compress to a sorted prefix by a masked re-sort — the
    same construction the kernel uses, so ties and all."""
    stage_v = np.asarray(stage_v, np.float64)
    stage_n = np.asarray(stage_n, np.int64)
    n, levels, s2 = stage_v.shape
    if s2 != STAGE_MUL * cap:
        raise ValueError(f"stage width {s2} != {STAGE_MUL * cap}")
    keep = keep_of(cap)
    b = BUF_MUL * cap
    iota = np.arange(b)[None, :]
    out = np.full((n, levels, cap), _PAD)
    carry_v = np.full((n, STAGE_MUL * cap), _PAD)
    carry_n = np.zeros(n, np.int64)
    for lvl in range(levels):
        buf = np.sort(
            np.concatenate([stage_v[:, lvl], carry_v], axis=1), axis=1)
        occ = (stage_n[:, lvl] + carry_n)[:, None]
        if lvl < levels - 1:
            do = occ > cap
            sec = occ - keep
            m = np.where(do, sec - (sec & 1), 0)
            o = off[:, lvl][:, None]
            surv = do & (iota < m) & (iota % 2 == o)
            retain = np.where(do, (iota >= m) & (iota < occ), iota < occ)
            carry_v = np.sort(np.where(surv, buf, _PAD),
                              axis=1)[:, :STAGE_MUL * cap]
            carry_n = (m // 2)[:, 0]
            out[:, lvl] = np.sort(np.where(retain, buf, _PAD),
                                  axis=1)[:, :cap]
        else:
            top = occ
            for r in range(CLIP_ROUNDS):
                do = top > cap
                m = np.where(do, top - (top & 1), 0)
                o = off[:, levels + r][:, None]
                surv = (iota < m) & (iota % 2 == o)
                keep_mask = np.where(do, surv | ((iota >= m) & (iota < top)),
                                     iota < top)
                buf = np.sort(np.where(keep_mask, buf, _PAD), axis=1)
                top = top - m // 2
            out[:, lvl] = buf[:, :cap]
    return out


def _levels_touched(n: float, cap: int, levels: int) -> int:
    if n <= cap:
        return 0
    return min(levels - 1, int(math.ceil(math.log2(n / cap))) + 1)


def rank_error_bound(n: float, cap: int = DEFAULT_CAP,
                     levels: int = DEFAULT_LEVELS) -> float:
    """Provable worst-case ABSOLUTE rank error after absorbing total
    mass ``n`` (any merge topology), the committed envelope the
    dossier and testbed assert against.

    Derivation: a compaction at level ``l`` replaces pairs of weight
    ``2**l`` by one survivor at ``2**(l+1)``, shifting any rank by at
    most ``2**l``.  A level holds back ``keep = cap/2`` items, so
    consecutive compactions at ``l`` are separated by at least
    ``cap/2`` arrivals there, and at most ``n / 2**l`` items ever
    arrive: ``m_l <= 2n / (cap * 2**l) + 1`` compactions.  Summing
    ``m_l * 2**l`` over the ``H`` levels that can compact (``H =
    ceil(log2(n / cap)) + 1``, +1 for merge-staging slack) gives
    ``2*H*n/cap`` plus a geometric tail under ``2n/cap``:

        err(n) <= (2*H + 2) * n / cap

    Valid while the top level never clips, i.e. ``n <= cap *
    2**(levels-1)`` — beyond that the function returns +inf and the
    read-off degrades to renormalized best-effort (module docstring)."""
    if n <= cap:
        return 0.0
    if n > cap * 2.0 ** (levels - 1):
        return float("inf")
    h = _levels_touched(n, cap, levels)
    return (2.0 * h + 2.0) * n / cap


def state_from_vector(vec: np.ndarray):
    """Decode a wire vector to ``(vals [levels, cap] (+inf padded),
    cnt [levels], comps, clip)`` plus params via the header."""
    cap, levels, seed = params_from_vector(vec)
    cnt = np.asarray(vec[HDR:HDR + levels], np.int64).copy()
    vals = np.asarray(
        vec[HDR + levels:], np.float64).reshape(levels, cap).copy()
    vals[np.arange(cap)[None, :] >= cnt[:, None]] = _PAD
    return vals, cnt, int(vec[IDX_COMPS]), int(vec[IDX_CLIP])


def _encode(vec: np.ndarray, vals: np.ndarray, cnt: np.ndarray,
            comps: int, clip: int) -> np.ndarray:
    levels, cap = vals.shape
    vec[IDX_COMPS] = comps
    vec[IDX_CLIP] = clip
    vec[HDR:HDR + levels] = cnt
    body = np.where(np.arange(cap)[None, :] < cnt[:, None], vals, 0.0)
    vec[HDR + levels:] = body.reshape(-1)
    return vec


def items_and_weights(vec: np.ndarray):
    """(values, weights) of every live item in a wire vector, weights
    renormalized so their total equals the exact header count (a
    no-op at clip == 0; past clip the implied mass undercounts and
    the uniform rescale keeps the read-off mass-exact)."""
    cap, levels, _ = params_from_vector(vec)
    vec = np.asarray(vec, np.float64)
    cnt = vec[HDR:HDR + levels].astype(np.int64)
    body = vec[HDR + levels:].reshape(levels, cap)
    live = np.arange(cap)[None, :] < cnt[:, None]
    vals = body[live]
    wts = np.repeat(2.0 ** np.arange(levels), cnt)
    total = float(wts.sum())
    count = float(vec[IDX_COUNT])
    if total > 0 and count > 0 and total != count:
        wts = wts * (count / total)
    return vals, wts


def quantiles_from_vectors(vecs: np.ndarray, qs) -> np.ndarray:
    """Rank/quantile read-off for batched wire vectors ``[n, M]``:
    weighted midpoint interpolation over the live items, pinned to the
    convention of query.engine.weighted_quantiles_np so fused /query
    answers and flush emissions agree.  Empty rows yield 0.0."""
    vecs = np.asarray(vecs, np.float64)
    qs = np.asarray(qs, np.float64)
    out = np.zeros((vecs.shape[0], len(qs)))
    for i in range(vecs.shape[0]):
        v, w = items_and_weights(vecs[i])
        if len(v) == 0:
            continue
        order = np.argsort(v, kind="stable")
        v, w = v[order], w[order]
        if len(v) == 1:
            row = np.full(len(qs), v[0])
        else:
            cum = np.cumsum(w)
            cmid = cum - 0.5 * w
            tq = qs * cum[-1]
            idx = np.clip(np.searchsorted(cmid, tq, side="left"),
                          1, len(v) - 1)
            lo, hi = v[idx - 1], v[idx]
            c_lo, c_hi = cmid[idx - 1], cmid[idx]
            t = np.where(c_hi > c_lo,
                         (tq - c_lo) / np.maximum(c_hi - c_lo, 1e-30),
                         0.0)
            row = lo + (hi - lo) * np.clip(t, 0.0, 1.0)
        out[i] = np.clip(row, vecs[i, IDX_MIN], vecs[i, IDX_MAX])
    return out


def split_levels(vals: np.ndarray, wts: np.ndarray, levels: int) -> list:
    """Bucket weighted samples into per-level pending queues: an item
    of weight ``2**l`` enters level ``l`` (imported compactor items
    re-enter at their originating level), and an arbitrary sample-rate
    weight decomposes by binary expansion of ``max(1, round(w))`` so
    no sample's VALUE is ever dropped — the exact header count carries
    the true mass and the read-off renormalizes the remainder.  Bits
    at or above the ladder clamp to the top level."""
    pending = [[] for _ in range(levels)]
    w_int = np.maximum(1, np.rint(wts)).astype(np.int64)
    top_extra = w_int >> (levels - 1)
    for l in range(levels):
        sel = ((w_int >> l) & 1).astype(bool) if l < levels - 1 \
            else (top_extra > 0)
        if sel.any():
            pending[l].append(vals[sel])
    return [np.concatenate(p) if p else np.empty(0) for p in pending]


def rank_of(vec: np.ndarray, x: float) -> float:
    """Estimated rank mass of ``x`` (weight of items <= x) — the other
    half of the read-off, used by the rank-error oracles."""
    v, w = items_and_weights(vec)
    if len(v) == 0:
        return 0.0
    return float(w[v <= x].sum())


class CompactorSketch:
    """Single-key convenience wrapper over one compactor state (the
    analysis harness / test twin; production keys live batched in
    core.arena.CompactorArena)."""

    def __init__(self, cap: int = DEFAULT_CAP, levels: int = DEFAULT_LEVELS,
                 seed: int = DEFAULT_SEED):
        self.cap, self.levels, self.seed = cap, levels, seed
        self.vals = np.full((levels, cap), _PAD)
        self.cnt = np.zeros(levels, np.int64)
        self.comps = 0
        self.clip = 0
        self.count = 0.0
        self.sum = 0.0
        self.rsum = 0.0
        self.min = np.inf
        self.max = -np.inf

    def _run_pass(self, stage_v, stage_n):
        off, cnt_out, comps, clip = plan_pass(
            stage_n, np.array([self.comps]), np.array([self.clip]),
            self.seed, self.cap)
        out = apply_pass(stage_v, stage_n, off, self.cap)
        self.vals, self.cnt = out[0], cnt_out[0]
        self.comps, self.clip = int(comps[0]), int(clip[0])

    def add_batch(self, values, weights=None) -> None:
        vals = np.asarray(values, np.float64).ravel()
        if len(vals) == 0:
            return
        wts = (np.ones_like(vals) if weights is None
               else np.asarray(weights, np.float64).ravel())
        self.count += float(wts.sum())
        self.sum += float(vals @ wts)
        with np.errstate(divide="ignore"):
            self.rsum += float((wts / vals).sum())
        self.min = min(self.min, float(vals.min()))
        self.max = max(self.max, float(vals.max()))
        vals = np.clip(vals, -_FCLAMP, _FCLAMP)
        s2 = STAGE_MUL * self.cap
        pending = split_levels(vals, wts, self.levels)
        pos = np.zeros(self.levels, np.int64)
        while True:
            stage_v = np.full((1, self.levels, s2), _PAD)
            stage_n = np.zeros((1, self.levels), np.int64)
            fed = False
            for l in range(self.levels):
                occ = self.cnt[l]
                stage_v[0, l, :occ] = self.vals[l, :occ]
                room = s2 - occ
                take = min(room, len(pending[l]) - pos[l])
                if take > 0:
                    stage_v[0, l, occ:occ + take] = \
                        pending[l][pos[l]:pos[l] + take]
                    pos[l] += take
                    fed = True
                stage_n[0, l] = occ + take
            if not fed:
                break
            self._run_pass(stage_v, stage_n)
            if all(pos[l] >= len(pending[l]) for l in range(self.levels)):
                break

    def merge(self, other: "CompactorSketch | np.ndarray") -> None:
        vec = (other.to_vector() if isinstance(other, CompactorSketch)
               else np.asarray(other, np.float64))
        merged = merge_vectors(self.to_vector()[None, :], vec[None, :])[0]
        new = CompactorSketch.from_vector(merged)
        self.__dict__.update(new.__dict__)

    def to_vector(self) -> np.ndarray:
        vec = empty_vector(self.cap, self.levels, self.seed)
        vec[IDX_COUNT] = self.count
        vec[IDX_SUM] = self.sum
        vec[IDX_RSUM] = self.rsum
        vec[IDX_MIN] = self.min
        vec[IDX_MAX] = self.max
        return _encode(vec, self.vals, self.cnt, self.comps, self.clip)

    @classmethod
    def from_vector(cls, vec: np.ndarray) -> "CompactorSketch":
        cap, levels, seed = params_from_vector(vec)
        s = cls(cap, levels, seed)
        s.vals, s.cnt, s.comps, s.clip = state_from_vector(vec)
        s.count = float(vec[IDX_COUNT])
        s.sum = float(vec[IDX_SUM])
        s.rsum = float(vec[IDX_RSUM])
        s.min = float(vec[IDX_MIN]) if s.count else np.inf
        s.max = float(vec[IDX_MAX]) if s.count else -np.inf
        return s

    def item_mass(self) -> float:
        return float((self.cnt * 2.0 ** np.arange(self.levels)).sum())

    def quantile(self, q: float) -> float:
        return self.quantiles([q])[0]

    def quantiles(self, qs) -> np.ndarray:
        return quantiles_from_vectors(self.to_vector()[None, :],
                                      np.asarray(qs, np.float64))[0]


def merge_vectors(dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """Merge batched compactor wire vectors ``[n, M]`` level-wise:
    concatenate each level's items (both sides are <= cap, so staging
    fits 2*cap), then ONE bottom-up compaction pass.  Exact for
    count/sum/min/max; the coin continues from the summed compaction
    counters, so the merge is order-invariant bit-for-bit.  Param
    (cap/levels/seed) mismatches are refused, never coerced."""
    dst = np.asarray(dst, np.float64)
    src = np.asarray(src, np.float64)
    if dst.shape != src.shape:
        raise ValueError(f"shape mismatch: {dst.shape} vs {src.shape}")
    n = dst.shape[0]
    out = np.empty_like(dst)
    params = None
    for i in range(n):
        a, b = dst[i], src[i]
        if float(b[IDX_COUNT]) == 0.0 and float(b[IDX_CAP]) == 0.0:
            out[i] = a  # all-zero placeholder rows merge as identity
            continue
        if float(a[IDX_COUNT]) == 0.0 and float(a[IDX_CAP]) == 0.0:
            out[i] = b
            continue
        pa, pb = params_from_vector(a), params_from_vector(b)
        if pa != pb:
            raise ValueError(f"compactor param mismatch: {pa} vs {pb}")
        params = pa
        cap, levels, seed = params
        va, ca, qa, la = state_from_vector(a)
        vb, cb, qb, lb = state_from_vector(b)
        s2 = STAGE_MUL * cap
        stage_v = np.full((1, levels, s2), _PAD)
        stage_n = (ca + cb)[None, :]
        for l in range(levels):
            stage_v[0, l, :ca[l]] = va[l, :ca[l]]
            stage_v[0, l, ca[l]:ca[l] + cb[l]] = vb[l, :cb[l]]
        off, cnt_out, comps, clip = plan_pass(
            stage_n, np.array([qa + qb]), np.array([la + lb]), seed, cap)
        sv = apply_pass(stage_v, stage_n, off, cap)
        vec = empty_vector(cap, levels, seed)
        vec[IDX_COUNT] = a[IDX_COUNT] + b[IDX_COUNT]
        vec[IDX_SUM] = a[IDX_SUM] + b[IDX_SUM]
        vec[IDX_RSUM] = a[IDX_RSUM] + b[IDX_RSUM]
        vec[IDX_MIN] = min(a[IDX_MIN], b[IDX_MIN]) \
            if vec[IDX_COUNT] else np.inf
        vec[IDX_MAX] = max(a[IDX_MAX], b[IDX_MAX]) \
            if vec[IDX_COUNT] else -np.inf
        out[i] = _encode(vec, sv[0], cnt_out[0], int(comps[0]),
                         int(clip[0]))
    return out
