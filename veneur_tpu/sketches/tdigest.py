"""Batched merging t-digest as JAX tensor kernels.

This is the TPU-native re-design of the reference's sequential merging
t-digest (`tdigest/merging_digest.go:23-483`): instead of one Go object per
metric key with an amortized in-place merge loop (`mergeAllTemps`,
`merging_digest.go:140-224`) and a greedy sequential compression scan
(`mergeOne`, `merging_digest.go:229-255`), we hold the centroids of *all* K
keys as struct-of-arrays tensors `[K, C]` and compress every key at once with
a data-parallel pipeline:

    sort by mean  ->  prefix-sum of weights  ->  arcsine scale-function
    bucket assignment  ->  segmented weighted reduce  ->  re-sort compact

The scale function is the same arcsine `indexEstimate`
(`merging_digest.go:258-262`): k(q) = delta * (asin(2q-1)/pi + 1/2).  The
sequential reference merges a centroid into its predecessor while the k-index
span stays <= 1; the parallel formulation instead inverts a 1.5x-refined
scale function into fixed cluster boundaries and assigns each (sorted)
centroid to the cluster containing its *left* cumulative-weight edge.  Every
produced cluster then has k-span <= 1/1.5 plus the k-width of its last
member, which matches or beats the sequential guarantee for raw-sample
ingest while the cluster count stays within the reference's
ceil(pi*delta/2) memory bound (`merging_digest.go:71`).  Statistical
equivalence is validated by tests/test_tdigest.py (weight conservation,
size bound, 2% median error, merge-order invariance) mirroring the
reference's `tdigest/histo_test.go`, and by direct comparison against the
faithful sequential arm in tdigest_cpu.py.

Merging two digests (`MergingDigest.Merge`, `merging_digest.go:374-389`)
shuffles and re-Adds centroids sequentially to avoid order bias; here merge is
concatenate + sort + compress, which is order-invariant by construction (the
sort erases input order), so no shuffle is needed.

Quantile / CDF use the same uniform-within-centroid interpolation with
min/max boundary handling as the reference (`merging_digest.go:266-332`,
`centroidUpperBound` `merging_digest.go:355-370`), vectorized over all keys
and all requested quantiles at once.

All functions are jit-friendly, shape-static, and batched over the leading
key axis K; sharding K across devices with pjit/shard_map gives multi-chip
scaling with zero code change (see veneur_tpu/parallel/).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_COMPRESSION = 100.0

_INF = jnp.inf


# The parallel compressor buckets with a refined internal scale
# delta_eff = OVERSAMPLE * delta.  Left-edge cluster assignment bounds each
# cluster's k-span by 1/OVERSAMPLE (+ the k-width of its last member), which
# beats the sequential reference's span-<=-1 guarantee while the cluster
# count floor(OVERSAMPLE*delta)+1 stays within the reference's
# ceil(pi*delta/2) memory bound (`tdigest/merging_digest.go:71`).
OVERSAMPLE = 1.5


def centroid_capacity(compression: float) -> int:
    """Number of centroid slots per key: floor(1.5*delta)+1 clusters,
    rounded up to a multiple of 8 for TPU sublane alignment."""
    need = int(math.floor(OVERSAMPLE * compression)) + 1
    return ((need + 7) // 8) * 8


class TDigestState(NamedTuple):
    """Struct-of-arrays batched t-digest for K keys.

    Invariants (maintained by every exported op):
      - per row, centroids are sorted ascending by mean with empty slots
        (weight == 0) packed at the end;
      - `min`/`max` are +inf/-inf for rows that have never seen a sample;
      - `rsum` is the running reciprocal sum (sum of weight/value), matching
        the reference's `reciprocalSum` (`merging_digest.go:131`).
    """

    mean: jax.Array    # [K, C] f32
    weight: jax.Array  # [K, C] f32; 0 == empty slot
    min: jax.Array     # [K] f32
    max: jax.Array     # [K] f32
    rsum: jax.Array    # [K] f32

    @property
    def num_keys(self) -> int:
        return self.mean.shape[0]

    @property
    def capacity(self) -> int:
        return self.mean.shape[1]


def empty(num_keys: int, compression: float = DEFAULT_COMPRESSION,
          capacity: int | None = None) -> TDigestState:
    """A fresh state for `num_keys` keys (all rows empty)."""
    cap = capacity if capacity is not None else centroid_capacity(compression)
    k = num_keys
    return TDigestState(
        mean=jnp.zeros((k, cap), jnp.float32),
        weight=jnp.zeros((k, cap), jnp.float32),
        min=jnp.full((k,), _INF, jnp.float32),
        max=jnp.full((k,), -_INF, jnp.float32),
        rsum=jnp.zeros((k,), jnp.float32),
    )


# ---------------------------------------------------------------------------
# Core compression kernel
# ---------------------------------------------------------------------------

def _scale_k(q: jax.Array, compression: float) -> jax.Array:
    """Arcsine scale function k(q), `merging_digest.go:258-262`."""
    q = jnp.clip(q, 0.0, 1.0)
    return compression * (jnp.arcsin(2.0 * q - 1.0) / jnp.pi + 0.5)


def compress(mean: jax.Array, weight: jax.Array, compression: float,
             out_capacity: int) -> tuple[jax.Array, jax.Array]:
    """Compress centroid rows `[K, M]` down to `[K, out_capacity]`.

    Replaces the reference's sequential greedy `mergeAllTemps`/`mergeOne`
    loop (`merging_digest.go:140-255`) with a fully parallel segmented
    reduction.  Input rows need not be sorted; empty slots are weight==0.
    """
    kdim, m = mean.shape
    c = out_capacity
    delta = float(compression)

    # 1. Sort each row by mean, empties (+inf key) to the end.
    sort_key = jnp.where(weight > 0, mean, _INF)
    sort_key, mean, weight = jax.lax.sort(
        (sort_key, mean, weight), dimension=1, num_keys=1)

    # 2. Normalized cumulative left edges.  Assigning each centroid to the
    #    cluster containing its *left* quantile edge bounds every cluster's
    #    k-span by 1 + (k-width of its last member) — tight for raw samples,
    #    <= 2 when re-compressing already-compressed centroids.
    total = jnp.sum(weight, axis=1, keepdims=True)          # [K, 1]
    safe_total = jnp.where(total > 0, total, 1.0)
    cum = jnp.cumsum(weight, axis=1)                        # inclusive
    qleft = (cum - weight) / safe_total                     # [K, M]

    # 3. Cluster id by inverted scale function; empties parked in the last
    #    bucket where their zero weight is harmless.
    kval = _scale_k(qleft, OVERSAMPLE * delta)
    bucket = jnp.clip(jnp.floor(kval).astype(jnp.int32), 0, c - 1)
    bucket = jnp.where(weight > 0, bucket, c - 1)

    # 4. Segmented weighted reduce via prefix sums + per-bucket boundary
    #    gather.  `bucket` is monotone non-decreasing along the row (qleft
    #    is monotone), so the last index with bucket <= b marks the segment
    #    end.
    s_w = cum                                                # [K, M]
    s_wm = jnp.cumsum(weight * mean, axis=1)                 # [K, M]

    # Last input index with bucket <= b, for every target bucket b.
    # `bucket` is monotone per row, so this is a counting reduce —
    # formulated as one fused [K, M, C] comparison-sum instead of a
    # vmapped binary search (dynamic gathers inside vmapped searchsorted
    # lower catastrophically on TPU).
    targets = jnp.arange(c, dtype=jnp.int32)                 # [C]
    pos = jnp.sum((bucket[:, :, None] <= targets[None, None, :])
                  .astype(jnp.int32), axis=1) - 1            # [K, C], -1 = none

    def gather_prefix(s):
        g = jnp.take_along_axis(s, jnp.maximum(pos, 0), axis=1)
        return jnp.where(pos >= 0, g, 0.0)

    g_w = gather_prefix(s_w)                                 # [K, C]
    g_wm = gather_prefix(s_wm)
    zero = jnp.zeros((kdim, 1), jnp.float32)
    w_out = g_w - jnp.concatenate([zero, g_w[:, :-1]], axis=1)
    wm_out = g_wm - jnp.concatenate([zero, g_wm[:, :-1]], axis=1)
    # Guard tiny negative dust from float cancellation.
    w_out = jnp.maximum(w_out, 0.0)
    m_out = jnp.where(w_out > 0, wm_out / jnp.where(w_out > 0, w_out, 1.0), 0.0)

    # 5. Re-sort to restore "sorted, empties at end" (empty buckets may be
    #    interleaved with occupied ones).
    key2 = jnp.where(w_out > 0, m_out, _INF)
    _, m_out, w_out = jax.lax.sort((key2, m_out, w_out), dimension=1, num_keys=1)
    return m_out, w_out


# ---------------------------------------------------------------------------
# Ingest / merge
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("compression",))
def ingest(state: TDigestState, values: jax.Array, vweights: jax.Array,
           compression: float = DEFAULT_COMPRESSION) -> TDigestState:
    """Fold a batch of raw samples `[K, T]` into the digest state.

    Equivalent of `MergingDigest.Add` + `mergeAllTemps`
    (`merging_digest.go:115-224`) for every key at once.  Empty sample slots
    have vweights == 0.  Also maintains min/max/reciprocal-sum exactly like
    `Add` (`merging_digest.go:127-131`).
    """
    occupied = vweights > 0
    vmin = jnp.min(jnp.where(occupied, values, _INF), axis=1)
    vmax = jnp.max(jnp.where(occupied, values, -_INF), axis=1)
    rs = jnp.sum(jnp.where(occupied, vweights / values, 0.0), axis=1)

    cat_mean = jnp.concatenate([state.mean, values], axis=1)
    cat_w = jnp.concatenate([state.weight, vweights], axis=1)
    m, w = compress(cat_mean, cat_w, compression, state.capacity)
    return TDigestState(
        mean=m, weight=w,
        min=jnp.minimum(state.min, vmin),
        max=jnp.maximum(state.max, vmax),
        rsum=state.rsum + rs,
    )


@functools.partial(jax.jit, static_argnames=("compression",))
def merge(state: TDigestState, other: TDigestState,
          compression: float = DEFAULT_COMPRESSION) -> TDigestState:
    """Merge another batched digest into this one, key-aligned.

    Equivalent of `MergingDigest.Merge` (`merging_digest.go:374-389`); the
    reference re-Adds the other digest's centroids in shuffled order to avoid
    order bias — our concat+sort+compress is order-invariant by construction
    so the shuffle is unnecessary.
    """
    cat_mean = jnp.concatenate([state.mean, other.mean], axis=1)
    cat_w = jnp.concatenate([state.weight, other.weight], axis=1)
    m, w = compress(cat_mean, cat_w, compression, state.capacity)
    return TDigestState(
        mean=m, weight=w,
        min=jnp.minimum(state.min, other.min),
        max=jnp.maximum(state.max, other.max),
        rsum=state.rsum + other.rsum,
    )


@functools.partial(jax.jit, static_argnames=("compression",))
def merge_stacked(state: TDigestState, means: jax.Array, weights: jax.Array,
                  mins: jax.Array, maxs: jax.Array, rsums: jax.Array,
                  compression: float = DEFAULT_COMPRESSION) -> TDigestState:
    """Merge R incoming digests per key: means/weights `[R, K, C2]`,
    scalars `[R, K]`.  This is the global-import reduce — the device-side
    equivalent of the gRPC `ImportMetric` merge loop (`worker.go:402-459`)
    that the north-star benchmark measures.
    """
    kdim = means.shape[1]
    flat_means = jnp.transpose(means, (1, 0, 2)).reshape(kdim, -1)
    flat_weights = jnp.transpose(weights, (1, 0, 2)).reshape(kdim, -1)
    cat_mean = jnp.concatenate([state.mean, flat_means], axis=1)
    cat_w = jnp.concatenate([state.weight, flat_weights], axis=1)
    m, w = compress(cat_mean, cat_w, compression, state.capacity)
    return TDigestState(
        mean=m, weight=w,
        min=jnp.minimum(state.min, jnp.min(mins, axis=0)),
        max=jnp.maximum(state.max, jnp.max(maxs, axis=0)),
        rsum=state.rsum + jnp.sum(rsums, axis=0),
    )


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------

def total_weight(state: TDigestState) -> jax.Array:
    """Count() equivalent, [K] (`merging_digest.go:340-342`)."""
    return jnp.sum(state.weight, axis=1)


def sum_values(state: TDigestState) -> jax.Array:
    """Sum() equivalent, [K] (`merging_digest.go:346-353`)."""
    return jnp.sum(state.weight * state.mean, axis=1)


def _bounds(state: TDigestState):
    """Per-centroid uniform-distribution bounds, mirroring
    `centroidUpperBound` (`merging_digest.go:355-370`): the upper bound of
    centroid i is the midpoint to centroid i+1, or max for the last occupied
    centroid; the lower bound is the previous upper bound, or min for the
    first."""
    mean, weight = state.mean, state.weight
    kdim, c = mean.shape
    occ = weight > 0
    n = jnp.sum(occ.astype(jnp.int32), axis=1)                       # [K]
    idx = jnp.arange(c)[None, :]
    mid = 0.5 * (mean + jnp.concatenate(
        [mean[:, 1:], mean[:, -1:]], axis=1))                        # [K, C]
    last = idx == (n[:, None] - 1)
    upper = jnp.where(last, state.max[:, None], mid)
    upper = jnp.where(idx < n[:, None], upper, state.max[:, None])
    lower = jnp.concatenate([state.min[:, None], upper[:, :-1]], axis=1)
    return lower, upper, n


@jax.jit
def quantile(state: TDigestState, qs: Sequence[float] | jax.Array) -> jax.Array:
    """Vectorized Quantile() (`merging_digest.go:304-332`): returns [K, P].

    Uniform interpolation inside the containing centroid between its lower
    and upper bounds; NaN for empty rows.
    """
    qs = jnp.asarray(qs, jnp.float32)
    lower, upper, n = _bounds(state)
    w = state.weight
    cum = jnp.cumsum(w, axis=1)                                      # [K, C]
    tot = cum[:, -1]
    target = qs[None, :] * tot[:, None]                              # [K, P]

    # First occupied centroid i with cum[i] >= target (q <= weightSoFar
    # + w) — a fused comparison-count, not a vmapped binary search (TPU).
    i = jnp.sum((cum[:, :, None] < target[:, None, :]).astype(jnp.int32),
                axis=1)                                              # [K, P]
    i = jnp.minimum(i, jnp.maximum(n[:, None] - 1, 0))

    cum_before = jnp.take_along_axis(
        jnp.concatenate([jnp.zeros_like(cum[:, :1]), cum[:, :-1]], axis=1),
        i, axis=1)
    w_i = jnp.take_along_axis(w, i, axis=1)
    lo = jnp.take_along_axis(lower, i, axis=1)
    up = jnp.take_along_axis(upper, i, axis=1)
    prop = jnp.where(w_i > 0, (target - cum_before) / jnp.where(w_i > 0, w_i, 1.0), 0.0)
    prop = jnp.clip(prop, 0.0, 1.0)
    out = lo + prop * (up - lo)
    return jnp.where((n > 0)[:, None], out, jnp.nan)


@jax.jit
def cdf(state: TDigestState, xs: Sequence[float] | jax.Array) -> jax.Array:
    """Vectorized CDF() (`merging_digest.go:266-298`): returns [K, P].

    Locates the single centroid whose [lower, upper) bound-interval contains
    each query via searchsorted (O(K*P*log C), same pattern as quantile)
    and interpolates its weight fraction uniformly.
    """
    xs = jnp.asarray(xs, jnp.float32)
    lower, upper, n = _bounds(state)
    w = state.weight
    cum = jnp.cumsum(w, axis=1)
    tot = cum[:, -1]
    x = jnp.broadcast_to(xs[None, :], (state.num_keys, xs.shape[0]))  # [K, P]

    # First centroid with upper > x holds the query point (fused
    # comparison-count; see quantile()).
    i = jnp.sum((upper[:, :, None] <= x[:, None, :]).astype(jnp.int32),
                axis=1)                                               # [K, P]
    i = jnp.minimum(i, jnp.maximum(n[:, None] - 1, 0))

    w_i = jnp.take_along_axis(w, i, axis=1)
    lo = jnp.take_along_axis(lower, i, axis=1)
    up = jnp.take_along_axis(upper, i, axis=1)
    cum_before = jnp.take_along_axis(cum, i, axis=1) - w_i
    span = up - lo
    frac = jnp.where(span > 0,
                     jnp.clip((x - lo) / jnp.where(span > 0, span, 1.0), 0.0, 1.0),
                     (x >= up).astype(jnp.float32))
    out = (cum_before + w_i * frac) / jnp.where(tot > 0, tot, 1.0)[:, None]
    # Boundary precedence matches the reference (merging_digest.go:272-277):
    # the <= min check wins over >= max (a min==max digest yields 0).
    out = jnp.where(x >= state.max[:, None], 1.0, out)
    out = jnp.where(x <= state.min[:, None], 0.0, out)
    return jnp.where((n > 0)[:, None], out, jnp.nan)


def weighted_eval(mean: jax.Array, weight: jax.Array,
                  d_min: jax.Array, d_max: jax.Array,
                  percentiles: jax.Array) -> jax.Array:
    """Quantiles + total weight + weighted sum for rows of weighted points
    `[K, D]` (raw samples and/or merged centroids), in one pass: sort by
    value, cumulative-weight midpoint interpolation, clamp to the
    authoritative [min, max].  Returns `[K, P + 2]`: the P quantile
    columns, then total weight, then weighted sum.

    This IS the serving flush's evaluation core.  The reference merges
    incoming digests into a compressed t-digest and interpolates within
    its centroids (`worker.go:402-459` -> `merging_digest.go:304-332`);
    evaluating directly on the *uncompressed* merged point cloud gives
    strictly finer quantiles for the interval being flushed, and — unlike
    compress — needs nothing but a sort, cumsums, and fused comparison
    reductions, all of which map cleanly onto the TPU's vector unit.
    Compression still runs where the sketch must stay bounded: forwarding
    export (serving.digest_export) and hot-key pre-reduction
    (partial_digests).

    Rows must have D >= 2 (callers pad).  Empty cells are weight == 0;
    fully-empty rows return zeros.

    This is the exactness REFERENCE for the fused Pallas kernel
    (ops/sorted_eval.py): lax.sort is stable, so tied values keep their
    staging order — the Pallas compact (packed-key) network matches that
    order exactly via its index payload, while the f32 paired bitonic
    network may order equal-valued points arbitrarily (pair-consistent
    either way: a weight never separates from its value, so totals,
    sums, and any quantile not straddling a tied run are unaffected).
    bf16-staged values widen here so the twin evaluates exactly what the
    kernel reconstructs.
    """
    if mean.dtype == jnp.bfloat16:
        mean = mean.astype(jnp.float32)
    kdim, d = mean.shape
    key = jnp.where(weight > 0, mean, _INF)
    key, mean, weight = jax.lax.sort((key, mean, weight), dimension=1,
                                     num_keys=1)
    cum = jnp.cumsum(weight, axis=1)                         # [K, D]
    total = cum[:, -1:]                                      # [K, 1]
    sums = jnp.sum(mean * weight, axis=1, keepdims=True)     # [K, 1]
    n_real = jnp.sum((weight > 0).astype(jnp.int32), axis=1,
                     keepdims=True)                          # [K, 1]

    # midpoint rule: the i-th sorted point sits at cumulative position
    # cum_i - w_i/2 (uniform-in-centroid semantics for unit weights,
    # merging_digest.go:266-332)
    cmid = cum - 0.5 * weight
    # pinned like the Pallas kernel's tq (ops/mxu.py pin): the rank
    # compares and `tq - c_lo` must see the ROUNDED product, not a
    # per-program-contracted FMS intermediate
    from veneur_tpu.ops.mxu import pin as _pin
    tq = _pin(percentiles[None, :] * total)                  # [K, P]
    # fused comparison-count instead of a vmapped binary search
    idx = jnp.sum((cmid[:, :, None] < tq[:, None, :])
                  .astype(jnp.int32), axis=1)                # [K, P]
    hi_bound = jnp.maximum(n_real - 1, 1)
    ii = jnp.clip(idx, 1, hi_bound)
    g = lambda a, i: jnp.take_along_axis(a, i, axis=1)
    m_lo, m_hi = g(mean, ii - 1), g(mean, ii)
    c_lo, c_hi = g(cmid, ii - 1), g(cmid, ii)
    t = jnp.where(c_hi > c_lo,
                  (tq - c_lo) / jnp.maximum(c_hi - c_lo, 1e-30), 0.0)
    # the interpolation product is pinned too: per-program FMA
    # contraction would otherwise leave last-ulp differences between
    # the twin and the kernel (and between kernel tilings), breaking
    # the bit-parity contract
    q = m_lo + _pin((m_hi - m_lo) * jnp.clip(t, 0.0, 1.0))
    # single-point rows interpolate against padding; take the point itself
    q = jnp.where(n_real <= 1, mean[:, :1], q)
    q = jnp.clip(q, d_min[:, None], d_max[:, None])
    q = jnp.where(total > 0, q, 0.0)
    return jnp.concatenate([q, total, sums], axis=1)


def aggregates(state: TDigestState) -> dict[str, jax.Array]:
    """All scalar aggregates the Histo sampler flushes
    (`samplers/samplers.go:377-495`): each [K]."""
    w = total_weight(state)
    s = sum_values(state)
    safe_w = jnp.where(w > 0, w, 1.0)
    med = quantile(state, jnp.array([0.5], jnp.float32))[:, 0]
    return {
        "min": state.min,
        "max": state.max,
        "sum": s,
        "count": w,
        "avg": s / safe_w,
        "median": med,
        "hmean": w / jnp.where(state.rsum != 0, state.rsum, 1.0),
    }


# ---------------------------------------------------------------------------
# Host-side scalar convenience wrapper (reference-API-shaped; used by tests,
# codecs, and the CPU baseline arm of the benchmark)
# ---------------------------------------------------------------------------

class MergingDigest:
    """Single-digest convenience wrapper over the batched kernels.

    API mirrors the reference `MergingDigest` (`merging_digest.go`) so the
    statistical tests translate directly.  Buffers samples host-side and
    flushes them through the batched `ingest` kernel (K=1).
    """

    def __init__(self, compression: float = DEFAULT_COMPRESSION):
        self.compression = float(compression)
        self._cap = centroid_capacity(compression)
        self._temp_cap = max(32, self._cap)
        self._state = empty(1, compression, self._cap)
        self._buf_v: list[float] = []
        self._buf_w: list[float] = []

    def add(self, value: float, weight: float = 1.0) -> None:
        if not np.isfinite(value) or not weight > 0:
            raise ValueError("invalid value added")
        self._buf_v.append(float(value))
        self._buf_w.append(float(weight))
        if len(self._buf_v) >= self._temp_cap:
            self._flush_temps()

    def add_batch(self, values, weights=None) -> None:
        values = np.asarray(values, np.float32).ravel()
        if weights is None:
            weights = np.ones_like(values)
        else:
            weights = np.asarray(weights, np.float32).ravel()
        if not np.isfinite(values).all() or not (weights > 0).all():
            raise ValueError("invalid value added")
        self._buf_v.extend(values.tolist())
        self._buf_w.extend(weights.tolist())
        self._flush_temps()

    def _flush_temps(self) -> None:
        if not self._buf_v:
            return
        n = len(self._buf_v)
        # Pad to the next power of two so repeated flushes reuse compiled
        # shapes (weight-0 slots are ignored by the kernel).
        padded = max(32, 1 << (n - 1).bit_length())
        v = np.zeros((1, padded), np.float32)
        w = np.zeros((1, padded), np.float32)
        v[0, :n] = self._buf_v
        w[0, :n] = self._buf_w
        self._buf_v, self._buf_w = [], []
        self._state = ingest(self._state, jnp.asarray(v), jnp.asarray(w),
                             self.compression)

    def merge(self, other: "MergingDigest") -> None:
        self._flush_temps()
        other._flush_temps()
        # merge() concatenates along the centroid axis, so mismatched
        # capacities (different compressions) are handled directly.
        self._state = merge(self._state, other._state, self.compression)

    # accessors mirroring merging_digest.go:334-353
    def quantile(self, q: float) -> float:
        self._flush_temps()
        return float(quantile(self._state, [q])[0, 0])

    def cdf(self, x: float) -> float:
        self._flush_temps()
        return float(cdf(self._state, [x])[0, 0])

    def min(self) -> float:
        self._flush_temps()
        return float(self._state.min[0])

    def max(self) -> float:
        self._flush_temps()
        return float(self._state.max[0])

    def count(self) -> float:
        self._flush_temps()
        return float(total_weight(self._state)[0])

    def sum(self) -> float:
        self._flush_temps()
        return float(sum_values(self._state)[0])

    def reciprocal_sum(self) -> float:
        self._flush_temps()
        return float(self._state.rsum[0])

    def centroids(self) -> tuple[np.ndarray, np.ndarray]:
        """(means, weights) of occupied centroids, sorted by mean."""
        self._flush_temps()
        m = np.asarray(self._state.mean[0])
        w = np.asarray(self._state.weight[0])
        occ = w > 0
        return m[occ], w[occ]
