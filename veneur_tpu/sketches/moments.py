"""Moments sketch: a fixed-size mergeable quantile summary.

The second sketch family (ROADMAP #3; "Moment-Based Quantile Sketches
for Efficient High Cardinality Aggregation Queries", arXiv:1803.01969).
Where a t-digest is a variable set of weighted centroids whose merge is
concat+sort+compress, a moments sketch is ONE fixed-size f64 vector
whose merge is (rebase +) elementwise addition — on TPU, merging a
million keys becomes one dense batched reduction with no sort network
at all (ops/moments_eval.py), a fundamentally better roofline for the
high-cardinality/low-accuracy tiers (cardinality-guard tail rollups,
coarse per-tenant quantiles).

Vector layout (``vector_len(k)`` = 6 + 2k doubles)::

    [0] count   total weight (exact; integer-exact below 2^53)
    [1] min     true minimum
    [2] max     true maximum
    [3] sum     exact weighted sum (conservation; NOT derived from the
                scaled power sums, whose reconstruction would round)
    [4] rsum    reciprocal sum (sum of w/x; harmonic mean)
    [5] logn    weight over strictly-positive samples (the mass the
                log-domain power sums cover)
    [6 .. 6+k)       U_j = sum of w * t^j, j = 1..k, with
                     t = (2x - (min+max)) / (max - min)  in [-1, 1]
    [6+k .. 6+2k)    V_j = sum of w * u^j, j = 1..k, with
                     u the same map applied to ln(x) over
                     [ln min, ln max]; all-zero unless min > 0

The raw and log power sums are stored RANGE-SCALED to the sketch's own
domain rather than as raw ``sum(x^j)``: raw power sums of values far
from zero relative to their spread (epoch stamps, latencies in a narrow
band) lose all significance when converted to the centered moments the
maxent solver needs — the binomial conversion cancels ``(mean/span)^k``
orders of magnitude, which at k = 8 exceeds f64 entirely.  Scaled sums
are bounded by ``count`` at every order, and a cross-sketch merge
rebases them to the combined domain with a binomial transform whose
coefficients are all O(1) — exact in exact arithmetic, numerically
stable by construction.  Within one domain the merge IS elementwise
addition, which is the form the flush kernel exploits.

The quantile solver (ops/moments_eval.py) recovers a maximum-entropy
density matching the Chebyshev moments derived from this vector and
reads quantiles off its CDF; accuracy per family is committed evidence
in analysis/tdigest_accuracy.csv (scripts/tdigest_analysis.py).
"""

from __future__ import annotations

import functools
import math

import numpy as np

# power sums per domain (raw + log); the wire/checkpoint contract —
# restoring or merging across a k mismatch is refused, never coerced
DEFAULT_K = 8

# log-domain solve engages when the data spans this dynamic range
# (heavy-tailed data: the log map spends moment resolution where the
# mass is instead of on the tail's span)
LOG_DOMAIN_RATIO = 64.0

IDX_COUNT = 0
IDX_MIN = 1
IDX_MAX = 2
IDX_SUM = 3
IDX_RSUM = 4
IDX_LOGN = 5
SUMS_OFF = 6


def vector_len(k: int = DEFAULT_K) -> int:
    return SUMS_OFF + 2 * k


def k_from_len(m: int) -> int:
    k, rem = divmod(m - SUMS_OFF, 2)
    if rem or k < 1:
        raise ValueError(f"not a moments vector length: {m}")
    return k


def empty_vector(k: int = DEFAULT_K) -> np.ndarray:
    v = np.zeros(vector_len(k), np.float64)
    v[IDX_MIN] = np.inf
    v[IDX_MAX] = -np.inf
    return v


def _scale_params(a, b):
    """(alpha, beta) of t = alpha*x + beta mapping [a, b] -> [-1, 1];
    degenerate domains (b <= a) map everything to 0."""
    span = b - a
    safe = np.where(span > 0, span, 1.0)
    alpha = np.where(span > 0, 2.0 / safe, 0.0)
    beta = np.where(span > 0, -(a + b) / safe, 0.0)
    return alpha, beta


@functools.lru_cache(maxsize=None)
def _binom_table(k: int) -> np.ndarray:
    # cached: rebase_sums sits on the per-imported-metric hot path
    t = np.zeros((k + 1, k + 1))
    for j in range(k + 1):
        for m in range(j + 1):
            t[j, m] = math.comb(j, m)
    return t


def rebase_sums(sums: np.ndarray, old_ab, new_ab) -> np.ndarray:
    """Rebase scaled power-sum rows ``[n, k+1]`` (order 0..k, order 0 =
    the count) from per-row domain ``old_ab = (a0, b0)`` to ``new_ab``.

    t_new = alpha * t_old + beta with alpha = span_old/span_new in
    (0, 1] and |beta| <= 1 when the new domain contains the old one, so
    every binomial term is O(count) — no cancellation blowup.  Rows
    whose old domain is degenerate (a0 == b0: single-valued data) map
    through the point's position in the new domain."""
    sums = np.asarray(sums, np.float64)
    n, kp1 = sums.shape
    k = kp1 - 1
    a0, b0 = (np.asarray(old_ab[0], np.float64),
              np.asarray(old_ab[1], np.float64))
    a1, b1 = (np.asarray(new_ab[0], np.float64),
              np.asarray(new_ab[1], np.float64))
    # empty sketches carry (inf, -inf) domains and all-zero sums; the
    # mapping is then irrelevant, but inf * 0 would poison the zeros
    # with NaN — sanitize to a degenerate finite domain instead
    a0 = np.where(np.isfinite(a0), a0, 0.0)
    b0 = np.where(np.isfinite(b0), b0, 0.0)
    a1 = np.where(np.isfinite(a1), a1, 0.0)
    b1 = np.where(np.isfinite(b1), b1, 0.0)
    span0, span1 = b0 - a0, b1 - a1
    safe1 = np.where(span1 > 0, span1, 1.0)
    alpha = np.where(span1 > 0, np.where(span0 > 0, span0 / safe1, 0.0),
                     0.0)
    # degenerate old domain: all mass sits at x = a0 -> t fixed
    t_point = np.where(span1 > 0, (2.0 * a0 - (a1 + b1)) / safe1, 0.0)
    beta = np.where(span0 > 0,
                    np.where(span1 > 0, (a0 + b0 - a1 - b1) / safe1,
                             0.0),
                    t_point)
    binom = _binom_table(k)
    # powers of alpha/beta per row, [n, k+1]
    ap = np.ones((n, kp1))
    bp = np.ones((n, kp1))
    for j in range(1, kp1):
        ap[:, j] = ap[:, j - 1] * alpha
        bp[:, j] = bp[:, j - 1] * beta
    out = np.zeros_like(sums)
    for j in range(kp1):
        acc = out[:, j]
        for m in range(j + 1):
            acc += binom[j, m] * ap[:, m] * bp[:, j - m] * sums[:, m]
    return out


def _scaled_powers_accumulate(sums: np.ndarray, rows: np.ndarray,
                              t: np.ndarray, w: np.ndarray) -> None:
    """sums[rows, j] += w * t^j for j = 1..k (order-0 column is the
    caller's count bookkeeping), vectorized with np.add.at."""
    k = sums.shape[1] - 1
    p = np.ones_like(t)
    for j in range(1, k + 1):
        p = p * t
        np.add.at(sums[:, j], rows, w * p)


class MomentsSketch:
    """Single-key convenience wrapper over one moments vector (the
    analysis harness / test twin; production keys live batched in
    core.arena.MomentsArena)."""

    def __init__(self, k: int = DEFAULT_K):
        self.k = k
        self.vec = empty_vector(k)

    def add_batch(self, values, weights=None) -> None:
        vals = np.asarray(values, np.float64).ravel()
        if len(vals) == 0:
            return
        wts = (np.ones_like(vals) if weights is None
               else np.asarray(weights, np.float64).ravel())
        inc = empty_vector(self.k)
        inc[IDX_COUNT] = wts.sum()
        inc[IDX_MIN] = vals.min()
        inc[IDX_MAX] = vals.max()
        inc[IDX_SUM] = float(vals @ wts)
        with np.errstate(divide="ignore"):
            nz = vals != 0
            inc[IDX_RSUM] = float((wts[nz] / vals[nz]).sum())
        pos = vals > 0
        inc[IDX_LOGN] = float(wts[pos].sum())
        alpha, beta = _scale_params(inc[IDX_MIN], inc[IDX_MAX])
        t = alpha * vals + beta
        sums = np.zeros((1, self.k + 1))
        _scaled_powers_accumulate(
            sums, np.zeros(len(vals), np.int64), t, wts)
        inc[SUMS_OFF:SUMS_OFF + self.k] = sums[0, 1:]
        if inc[IDX_MIN] > 0:
            lv = np.log(vals)
            la, lb = np.log(inc[IDX_MIN]), np.log(inc[IDX_MAX])
            alpha, beta = _scale_params(la, lb)
            u = alpha * lv + beta
            lsums = np.zeros((1, self.k + 1))
            _scaled_powers_accumulate(
                lsums, np.zeros(len(vals), np.int64), u, wts)
            inc[SUMS_OFF + self.k:] = lsums[0, 1:]
        self.vec = merge_vectors(self.vec[None, :], inc[None, :])[0]

    def merge(self, other: "MomentsSketch | np.ndarray") -> None:
        vec = other.vec if isinstance(other, MomentsSketch) else other
        self.vec = merge_vectors(self.vec[None, :],
                                 np.asarray(vec, np.float64)[None, :])[0]

    @property
    def count(self) -> float:
        return float(self.vec[IDX_COUNT])

    def quantile(self, q: float) -> float:
        return self.quantiles([q])[0]

    def quantiles(self, qs) -> np.ndarray:
        from veneur_tpu.ops import moments_eval
        return moments_eval.quantiles_from_vectors(
            self.vec[None, :], np.asarray(qs, np.float64))[0]


def merge_vectors(dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """Merge batched moments vectors ``[n, M]`` elementwise: combined
    domain, both power-sum blocks rebased to it, then added.  Exact for
    count/min/max/sum/rsum/logn; the scaled sums rebase with O(1)
    coefficients (see module docstring).  Returns a new array."""
    dst = np.asarray(dst, np.float64)
    src = np.asarray(src, np.float64)
    if dst.shape != src.shape:
        raise ValueError(f"shape mismatch: {dst.shape} vs {src.shape}")
    k = k_from_len(dst.shape[1])
    out = np.empty_like(dst)
    out[:, IDX_COUNT] = dst[:, IDX_COUNT] + src[:, IDX_COUNT]
    out[:, IDX_MIN] = np.minimum(dst[:, IDX_MIN], src[:, IDX_MIN])
    out[:, IDX_MAX] = np.maximum(dst[:, IDX_MAX], src[:, IDX_MAX])
    for i in (IDX_SUM, IDX_RSUM, IDX_LOGN):
        out[:, i] = dst[:, i] + src[:, i]
    new_ab = (out[:, IDX_MIN], out[:, IDX_MAX])

    def sums_of(v, lo, hi, dom):
        s = np.zeros((v.shape[0], k + 1))
        s[:, 0] = v[:, IDX_COUNT] if dom == "raw" else v[:, IDX_LOGN]
        s[:, 1:] = v[:, lo:hi]
        return s

    def domain_of(v, dom):
        a, b = v[:, IDX_MIN], v[:, IDX_MAX]
        if dom == "raw":
            return a, b
        ok = (a > 0) & np.isfinite(a) & np.isfinite(b)
        sa = np.where(ok, a, 1.0)
        sb = np.where(ok, np.maximum(b, sa), 1.0)
        return np.log(sa), np.log(sb)

    raw = (rebase_sums(sums_of(dst, SUMS_OFF, SUMS_OFF + k, "raw"),
                       domain_of(dst, "raw"), new_ab)
           + rebase_sums(sums_of(src, SUMS_OFF, SUMS_OFF + k, "raw"),
                         domain_of(src, "raw"), new_ab))
    out[:, SUMS_OFF:SUMS_OFF + k] = raw[:, 1:]
    # log sums survive only while the combined domain stays positive
    ok = (out[:, IDX_MIN] > 0) & np.isfinite(out[:, IDX_MIN]) \
        & np.isfinite(out[:, IDX_MAX])
    if ok.any():
        la = np.log(np.where(ok, out[:, IDX_MIN], 1.0))
        lb = np.log(np.where(ok, np.maximum(out[:, IDX_MAX],
                                            out[:, IDX_MIN]), 1.0))
        lg = (rebase_sums(sums_of(dst, SUMS_OFF + k, SUMS_OFF + 2 * k,
                                  "log"),
                          domain_of(dst, "log"), (la, lb))
              + rebase_sums(sums_of(src, SUMS_OFF + k,
                                    SUMS_OFF + 2 * k, "log"),
                            domain_of(src, "log"), (la, lb)))
        out[:, SUMS_OFF + k:] = np.where(ok[:, None], lg[:, 1:], 0.0)
    else:
        out[:, SUMS_OFF + k:] = 0.0
    # empty-side hygiene: merging with an all-empty vector must be the
    # identity (inf/-inf min/max poison nothing above by construction)
    return out


def fold_values(sums: np.ndarray, lsums: np.ndarray, rows: np.ndarray,
                vals: np.ndarray, wts: np.ndarray, ab, lab) -> None:
    """Fold weighted samples into batched scaled power-sum blocks
    ``sums``/``lsums`` ``[n, k+1]`` (order 0 = count mass folded here),
    where each row's domain is ``ab = (a[n], b[n])`` (and ``lab`` its
    log twin; rows with a <= 0 skip the log block).  Pure numpy f64 —
    the host-side fold the arena uses for hot-row pre-reduction and
    forwarding export; the flush-path equivalent runs on device
    (ops/moments_eval.py)."""
    a, b = ab
    alpha, beta = _scale_params(a[rows], b[rows])
    t = np.clip(alpha * vals + beta, -1.0, 1.0)
    np.add.at(sums[:, 0], rows, wts)
    _scaled_powers_accumulate(sums, rows, t, wts)
    pos = vals > 0
    if pos.any():
        la, lb = lab
        prow = rows[pos]
        ok = a[prow] > 0
        prow, pv, pw = prow[ok], vals[pos][ok], wts[pos][ok]
        if len(prow):
            alpha, beta = _scale_params(la[prow], lb[prow])
            u = np.clip(alpha * np.log(pv) + beta, -1.0, 1.0)
            np.add.at(lsums[:, 0], prow, pw)
            _scaled_powers_accumulate(lsums, prow, u, pw)


def log_domain(a: np.ndarray, b: np.ndarray):
    """(ln a, ln b) with degenerate placeholders where a <= 0 (the
    sentinel lb < la disables the log-domain solve in-program)."""
    ok = a > 0
    la = np.where(ok, np.log(np.where(ok, a, 1.0)), 0.0)
    lb = np.where(ok, np.log(np.where(ok, np.maximum(b, a), 1.0)),
                  -1.0)
    return la, lb
