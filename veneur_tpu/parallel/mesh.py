"""Device mesh construction for the sharded aggregation tier.

The reference scales with hash-sharded workers in one process
(`worker.go:34-50`, P2 in SURVEY.md §2.10) and a consistent-hash proxy tier
across processes (P4).  The TPU-native analog is a 2-D mesh:

  - axis "shard": partitions the metric-key space — each device owns
    K/n_shards rows of every arena (the pjit analog of fnv1a % num_workers
    and of the proxy's hash ring);
  - axis "replica": parallel ingest lanes — each replica holds partial
    sketches for the same keys (e.g. digests forwarded by a subset of local
    instances), reduced at flush time with XLA collectives over ICI
    (all_gather + compress for t-digests, pmax for HLL registers, psum for
    counters) — the map-reduce of flusher.go:516-591 / worker.go:402-459
    as a device collective.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"
REPLICA_AXIS = "replica"


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """jax.shard_map across JAX releases: new JAX exposes it at the top
    level (with `check_vma`); older releases only ship
    jax.experimental.shard_map.shard_map (with `check_rep`).  Both
    checks are disabled — the flush body's collectives are hand-placed
    and the replication checker rejects the axis-size-1 specialization
    it cannot see through."""
    top = getattr(jax, "shard_map", None)
    if top is not None:
        return top(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    from jax.experimental.shard_map import shard_map as legacy
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def make_mesh(n_devices: int | None = None,
              replicas: int | None = None) -> Mesh:
    """A (shard, replica) mesh over the first n devices.

    replicas defaults to 2 when the device count allows, else 1 — key
    sharding is the primary scaling axis.
    """
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    if replicas is None:
        replicas = 2 if n % 2 == 0 and n >= 2 else 1
    if n % replicas != 0:
        raise ValueError(f"{n} devices not divisible into {replicas} replicas")
    if jax.process_count() > 1:
        per_host = len(jax.local_devices())
        if per_host and per_host % replicas != 0:
            import logging
            logging.getLogger("veneur_tpu.parallel.mesh").warning(
                "mesh_replicas=%d does not divide the per-host device "
                "count %d: replica groups will straddle hosts and the "
                "flush all_gather will ride DCN instead of ICI",
                replicas, per_host)
    shards = n // replicas
    dev_array = np.asarray(devices[:n]).reshape(shards, replicas)
    return Mesh(dev_array, (SHARD_AXIS, REPLICA_AXIS))


def key_sharding(mesh: Mesh) -> NamedSharding:
    """Arrays whose leading axis is the key axis: sharded over 'shard',
    replicated over 'replica'."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replica_key_sharding(mesh: Mesh) -> NamedSharding:
    """Staged partials [R, K, ...]: replica-sharded leading axis, key-sharded
    second axis."""
    return NamedSharding(mesh, P(REPLICA_AXIS, SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, mult: int) -> int:
    return int(math.ceil(n / mult)) * mult
