"""The sharded global-aggregation flush step — the framework's flagship
SPMD program.

One call evaluates the whole global tier's flush: the interval's staged
weighted points (raw samples and forwarded digest centroids alike) are
evaluated for every key at once, with
  - t-digest reduce  = all_gather(sample slices) over the replica axis +
    one batched sorted evaluation (the collective form of Histo.Merge,
    `samplers/samplers.go:539-543` / `worker.go:402-459`),
  - HLL reduce       = lax.pmax over replica register lanes,
  - counter reduce   = lax.psum over (hi, lo) f32 planes,
  - unique-timeseries tally = pmax over *both* axes + estimate
    (the device analog of tallyTimeseries, `flusher.go:249-258`).

Keys are sharded over the 'shard' mesh axis, so each device only touches
its K/n_shards rows; collectives ride ICI within the replica groups.
Single-device use (entry() in __graft_entry__.py) is the same body with no
collectives.  The body is shared with the production serving path
(veneur_tpu/parallel/serving.py flush_body) — this module only packages it
with example inputs for compile checks and the benchmark.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from veneur_tpu.parallel import serving
from veneur_tpu.parallel.mesh import REPLICA_AXIS, SHARD_AXIS
from veneur_tpu.sketches import tdigest as td

FlushInputs = serving.FlushInputs
FlushOutputs = serving.FlushOutputs


@functools.partial(jax.jit, static_argnames=("uniform",))
def flush_step(inputs: FlushInputs, percentiles: jax.Array,
               uniform: bool = False) -> FlushOutputs:
    """Single-device flush step (the compile-checked entry point)."""
    return serving.flush_body(inputs, percentiles, axis=None,
                              uniform=uniform)


@functools.partial(jax.jit, static_argnames=("uniform",))
def flush_step_packed(inputs: FlushInputs, percentiles: jax.Array,
                      uniform: bool = False
                      ) -> tuple[jax.Array, jax.Array]:
    """flush_step with its f32 outputs packed into ONE flat buffer
    (serving.pack_outputs) — the production launch shape: per-launch
    dispatch cost scales with output-handle count, so the global tier's
    flush hands the host (flat_f32, set_regs_u8) instead of six arrays.
    `uniform` (static) selects the key-only sort when every staged
    weight is 1 (see ops/sorted_eval.py)."""
    out = serving.flush_body(inputs, percentiles, axis=None,
                              uniform=uniform)
    return serving.pack_outputs(out), out.set_regs


def _sharded_body(mesh: Mesh):
    """The shard_map'd flush body over a (shard, replica) mesh: keys
    over 'shard', staged depth repartitioned over 'replica' with one
    all_to_all (each device evaluates K_s/R keys at full depth), lane
    reductions over 'replica'.  When the replica axis has size 1 the
    collectives are elided at trace time (the mesh=1 specialization)."""
    from veneur_tpu.parallel import mesh as mesh_mod
    n_replicas = int(mesh.shape[REPLICA_AXIS])
    axis = REPLICA_AXIS if n_replicas > 1 else None
    ev_spec = (P((SHARD_AXIS, REPLICA_AXIS), None) if n_replicas > 1
               else P(SHARD_AXIS, None))
    spec_lanes = P(REPLICA_AXIS, SHARD_AXIS, None)
    return mesh_mod.shard_map(
        functools.partial(serving.flush_body, axis=axis,
                          shard_axis=SHARD_AXIS),
        mesh=mesh,
        in_specs=(FlushInputs(
            dense_v=P(SHARD_AXIS, REPLICA_AXIS),
            dense_w=P(SHARD_AXIS, REPLICA_AXIS),
            minmax=P(None, SHARD_AXIS),
            hll_regs=spec_lanes,
            counter_planes=spec_lanes,
            uts_regs=P(REPLICA_AXIS, None)), P(None)),
        out_specs=FlushOutputs(
            digest_eval=ev_spec,
            counter_hi=P(SHARD_AXIS), counter_lo=P(SHARD_AXIS),
            set_regs=P(SHARD_AXIS, None), set_estimates=P(SHARD_AXIS),
            unique_ts=P()))


def make_sharded_flush_step(mesh: Mesh):
    """Build the shard_map'd multi-chip flush step over a
    (shard, replica) mesh, returning unpacked FlushOutputs (the
    compile-check / parity-test shape; production and the benches use
    make_sharded_flush_step_packed)."""
    return jax.jit(_sharded_body(mesh))


def make_sharded_flush_step_packed(mesh: Mesh, donate: bool = False):
    """The production launch shape of the sharded step: ONE flat f32
    buffer + the u8 set registers (serving.pack_outputs) — dispatch
    cost scales with output-handle count.  `donate=True` donates the
    PER-FLUSH f32 buffers (dense matrices, minmax, counter planes) the
    way the serving path does — legal only when the caller stages fresh
    buffers each flush; the register lanes (set + unique-ts) stay
    undonated, mirroring their device-resident production role."""
    body = _sharded_body(mesh)

    def run(dense_v, dense_w, minmax, counter_planes, uts_regs,
            hll_regs, pct):
        out = body(FlushInputs(
            dense_v=dense_v, dense_w=dense_w, minmax=minmax,
            hll_regs=hll_regs, counter_planes=counter_planes,
            uts_regs=uts_regs), pct)
        return serving.pack_outputs(out), out.set_regs

    jitted = jax.jit(run, donate_argnums=(0, 1, 2, 3) if donate else ())

    def step(inputs: FlushInputs, pct):
        return jitted(inputs.dense_v, inputs.dense_w, inputs.minmax,
                      inputs.counter_planes, inputs.uts_regs,
                      inputs.hll_regs, pct)

    return step


def example_depth_inputs(n_keys: int = 64, n_lanes: int = 2,
                         depth: int = 32, seed: int = 0,
                         bf16: bool = False):
    """Synthetic (dense values, per-row depth vector) pair for the
    depth-vector flush program (serving.digest_eval_uniform) — the
    production unmeshed uniform-interval launch shape: the weight matrix
    never crosses the link, occupancy is `col < depths[row]`.
    bf16=True stages the values at wire width (digest_bf16_staging), the
    shape whose sort network runs on compact 16-bit keys."""
    import numpy as np
    rng = np.random.default_rng(seed)
    k = 1 << (n_keys - 1).bit_length() if n_keys > 1 else 1
    d = n_lanes * depth
    vals = rng.gamma(2.0, 10.0, (k, d)).astype(np.float32)
    depths = np.zeros(k, np.int16)
    depths[:n_keys] = d
    vals[n_keys:] = 0.0
    dv = jnp.asarray(vals)
    if bf16:
        dv = dv.astype(jnp.bfloat16)
    return dv, jnp.asarray(depths)


def example_delta_chunks(n_keys: int = 64, depth: int = 32,
                         chunk_points: int = 1024, seed: int = 0,
                         weighted: bool = False):
    """Synthetic resident-delta stream for the scatter-assembly path
    (serving.resident_scatter*): the interval's staged COO points cut
    into fixed-size chunks of (rows, pos, vals[, wts]) exactly as
    DigestArena.stream_resident emits them — rows padded with the
    `capacity` sentinel, positions being per-row arrival ordinals — plus
    the flush-time dense_id map and the dense [U, D] matrix the host
    builder would have produced, for bit-parity checks and the
    chunk-size × nbuf sweep in scripts/profile_flush_kernel.py delta
    mode.  Returns (chunks, dense_id, expect_v, expect_w)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    cap = max(n_keys, 2 * n_keys)
    k = 1 << (n_keys - 1).bit_length() if n_keys > 1 else 1
    d = 1 << (depth - 1).bit_length() if depth > 1 else 2
    rows = rng.integers(0, n_keys, n_keys * depth).astype(np.int64)
    vals = rng.gamma(2.0, 10.0, len(rows)).astype(np.float32)
    wts = (rng.integers(1, 9, len(rows)).astype(np.float32)
           if weighted else np.ones(len(rows), np.float32))
    dense_id = np.full(cap + 1, serving._RESIDENT_DROP, np.int32)
    dense_id[:n_keys] = np.arange(n_keys, dtype=np.int32)
    expect_v = np.zeros((k, d), np.float32)
    expect_w = np.zeros((k, d), np.float32)
    cursors = np.zeros(cap, np.int64)
    chunks = []
    for lo in range(0, len(rows), chunk_points):
        cr, cv, cw = (a[lo:lo + chunk_points] for a in (rows, vals, wts))
        order = np.argsort(cr, kind="stable")
        sr, sv, sw = cr[order], cv[order], cw[order]
        pos = (cursors[sr]
               + (np.arange(len(sr)) - np.searchsorted(sr, sr)))
        cursors[sr] = pos + 1
        keep = pos < d            # overfull rows drop, like build_dense
        expect_v[sr[keep], pos[keep]] = sv[keep]
        expect_w[sr[keep], pos[keep]] = sw[keep]
        pr = np.full(chunk_points, cap, np.int32)
        pp = np.zeros(chunk_points, np.int32)
        pv = np.zeros(chunk_points, np.float32)
        pr[:len(sr)] = sr
        pp[:len(sr)] = pos
        pv[:len(sr)] = sv
        ch = {"rows": jnp.asarray(pr), "pos": jnp.asarray(pp),
              "vals": jnp.asarray(pv)}
        if weighted:
            pw = np.zeros(chunk_points, np.float32)
            pw[:len(sr)] = sw
            ch["wts"] = jnp.asarray(pw)
        chunks.append(ch)
    return chunks, jnp.asarray(dense_id), expect_v, expect_w


def example_inputs(n_keys: int = 64, n_lanes: int = 2, n_sets: int = 8,
                   depth: int = 32,
                   compression: float = td.DEFAULT_COMPRESSION,
                   hll_p: int = 10, seed: int = 0,
                   weighted: bool = False) -> FlushInputs:
    """Small synthetic inputs for compile checks and dry runs: every key
    holds `n_lanes * depth` staged weighted points (the dense depth axis
    tiles the replica mesh axis evenly).  Rows pad up to a power of two
    with zero-weight rows, exactly like the production dense builder
    (arena.py build_dense) — the padded rows are part of the honest
    workload.  weighted=True stages integer centroid weights in [1, 8]
    (the shape of re-compressed forwarded digests) instead of the
    weight-1 singletons an under-compressed incoming digest carries."""
    import numpy as np
    rng = np.random.default_rng(seed)
    m = 1 << hll_p
    r, s = n_lanes, n_sets
    k = 1 << (n_keys - 1).bit_length() if n_keys > 1 else 1
    d = r * depth

    vals = rng.gamma(2.0, 10.0, (k, d)).astype(np.float32)
    wts = np.zeros((k, d), np.float32)
    if weighted:
        wts[:n_keys] = rng.integers(1, 9, (n_keys, d)).astype(np.float32)
    else:
        wts[:n_keys] = 1.0
    minmax = np.stack([vals.min(axis=1), vals.max(axis=1)]).astype(
        np.float32)
    counters = rng.integers(0, 100, (r, k)).astype(np.float32)
    planes = np.stack(
        [np.zeros_like(counters), counters], axis=-1)  # values < 2^24
    return FlushInputs(
        dense_v=jnp.asarray(vals), dense_w=jnp.asarray(wts),
        minmax=jnp.asarray(minmax),
        hll_regs=jnp.asarray(
            rng.integers(0, 20, (r, s, m)).astype(np.uint8)),
        counter_planes=jnp.asarray(planes),
        uts_regs=jnp.asarray(
            rng.integers(0, 20, (r, m)).astype(np.uint8)))
