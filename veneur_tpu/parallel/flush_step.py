"""The sharded global-aggregation flush step — the framework's flagship
SPMD program.

One call evaluates the whole global tier's flush: staged partial sketches
from R ingest lanes are reduced into the persistent per-key state and every
key's percentiles/aggregates/cardinalities come back, with
  - t-digest reduce  = all_gather(centroids) over the replica axis +
    batched compress (the collective form of Histo.Merge,
    `samplers/samplers.go:539-543` / `worker.go:402-459`),
  - HLL reduce       = lax.pmax over replica registers,
  - counter reduce   = lax.psum,
  - unique-timeseries tally = pmax over *both* axes + estimate
    (the device analog of tallyTimeseries, `flusher.go:249-258`).

Keys are sharded over the 'shard' mesh axis, so each device only touches
its K/n_shards rows; collectives ride ICI within the replica groups.
Single-device use (entry() in __graft_entry__.py) is the same function with
a 1x1 mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from veneur_tpu.parallel import serving
from veneur_tpu.parallel.mesh import REPLICA_AXIS, SHARD_AXIS
from veneur_tpu.sketches import hll as hll_mod
from veneur_tpu.sketches import tdigest as td


class FlushInputs(NamedTuple):
    """Device-resident inputs to one global flush.

    Shapes (K = keys, R = ingest lanes/replicas, C = centroid cap,
    S = set keys, m = HLL registers, P implicit in percentiles arg):
    """
    state_mean: jax.Array      # [K, C]   persistent digest state
    state_weight: jax.Array    # [K, C]
    state_min: jax.Array       # [K]
    state_max: jax.Array       # [K]
    state_rsum: jax.Array      # [K]
    in_means: jax.Array        # [R, K, C] staged incoming digests
    in_weights: jax.Array      # [R, K, C]
    in_min: jax.Array          # [R, K]
    in_max: jax.Array          # [R, K]
    in_rsum: jax.Array         # [R, K]
    hll_regs: jax.Array        # [R, S, m] staged incoming HLL registers
    counters: jax.Array        # [R, K] staged counter partials
    uts_regs: jax.Array        # [R, m] unique-timeseries HLL partials


class FlushOutputs(NamedTuple):
    new_mean: jax.Array        # [K, C] merged digest state
    new_weight: jax.Array      # [K, C]
    new_min: jax.Array         # [K]
    new_max: jax.Array         # [K]
    new_rsum: jax.Array        # [K]
    quantiles: jax.Array       # [K, P]
    counts: jax.Array          # [K]
    sums: jax.Array            # [K]
    counter_totals: jax.Array  # [K]
    set_estimates: jax.Array   # [S]
    unique_ts: jax.Array       # [] scalar


def _local_flush(inputs: FlushInputs, percentiles: jax.Array,
                 compression: float, axis: str | None) -> FlushOutputs:
    """Per-shard flush body; `axis` names the replica mesh axis for
    collectives (None = no mesh, plain single-device math)."""
    if axis is not None:
        # Reduce staged scalar partials across the replica axis; the
        # centroid-lane gather happens inside serving.reduce_eval (the
        # shared digest-flush core used by the serving path too).
        in_min = jax.lax.pmin(jnp.min(inputs.in_min, axis=0), axis)
        in_max = jax.lax.pmax(jnp.max(inputs.in_max, axis=0), axis)
        in_rsum = jax.lax.psum(jnp.sum(inputs.in_rsum, axis=0), axis)
        hll_regs = jax.lax.pmax(jnp.max(inputs.hll_regs, axis=0), axis)
        counter_totals = jax.lax.psum(jnp.sum(inputs.counters, axis=0), axis)
        uts = jax.lax.pmax(jnp.max(inputs.uts_regs, axis=0), axis)
    else:
        in_min = jnp.min(inputs.in_min, axis=0)
        in_max = jnp.max(inputs.in_max, axis=0)
        in_rsum = jnp.sum(inputs.in_rsum, axis=0)
        hll_regs = jnp.max(inputs.hll_regs, axis=0)
        counter_totals = jnp.sum(inputs.counters, axis=0)
        uts = jnp.max(inputs.uts_regs, axis=0)

    new_min = jnp.minimum(inputs.state_min, in_min)
    new_max = jnp.maximum(inputs.state_max, in_max)
    new_rsum = inputs.state_rsum + in_rsum
    merged = serving.reduce_eval(
        inputs.in_means, inputs.in_weights,
        new_min, new_max, new_rsum,
        percentiles, compression, axis,
        state_mean=inputs.state_mean, state_weight=inputs.state_weight)

    set_est = hll_mod.estimate(hll_regs)

    if axis is not None:
        # union the unique-timeseries registers across shards too
        uts = jax.lax.pmax(uts, SHARD_AXIS)
    uts_est = hll_mod.estimate(uts[None, :])[0]

    return FlushOutputs(
        new_mean=merged.mean, new_weight=merged.weight,
        new_min=new_min, new_max=new_max, new_rsum=new_rsum,
        quantiles=merged.quantiles, counts=merged.counts, sums=merged.sums,
        counter_totals=counter_totals, set_estimates=set_est,
        unique_ts=uts_est)


@functools.partial(jax.jit, static_argnames=("compression",))
def flush_step(inputs: FlushInputs, percentiles: jax.Array,
               compression: float = td.DEFAULT_COMPRESSION) -> FlushOutputs:
    """Single-device flush step (the compile-checked entry point)."""
    return _local_flush(inputs, percentiles, compression, axis=None)


def make_sharded_flush_step(mesh: Mesh,
                            compression: float = td.DEFAULT_COMPRESSION):
    """Build the pjit'd multi-chip flush step over a (shard, replica) mesh.

    Returns a function (FlushInputs, percentiles) -> FlushOutputs whose
    inputs/outputs carry these shardings:
      state/K-arrays:      P(shard)           (key-space partition)
      staged [R, ...]:     P(replica, shard)  (lane-partitioned partials)
      uts_regs [R, m]:     P(replica)
      outputs:             P(shard) / replicated scalars
    """
    spec_k = P(SHARD_AXIS)
    spec_kc = P(SHARD_AXIS, None)
    spec_rkc = P(REPLICA_AXIS, SHARD_AXIS, None)
    spec_rk = P(REPLICA_AXIS, SHARD_AXIS)
    spec_rsm = P(REPLICA_AXIS, SHARD_AXIS, None)
    spec_rm = P(REPLICA_AXIS, None)

    in_specs = (FlushInputs(
        state_mean=spec_kc, state_weight=spec_kc,
        state_min=spec_k, state_max=spec_k, state_rsum=spec_k,
        in_means=spec_rkc, in_weights=spec_rkc,
        in_min=spec_rk, in_max=spec_rk, in_rsum=spec_rk,
        hll_regs=spec_rsm, counters=spec_rk, uts_regs=spec_rm),
        P(None))
    out_specs = FlushOutputs(
        new_mean=spec_kc, new_weight=spec_kc,
        new_min=spec_k, new_max=spec_k, new_rsum=spec_k,
        quantiles=spec_kc, counts=spec_k, sums=spec_k,
        counter_totals=spec_k, set_estimates=spec_k,
        unique_ts=P())

    def body(inputs: FlushInputs, percentiles: jax.Array) -> FlushOutputs:
        return _local_flush(inputs, percentiles, compression, REPLICA_AXIS)

    fn = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
    return jax.jit(fn)


def example_inputs(n_keys: int = 64, n_lanes: int = 2, n_sets: int = 8,
                   compression: float = td.DEFAULT_COMPRESSION,
                   hll_p: int = 10, seed: int = 0) -> FlushInputs:
    """Small synthetic inputs for compile checks and dry runs."""
    import numpy as np
    rng = np.random.default_rng(seed)
    C = td.centroid_capacity(compression)
    m = 1 << hll_p
    k, r, s = n_keys, n_lanes, n_sets

    def digest_batch(shape_prefix):
        vals = rng.gamma(2.0, 10.0, shape_prefix + (32,)).astype(np.float32)
        means = np.zeros(shape_prefix + (C,), np.float32)
        weights = np.zeros(shape_prefix + (C,), np.float32)
        means[..., :32] = np.sort(vals, axis=-1)
        weights[..., :32] = 1.0
        return means, weights, vals.min(-1), vals.max(-1), (1 / vals).sum(-1)

    sm, sw, smin, smax, srs = digest_batch((k,))
    im, iw, imin, imax, irs = digest_batch((r, k))
    return FlushInputs(
        state_mean=jnp.asarray(sm), state_weight=jnp.asarray(sw),
        state_min=jnp.asarray(smin), state_max=jnp.asarray(smax),
        state_rsum=jnp.asarray(srs),
        in_means=jnp.asarray(im), in_weights=jnp.asarray(iw),
        in_min=jnp.asarray(imin), in_max=jnp.asarray(imax),
        in_rsum=jnp.asarray(irs),
        hll_regs=jnp.asarray(
            rng.integers(0, 20, (r, s, m)).astype(np.uint8)),
        counters=jnp.asarray(
            rng.integers(0, 100, (r, k)).astype(np.float32)),
        uts_regs=jnp.asarray(
            rng.integers(0, 20, (r, m)).astype(np.uint8)))
