"""The serving-path SPMD programs: lane ingest + the per-flush family reduce.

This wires the sharded flush (veneur_tpu/parallel/flush_step.py) into the
*production* aggregation tier: `DigestArena` keeps its centroid state as
lane-striped device tensors `[R, K, C]` (R ingest lanes x K keys x C
centroid slots), `SetArena` keeps its HLL registers as `[R_s, S, m]`
lane-striped uint8 tensors, both sharded over a (shard, replica) `Mesh`
when one is configured —

  - the **shard** axis partitions the key space K (the device analog of the
    reference's fnv1a-hash worker sharding, `server.go:997-1011` /
    `worker.go:34-50`, and of the proxy's consistent-hash ring);
  - the **replica** axis partitions the R ingest lanes, so each replica
    group accumulates a subset of lanes' partial digests and the flush
    reduces them with an `all_gather` over ICI followed by one batched
    compress — the collective form of the gRPC ImportMetric merge loop
    (`worker.go:402-459`).

The programs:

  * `lane_ingest`   — fold one dense sample wave `[K, W]` into lane r of the
                      striped state (the device half of `DigestArena.sync`).
                      Striping waves across lanes both feeds the replica
                      axis and cuts the sequential kernel-launch depth for a
                      hot key by R (each lane's chain is independent).
  * `set_lane_scatter` / `set_lane_merge_rows` — scatter-max staged HLL
                      (row, register, rank) updates / imported register rows
                      into lane r of the set state (Sketch.Insert / Merge,
                      `samplers/samplers.go:242-244,299-311`).
  * `make_family_flush` — build the per-flush evaluation for EVERY sampler
                      family in one program: gather digest lanes over the
                      replica axis and merge+evaluate percentiles, pmax the
                      HLL set lanes and estimate cardinalities, psum the
                      hi/lo counter planes, and estimate the
                      unique-timeseries HLL (tallyTimeseries,
                      `flusher.go:249-258`).  With `mesh=None` this is the
                      same math under plain `jit` on the default device, so
                      single-chip and multi-chip serving share one code
                      path.
  * `reset_rows` / `set_reset_rows` — zero the touched rows across every
                      lane after flush (the map-swap of `worker.go:462-481`;
                      rows persist, state is interval-scoped).

Counters ride as two float32 planes (hi, lo) with value = hi * 2^24 + lo:
each plane is integer-exact below 2^24, so the psum'd total is exact below
2^48 without relying on x64 mode — int64 counter semantics
(`samplers/samplers.go:97-150`) on an f32-native device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veneur_tpu.parallel.mesh import REPLICA_AXIS, SHARD_AXIS
from veneur_tpu.sketches import hll as hll_mod
from veneur_tpu.sketches import tdigest as td

# counters travel as (hi, lo) f32 planes: value = hi * COUNTER_SPLIT + lo,
# each plane integer-exact below 2^24 => totals exact below 2^48
COUNTER_SPLIT = float(1 << 24)


class ServingFlushOutputs(NamedTuple):
    mean: jax.Array       # [K, C] merged centroids (forwarding export)
    weight: jax.Array     # [K, C]
    quantiles: jax.Array  # [K, P]
    counts: jax.Array     # [K] total weight
    sums: jax.Array       # [K] weighted sum


class FamilyFlushOutputs(NamedTuple):
    """One production flush, every sampler family reduced on device."""
    mean: jax.Array           # [K, C] merged centroids (forwarding export)
    weight: jax.Array         # [K, C]
    quantiles: jax.Array      # [K, P]
    counts: jax.Array         # [K] total digest weight
    sums: jax.Array           # [K] weighted sum
    set_regs: jax.Array       # [S, m] uint8 merged HLL registers
    set_estimates: jax.Array  # [S] f32 cardinality estimates
    counter_hi: jax.Array     # [K2] f32 psum'd high counter plane
    counter_lo: jax.Array     # [K2] f32 psum'd low counter plane
    unique_ts: jax.Array      # [] f32 distinct-timeseries estimate


# ---------------------------------------------------------------------------
# Shardings
# ---------------------------------------------------------------------------

def lane_sharding(mesh: Optional[Mesh]):
    """[R, K, C] lane-striped state: lanes over 'replica', keys over
    'shard'."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(REPLICA_AXIS, SHARD_AXIS, None))


def row_sharding(mesh: Optional[Mesh], ndim: int = 1):
    """[K, ...] per-key arrays: keys over 'shard'."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(SHARD_AXIS, *([None] * (ndim - 1))))


def put(x, sharding):
    x = jnp.asarray(x)
    return x if sharding is None else jax.device_put(x, sharding)


# ---------------------------------------------------------------------------
# Lane ingest
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("lane", "compression"),
                   donate_argnums=(0, 1))
def lane_ingest(lanes_mean: jax.Array, lanes_weight: jax.Array,
                values: jax.Array, vweights: jax.Array,
                lane: int, compression: float
                ) -> tuple[jax.Array, jax.Array]:
    """Fold a dense sample wave `[K, W]` into lane `lane` of `[R, K, C]`.

    Device half of `MergingDigest.Add`/`mergeAllTemps`
    (`merging_digest.go:115-224`) batched over all keys; min/max/rsum are
    tracked host-side by the arena (they are authoritative there — see
    DigestArena docstring) so only centroids live here.
    """
    cap = lanes_mean.shape[2]
    cat_m = jnp.concatenate([lanes_mean[lane], values], axis=1)
    cat_w = jnp.concatenate([lanes_weight[lane], vweights], axis=1)
    nm, nw = td.compress(cat_m, cat_w, compression, cap)
    return lanes_mean.at[lane].set(nm), lanes_weight.at[lane].set(nw)


@functools.partial(jax.jit, static_argnames=("compression", "cap"))
def partial_digests(dense_v: jax.Array, dense_w: jax.Array,
                    compression: float, cap: int
                    ) -> tuple[jax.Array, jax.Array]:
    """One batched compress of a dense `[U, W]` sample matrix into per-row
    partial digests `[U, cap]` — stage 1 of the hot-key ingest path (the
    tree form of `mergeAllTemps`: any W collapses in a single launch
    instead of a W/wave-width sequential chain)."""
    return td.compress(dense_v, dense_w, compression, cap)


@jax.jit
def reset_rows(lanes_mean: jax.Array, lanes_weight: jax.Array,
               rows: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Zero the given key rows in every lane.  NOT donating: the flush
    snapshot may still reference the pre-reset buffers while emission runs
    outside the aggregator lock."""
    return (lanes_mean.at[:, rows].set(0.0),
            lanes_weight.at[:, rows].set(0.0))


# ---------------------------------------------------------------------------
# Set (HLL) lane ingest
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("lane",), donate_argnums=(0,))
def set_lane_scatter(lanes_regs: jax.Array, rows: jax.Array,
                     idx: jax.Array, rank: jax.Array,
                     lane: int) -> jax.Array:
    """Scatter-max staged (set row, register index, rank) triples into lane
    `lane` of the `[R_s, S, m]` register state — the device half of
    Sketch.Insert (`samplers/samplers.go:242-244`).  Padding entries with
    rank 0 are no-ops (max against an empty register)."""
    return lanes_regs.at[lane, rows, idx].max(rank)


@functools.partial(jax.jit, static_argnames=("lane",), donate_argnums=(0,))
def set_lane_merge_rows(lanes_regs: jax.Array, rows: jax.Array,
                        regmat: jax.Array, lane: int) -> jax.Array:
    """Register-wise max of imported full register rows `[n, m]` into lane
    `lane` (Set.Merge, `samplers/samplers.go:299-311`).  All-zero padding
    rows are no-ops."""
    return lanes_regs.at[lane, rows].max(regmat)


@jax.jit
def set_reset_rows(lanes_regs: jax.Array, rows: jax.Array) -> jax.Array:
    """Zero the given set rows in every lane (NOT donating — see
    reset_rows)."""
    return lanes_regs.at[:, rows].set(0)


# ---------------------------------------------------------------------------
# Flush evaluation
# ---------------------------------------------------------------------------

def reduce_eval(lanes_mean, lanes_weight, d_min, d_max, d_rsum,
                percentiles, compression, replica_axis,
                state_mean=None, state_weight=None) -> ServingFlushOutputs:
    """THE digest-flush core, shared by the serving path and the benchmark
    flush_step: all_gather lanes over the replica axis -> one batched
    compress (optionally folding a persistent [K, C] state in) -> evaluate
    quantiles/counts/sums for every key at once.

    `replica_axis` names the mesh axis to gather over (None = single
    device).  The merged min/max/rsum come from the caller's authoritative
    scalars (re-ingested centroid means never reach the true extremes —
    `worker.go:402-459` semantics); pass zeros for rsum if the caller
    tracks it host-side (no device computation consumes it).
    """
    if replica_axis is not None:
        lanes_mean = jax.lax.all_gather(
            lanes_mean, replica_axis, axis=0, tiled=True)
        lanes_weight = jax.lax.all_gather(
            lanes_weight, replica_axis, axis=0, tiled=True)
    k = lanes_mean.shape[1]
    cap = lanes_mean.shape[2]
    flat_m = jnp.transpose(lanes_mean, (1, 0, 2)).reshape(k, -1)
    flat_w = jnp.transpose(lanes_weight, (1, 0, 2)).reshape(k, -1)
    if state_mean is not None:
        flat_m = jnp.concatenate([state_mean, flat_m], axis=1)
        flat_w = jnp.concatenate([state_weight, flat_w], axis=1)
    mm, mw = td.compress(flat_m, flat_w, compression, cap)
    merged = td.TDigestState(mean=mm, weight=mw,
                             min=d_min, max=d_max, rsum=d_rsum)
    return ServingFlushOutputs(
        mean=mm, weight=mw,
        quantiles=td.quantile(merged, percentiles),
        counts=td.total_weight(merged),
        sums=td.sum_values(merged))


def make_family_flush(mesh: Optional[Mesh],
                      compression: float = td.DEFAULT_COMPRESSION):
    """Build the per-flush program covering every sampler family.

    Returns fn(lanes_mean [R,K,C], lanes_weight, d_minmax [2,K] (min;max,
    one upload), percentiles [P], set_lanes [R_s,S,m] u8, counter_planes
    [R_c,K2,2] f32, uts_regs [m_u] u8) -> FamilyFlushOutputs.  With a mesh, the function is
    a shard_map'd SPMD program: keys/set rows/counter rows are sharded over
    'shard'; digest lanes all_gather, set lanes pmax, and counter planes
    psum over 'replica'; the unique-timeseries registers pmax over both
    axes (they are replicated within a process, so in-process this is an
    identity — across processes it is the DCN union of per-host tallies).
    Without a mesh, the identical math runs under plain jit.  Digest rsum
    stays host-side (hmean is emitted from host scalars; no device
    computation needs it).
    """
    def body_for(axis):
        def body(lanes_mean, lanes_weight, d_minmax, percentiles,
                 set_lanes, counter_planes, uts_regs):
            d_min, d_max = d_minmax[0], d_minmax[1]
            dig = reduce_eval(lanes_mean, lanes_weight, d_min, d_max,
                              jnp.zeros_like(d_min), percentiles,
                              compression, axis)
            set_regs = jnp.max(set_lanes, axis=0)
            chi = jnp.sum(counter_planes[..., 0], axis=0)
            clo = jnp.sum(counter_planes[..., 1], axis=0)
            uts = uts_regs
            if axis is not None:
                set_regs = jax.lax.pmax(set_regs, axis)
                chi = jax.lax.psum(chi, axis)
                clo = jax.lax.psum(clo, axis)
                uts = jax.lax.pmax(jax.lax.pmax(uts, axis), SHARD_AXIS)
            return FamilyFlushOutputs(
                mean=dig.mean, weight=dig.weight, quantiles=dig.quantiles,
                counts=dig.counts, sums=dig.sums,
                set_regs=set_regs,
                set_estimates=hll_mod.estimate(set_regs),
                counter_hi=chi, counter_lo=clo,
                unique_ts=hll_mod.estimate(uts[None, :])[0])
        return body

    if mesh is None:
        return jax.jit(body_for(None))

    spec_lanes = P(REPLICA_AXIS, SHARD_AXIS, None)
    spec_k = P(SHARD_AXIS)
    spec_kc = P(SHARD_AXIS, None)
    fn = jax.shard_map(
        body_for(REPLICA_AXIS), mesh=mesh,
        in_specs=(spec_lanes, spec_lanes, P(None, SHARD_AXIS), P(None),
                  spec_lanes, spec_lanes, P(None)),
        out_specs=FamilyFlushOutputs(
            mean=spec_kc, weight=spec_kc, quantiles=spec_kc,
            counts=spec_k, sums=spec_k,
            set_regs=spec_kc, set_estimates=spec_k,
            counter_hi=spec_k, counter_lo=spec_k,
            unique_ts=P()),
        check_vma=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Flush readback packing
# ---------------------------------------------------------------------------
#
# The host needs a small, fixed set of per-touched-row values out of each
# flush (quantiles/counts/sums per digest row, hi/lo per counter row,
# estimates per set row, the unique-ts scalar).  Reading them with eager
# per-family gathers costs one device round-trip + one tiled-layout
# transfer EACH; over a remote device link those round-trips dominate the
# whole flush.  `flush_pack` gathers every family's touched rows inside
# one jitted program and returns ONE flat f32 vector, so the host pays a
# single linear-layout transfer per flush regardless of family count.
# Row index arrays are padded to powers of two by the caller (row 0
# repeated; the padding lanes are sliced off after unpack) to bound the
# jit cache.

@jax.jit
def flush_pack(quantiles: jax.Array, counts: jax.Array, sums: jax.Array,
               counter_hi: jax.Array, counter_lo: jax.Array,
               set_estimates: jax.Array, unique_ts: jax.Array,
               drows: jax.Array, crows: jax.Array, srows: jax.Array
               ) -> jax.Array:
    return jnp.concatenate([
        quantiles[drows].reshape(-1),
        counts[drows], sums[drows],
        counter_hi[crows], counter_lo[crows],
        set_estimates[srows],
        unique_ts[None].astype(jnp.float32),
    ])


@jax.jit
def forward_pack(mean: jax.Array, weight: jax.Array, rows: jax.Array
                 ) -> jax.Array:
    """Flat [2 * n * C] f32 readback of merged centroids for the rows a
    local tier forwards (ForwardableMetrics, `worker.go:179-216`)."""
    return jnp.concatenate([mean[rows].reshape(-1),
                            weight[rows].reshape(-1)])


@jax.jit
def set_regs_pack(set_regs: jax.Array, rows: jax.Array) -> jax.Array:
    """Flat [n * m] u8 readback of merged HLL registers for forwarding
    (Set.Metric marshal, `samplers/samplers.go:279-295`)."""
    return set_regs[rows].reshape(-1)
