"""The serving-path SPMD programs: the per-flush family evaluation.

This wires the sharded flush into the *production* aggregation tier.  The
digest pipeline is **stateless on device**: every interval's samples (and
imported digest centroids, which are just weighted points) stage host-side
in `DigestArena`, and one program per flush evaluates the whole tier —

  - the **shard** mesh axis partitions the touched-key space (the device
    analog of the reference's fnv1a-hash worker sharding,
    `server.go:997-1011` / `worker.go:34-50`, and of the proxy's
    consistent-hash ring);
  - the **replica** axis partitions the sample depth `D`: each replica
    group holds a slice of every key's staged points, and the flush
    all_gathers the slices over ICI before one batched sorted evaluation —
    the collective form of the gRPC ImportMetric merge loop
    (`worker.go:402-459`).

Per-flush device traffic is minimal by design: upload = the interval's
staged points (`[K, D]`, proportional to samples), download = one
`[K, P+2]` evaluation matrix.  No persistent centroid state is rewritten
per flush — t-digest *compression* runs only where the sketch must stay
bounded: forwarding export (`digest_export`) and hot-key
pre-reduction (`partial_digests`), both of which return slim arrays.

Sets (HLL registers) and counters keep device-resident lane state only
when a mesh is configured (the registers then pmax over 'replica' and the
counter hi/lo planes psum); without a mesh both families resolve on host
(see core/arena.py) and the program evaluates digests only.

Counters ride as two float32 planes (hi, lo) with value = hi * 2^24 + lo:
each plane is integer-exact below 2^24, so the psum'd total is exact below
2^48 without relying on x64 mode — int64 counter semantics
(`samplers/samplers.go:97-150`) on an f32-native device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veneur_tpu.parallel.mesh import REPLICA_AXIS, SHARD_AXIS
from veneur_tpu.sketches import hll as hll_mod
from veneur_tpu.sketches import tdigest as td

# counters travel as (hi, lo) f32 planes: value = hi * COUNTER_SPLIT + lo,
# each plane integer-exact below 2^24 => totals exact below 2^48
COUNTER_SPLIT = float(1 << 24)


class FlushInputs(NamedTuple):
    """Device inputs to one full flush (shapes: K touched digest keys
    padded pow2, D staged depth padded pow2, R replica lanes, S set rows,
    m HLL registers, K2 counter rows)."""
    dense_v: jax.Array        # [K, D] f32 staged values / centroid means
    dense_w: jax.Array        # [K, D] f32 weights (0 = empty cell)
    minmax: jax.Array         # [2, K] f32 authoritative min;max
    hll_regs: jax.Array       # [R, S, m] u8 set register lanes
    counter_planes: jax.Array  # [R, K2, 2] f32 (hi, lo)
    uts_regs: jax.Array       # [R, m_u] u8 unique-timeseries registers


class FlushOutputs(NamedTuple):
    digest_eval: jax.Array    # [K, P+2]: P quantiles, total weight, sum
    counter_hi: jax.Array     # [K2]
    counter_lo: jax.Array     # [K2]
    set_regs: jax.Array       # [S, m] u8 merged registers (forwarding)
    set_estimates: jax.Array  # [S] f32
    unique_ts: jax.Array      # [] f32


# ---------------------------------------------------------------------------
# Shardings
# ---------------------------------------------------------------------------

def mesh_device_count(mesh: Optional[Mesh]) -> int:
    """Devices a flush program runs over: 1 unmeshed, else the full
    (shard x replica) grid.  The flush-timeline records carry this so a
    live server's segment decomposition is comparable across mesh
    reconfigurations (the bench's mesh-scaling curve, observable in
    production)."""
    return 1 if mesh is None else int(mesh.size)


def lane_sharding(mesh: Optional[Mesh]):
    """[R, K, ...] lane-striped state: lanes over 'replica', keys over
    'shard'."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(REPLICA_AXIS, SHARD_AXIS, None))


def dense_sharding(mesh: Optional[Mesh]):
    """[K, D] staged sample matrices: keys over 'shard', depth over
    'replica' (the replica groups each evaluate a sample slice)."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(SHARD_AXIS, REPLICA_AXIS))


def minmax_sharding(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P(None, SHARD_AXIS))


def put(x, sharding):
    """Host array -> (sharded) device array.

    Multi-controller runs (jax.distributed, multihost.py) construct the
    global array from each process's view via make_array_from_callback:
    every process supplies the slices its devices own, so per-process
    staging lands on the shards that process is responsible for — the
    key-ownership model of the proxy ring (`destinations.go:129-142`)
    carried onto the device mesh."""
    if sharding is None:
        return jnp.asarray(x)
    if jax.process_count() > 1:
        import numpy as _np
        arr = _np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])
    return jax.device_put(x, sharding)


def place_dense_blocks(mesh: Mesh, dv, dw, minmax,
                       dense_shd: NamedSharding,
                       mm_shd: NamedSharding):
    """Pre-sharded staging of the dense build: every device's blocks —
    its row block of each shard (the dense builder's row order IS shard
    order) and its depth slice — are placed DIRECTLY on their owning
    device with one batched jax.device_put, then assembled with
    make_array_from_single_device_arrays.  The mesh program consumes
    already-resident shards instead of re-laying-out one process-wide
    host matrix on entry, and the per-device transfers overlap on real
    hardware.  Shared by DigestArena.put_dense_sharded (production) and
    scripts/bench_mesh_scaling.py (so the bench times the REAL staging
    path, not a copy of it).  minmax is key-sharded, replica-replicated:
    every replica gets its shard's columns."""
    from jax.sharding import SingleDeviceSharding
    S = int(mesh.shape[SHARD_AXIS])
    R = int(mesh.shape[REPLICA_AXIS])
    ps, dr = dv.shape[0] // S, dv.shape[1] // R
    devs = mesh.devices  # [S, R] device grid
    blocks: list = []
    tgts: list = []
    for s in range(S):
        for r in range(R):
            dev = SingleDeviceSharding(devs[s][r])
            blocks.append(dv[s * ps:(s + 1) * ps, r * dr:(r + 1) * dr])
            tgts.append(dev)
            blocks.append(dw[s * ps:(s + 1) * ps, r * dr:(r + 1) * dr])
            tgts.append(dev)
            blocks.append(minmax[:, s * ps:(s + 1) * ps])
            tgts.append(dev)
    arrs = jax.device_put(blocks, tgts)
    asm = jax.make_array_from_single_device_arrays
    return (asm(dv.shape, dense_shd, arrs[0::3]),
            asm(dw.shape, dense_shd, arrs[1::3]),
            asm(minmax.shape, mm_shd, arrs[2::3]))


def fetch(x):
    """Device array (or pytree of arrays) -> host numpy.  Multi-controller:
    ONE process_allgather over DCN for the whole tree (callers batch every
    readback of a flush into a single fetch so each flush pays one
    cross-process barrier, not one per family)."""
    import numpy as _np
    if jax.process_count() > 1:
        leaves = jax.tree_util.tree_leaves(x)
        if leaves and not all(l.is_fully_addressable for l in leaves):
            from jax.experimental import multihost_utils
            return multihost_utils.process_allgather(x, tiled=True)
    return jax.tree_util.tree_map(_np.asarray, x)


# ---------------------------------------------------------------------------
# Flush body (shared by the serving path and the bench's flush_step)
# ---------------------------------------------------------------------------

def pallas_eval_applies(u: int, d: int, dtype=jnp.float32) -> bool:
    """True when digest_eval will route this shape to the fused Pallas
    kernel (where the uniform/general network choice is a DISTINCT
    program).  Callers normalize their `uniform` flag with this so the
    XLA-twin fallback never compiles two identical programs under two
    static keys.  bf16 staging takes the Pallas path too: the compact
    packed-key network at shallow depths, the f32 paired network on
    in-kernel-widened values otherwise."""
    import os

    from veneur_tpu.ops import sorted_eval as se
    return (not os.environ.get("VENEUR_TPU_DISABLE_PALLAS_EVAL")
            and dtype in (jnp.float32, jnp.bfloat16)
            and se.usable(u, d, jax.default_backend()))


def digest_eval(dv: jax.Array, dw: jax.Array, d_min: jax.Array,
                d_max: jax.Array, percentiles: jax.Array,
                uniform: bool = False) -> jax.Array:
    """The flush's evaluation core, routed to the fused Pallas kernel
    (ops/sorted_eval.py: in-VMEM bitonic sort + MXU prefix sums) when the
    backend and static shapes allow, else the XLA formulation — bitwise
    parity between the two is test-enforced.  `uniform` (static) selects
    the key-only sort network, legal when every nonzero staged weight is
    exactly 1 (tracked per interval by the dense builder).

    bf16-staged dense values (the arena's compact_general staging) keep
    their wire width into the kernel where the compact packed-key
    network applies (usable_compact: the value-exactness half of the
    gate is the bf16 dtype itself — every staged value IS
    bf16-representable by construction); deeper bf16 shapes widen
    in-kernel and run the f32 paired network.

    VENEUR_TPU_DISABLE_PALLAS_EVAL is read at TRACE time (the choice is
    baked into each compiled program): set it before process start."""
    import os

    from veneur_tpu.ops import sorted_eval as se
    u, d = dv.shape
    backend = jax.default_backend()
    if (not os.environ.get("VENEUR_TPU_DISABLE_PALLAS_EVAL")
            and dv.dtype in (jnp.float32, jnp.bfloat16)  # f64 -> twin
            and se.usable(u, d, backend)):
        if uniform:
            # the key-only network beats the compact one (~1.8x: no
            # payload, no prefix-sum) and sorts bf16 keys natively —
            # checked FIRST so bf16 uniform intervals never pay the
            # packed network's permutation-apply
            return se.weighted_eval(dv, dw, d_min, d_max, percentiles,
                                    uniform=True)
        if (dv.dtype == jnp.bfloat16
                and se.usable_compact(u, d, backend)):
            return se.weighted_eval(dv, dw, d_min, d_max, percentiles,
                                    compact=True)
        # bf16 stays bf16 into the kernel here too: the paired network
        # widens in-register, so no f32 copy ever lands in HBM
        return se.weighted_eval(dv, dw, d_min, d_max, percentiles)
    return td.weighted_eval(dv, dw, d_min, d_max, percentiles)


def digest_eval_uniform(dv: jax.Array, depths: jax.Array,
                        percentiles: jax.Array) -> jax.Array:
    """Depth-vector evaluation for uniform (all-weight-1) intervals ->
    `[U, P]` quantiles only: the weight matrix never uploads (occupancy
    is `col < depths[row]`), no minmax operand (each staged point is a
    true sample, so interpolation cannot leave the data range), and the
    totals come from host accumulators instead of the readback.  Routes
    to the fused Pallas depth kernel when shapes allow, else
    reconstructs the 0/1 weights and the row ranges ON DEVICE (free
    next to uploading them) and runs the XLA twin."""
    import os

    from veneur_tpu.ops import sorted_eval as se
    u, d = dv.shape
    n_pct = percentiles.shape[0]
    if (not os.environ.get("VENEUR_TPU_DISABLE_PALLAS_EVAL")
            and dv.dtype in (jnp.float32, jnp.bfloat16)
            and se.usable(u, d, jax.default_backend())):
        return se.uniform_eval(dv, depths, percentiles)
    # XLA-twin fallback: widen narrow staging, keep f64 as f64
    dt = jnp.float64 if dv.dtype == jnp.float64 else jnp.float32
    dv = dv.astype(dt)
    dw = (jnp.arange(d, dtype=jnp.int32)[None, :]
          < depths[:, None].astype(jnp.int32)).astype(dt)
    occ = dw > 0
    d_min = jnp.where(depths > 0,
                      jnp.where(occ, dv, jnp.inf).min(axis=1), 0.0)
    d_max = jnp.where(depths > 0,
                      jnp.where(occ, dv, -jnp.inf).max(axis=1), 0.0)
    return td.weighted_eval(dv, dw, d_min.astype(dt),
                            d_max.astype(dt),
                            percentiles)[:, :n_pct]


def flush_body(inputs: FlushInputs, percentiles: jax.Array,
               axis: Optional[str],
               uniform: bool = False,
               shard_axis: Optional[str] = None) -> FlushOutputs:
    """Evaluate every family for one flush.

    `axis` names the replica mesh axis for cross-replica collectives;
    None means the replica axis has size 1 (or no mesh at all) and the
    math is identical with every collective elided at TRACE time — the
    axis-size-1 specialization that keeps the mesh=1 wrapper overhead at
    dispatch cost only.  `shard_axis` names the shard axis when meshed
    (the unique-timeseries union must span it even when R == 1).

    The digest repartition is an **all_to_all**, not an all_gather: each
    replica group re-splits its key rows over the replicas while
    concatenating the depth slices, so every device evaluates
    K_s/R keys at FULL depth.  The old all_gather form materialized all
    K_s keys at full depth on EVERY replica — R× the eval work and R×
    the collective bytes for identical output (t-digest mergeability,
    arxiv 1902.04023, is what makes any per-shard split legal; the
    quantile evaluation itself is row-local either way)."""
    dv, dw = inputs.dense_v, inputs.dense_w
    if axis is not None and dv.dtype != dw.dtype:
        # the stacked all_to_all needs one dtype; bf16 staging is an
        # unmeshed option (arena.compact_general), so this only guards
        # hand-built inputs
        dv = dv.astype(dw.dtype)
    if axis is not None:
        # repartition [K_s, D/R] -> [K_s/R, D]: split keys, concat depth.
        # BOTH matrices ride ONE all_to_all (stacked on a leading axis):
        # every collective is a cross-device rendezvous, and the flush's
        # wall-clock overhead scales with rendezvous count, not bytes —
        # the stack copy is plain HBM traffic the combiner pays anyway.
        both = jax.lax.all_to_all(jnp.stack([dv, dw]), axis,
                                  split_axis=1, concat_axis=2,
                                  tiled=True)
        dv, dw = both[0], both[1]
        # this replica's key sub-block of the (replica-replicated) minmax
        j = jax.lax.axis_index(axis)
        mm = jax.lax.dynamic_slice_in_dim(
            inputs.minmax, j * dv.shape[0], dv.shape[0], axis=1)
    else:
        mm = inputs.minmax
    ev = digest_eval(dv, dw, mm[0], mm[1], percentiles, uniform=uniform)

    set_regs = jnp.max(inputs.hll_regs, axis=0)
    planes = jnp.sum(inputs.counter_planes, axis=0)   # [K2_s, 2]
    uts = jnp.max(inputs.uts_regs, axis=0)
    if axis is not None:
        # one psum for both counter planes, one u8 pmax for both
        # register families (same rendezvous-count argument as above)
        planes = jax.lax.psum(planes, axis)
        n_set = set_regs.size
        regs = jax.lax.pmax(
            jnp.concatenate([set_regs.ravel(), uts]), axis)
        set_regs = regs[:n_set].reshape(set_regs.shape)
        uts = regs[n_set:]
    chi, clo = planes[..., 0], planes[..., 1]
    if shard_axis is not None:
        uts = jax.lax.pmax(uts, shard_axis)
    return FlushOutputs(
        digest_eval=ev, counter_hi=chi, counter_lo=clo,
        set_regs=set_regs, set_estimates=hll_mod.estimate(set_regs),
        unique_ts=hll_mod.estimate(uts[None, :])[0])


def pack_outputs(out: FlushOutputs) -> jax.Array:
    """Flatten every f32-representable flush output into ONE device
    buffer.  Per-launch dispatch cost scales with the number of output
    buffer handles (measured ~0.1 ms/handle on a congested link — see
    BASELINE.md), so the production program hands the host one flat
    vector to slice instead of six arrays; `set_regs` stays separate
    (u8, 4x the bytes as f32, and only consumed when a local tier
    forwards mixed-scope sets)."""
    return jnp.concatenate([
        out.digest_eval.ravel(), out.counter_hi, out.counter_lo,
        out.set_estimates, out.unique_ts[None]])


def unpack_outputs(flat, k: int, n_pct: int, k2: int, s: int):
    """Host-side views into a fetched pack_outputs vector: returns
    (digest_eval [k, n_pct+2], counter_hi [k2], counter_lo [k2],
    set_estimates [s], unique_ts scalar)."""
    ne = k * (n_pct + 2)
    ev = flat[:ne].reshape(k, n_pct + 2)
    chi = flat[ne:ne + k2]
    clo = flat[ne + k2:ne + 2 * k2]
    est = flat[ne + 2 * k2:ne + 2 * k2 + s]
    return ev, chi, clo, est, float(flat[ne + 2 * k2 + s])


def make_serving_flush(mesh: Optional[Mesh]):
    """Build the per-flush program.

    Without a mesh, returns fn(dense_v, dense_w, minmax, percentiles) ->
    [K, P+2] — digests only, because sets/counters/unique-ts resolve on
    host when there is nothing to reduce over (core/arena.py).

    With a mesh, returns the shard_map'd full-family program
    fn(FlushInputs, percentiles, uniform=False, donate=False) ->
    (packed_f32, set_regs_u8): keys and set/counter rows shard over
    'shard'; staged sample depth repartitions over 'replica' with ONE
    all_to_all (each device evaluates K_s/R keys at full depth — no
    redundant replica evaluation), set register lanes and counter planes
    reduce over 'replica' (pmax / psum); the unique-timeseries registers
    pmax over both axes (across processes this is the DCN union of
    per-host tallies).  When the replica axis has size 1 every
    collective is elided at trace time, so the mesh=1 program is the
    single-device program plus wrapper dispatch only.  The f32 outputs
    come back as ONE flat buffer (pack_outputs; unpack with
    unpack_outputs) — per-launch dispatch cost scales with
    output-handle count, so the production flush hands the host two
    buffers, not six.  `donate=True` (static) donates the PER-FLUSH f32
    input buffers — the staged dense matrices, minmax and counter
    planes — killing XLA's copy-on-entry; the u8 unique-ts registers
    (fresh each flush but with no aliasable u8 output) and the live
    set-register lanes (arena state that must survive the call) are
    never donated.  Donate only when the caller will not touch the
    staged buffers again (a forwarding tier re-reads the dense matrices
    for digest export).  On CPU the donations are reported unusable at
    compile (one UserWarning per shape — no f32 output matches the
    staged buffers' layouts); they stay marked for the TPU backend,
    where XLA reuses the donated HBM as scratch.
    """
    if mesh is None:
        @functools.partial(jax.jit, static_argnames=("uniform",))
        def general(dv, dw, minmax, pct, uniform=False):
            return digest_eval(dv, dw, minmax[0], minmax[1], pct,
                               uniform=uniform)

        general_d = jax.jit(
            lambda dv, dw, minmax, pct, uniform=False: digest_eval(
                dv, dw, minmax[0], minmax[1], pct, uniform=uniform),
            static_argnames=("uniform",), donate_argnums=(0, 1, 2))

        @jax.jit
        def depth_variant(dv, depths, pct):
            return digest_eval_uniform(dv, depths, pct)

        # the int16 depth vector stays undonated: no int16 output
        # exists to alias it into, and jax warns on unusable donations
        depth_variant_d = jax.jit(
            lambda dv, depths, pct: digest_eval_uniform(dv, depths, pct),
            donate_argnums=(0,))

        def unmeshed(dv, dw, minmax, pct, uniform=False, donate=False):
            fn = general_d if donate else general
            return fn(dv, dw, minmax, pct, uniform=uniform)

        unmeshed.lower = general.lower
        unmeshed.lower_donated = general_d.lower
        # uniform intervals upload (values, per-row depths) instead of
        # (values, weights) — half the bytes; the aggregator routes
        # there whenever DigestArena.staged_uniform held
        unmeshed.depth_variant = depth_variant
        unmeshed.depth_variant_donated = depth_variant_d
        return unmeshed

    n_replicas = int(mesh.shape[REPLICA_AXIS])
    axis = REPLICA_AXIS if n_replicas > 1 else None
    spec_lanes = P(REPLICA_AXIS, SHARD_AXIS, None)
    # with the all_to_all repartition the evaluation rows shard over
    # BOTH axes (shard-major, replica-minor — exactly the dense build's
    # row order); at R == 1 nothing repartitions
    ev_spec = (P((SHARD_AXIS, REPLICA_AXIS), None) if n_replicas > 1
               else P(SHARD_AXIS, None))
    progs: dict = {}

    def _prog(uniform: bool, donate: bool):
        prog = progs.get((uniform, donate))
        if prog is None:
            from veneur_tpu.parallel import mesh as mesh_mod
            fn = mesh_mod.shard_map(
                functools.partial(flush_body, axis=axis,
                                  shard_axis=SHARD_AXIS,
                                  uniform=uniform),
                mesh=mesh,
                in_specs=(FlushInputs(
                    dense_v=P(SHARD_AXIS, REPLICA_AXIS),
                    dense_w=P(SHARD_AXIS, REPLICA_AXIS),
                    minmax=P(None, SHARD_AXIS),
                    hll_regs=spec_lanes,
                    counter_planes=spec_lanes,
                    uts_regs=P(REPLICA_AXIS, None)), P(None)),
                out_specs=FlushOutputs(
                    digest_eval=ev_spec,
                    counter_hi=P(SHARD_AXIS), counter_lo=P(SHARD_AXIS),
                    set_regs=P(SHARD_AXIS, None),
                    set_estimates=P(SHARD_AXIS),
                    unique_ts=P()))

            # leaf-splayed signature: jit donation is per-argument, and
            # the live set registers (hll_regs) must NOT be donated —
            # so the per-flush buffers travel as the leading arguments
            def run(dense_v, dense_w, minmax, counter_planes, uts_regs,
                    hll_regs, pct):
                out = fn(FlushInputs(
                    dense_v=dense_v, dense_w=dense_w, minmax=minmax,
                    hll_regs=hll_regs, counter_planes=counter_planes,
                    uts_regs=uts_regs), pct)
                return pack_outputs(out), out.set_regs

            # donate the f32 per-flush buffers only: the u8 unique-ts
            # registers are tiny and have no aliasable u8 output (jax
            # warns on unusable donations), and the live set-register
            # lanes must survive the call
            prog = progs[(uniform, donate)] = jax.jit(
                run, donate_argnums=(0, 1, 2, 3) if donate else ())
        return prog

    def _splay(inputs):
        return (inputs.dense_v, inputs.dense_w, inputs.minmax,
                inputs.counter_planes, inputs.uts_regs, inputs.hll_regs)

    def meshed(inputs, pct, uniform=False, donate=False):
        return _prog(uniform, donate)(*_splay(inputs), pct)

    # expose lowering for HLO inspection (dryrun's replica-group check)
    meshed.lower = (
        lambda inputs, pct, uniform=False: _prog(uniform, False).lower(
            *_splay(inputs), pct))
    return meshed


@functools.partial(jax.jit, static_argnames=("compression", "cap"))
def digest_export(dense_v: jax.Array, dense_w: jax.Array,
                  rows: jax.Array, compression: float, cap: int
                  ) -> tuple[jax.Array, jax.Array]:
    """Compress the staged points of the given (compacted) rows into wire
    centroids `[F, cap]` for forwarding (ForwardableMetrics,
    `worker.go:179-216` / `MergingDigest.Data`,
    `merging_digest.go:474-483`).  Gathers rows first so both the compute
    and the readback scale with the forwarded subset, not the arena.
    bf16-staged values (compact_general staging) widen here: compress
    accumulates weighted sums, which bf16 would corrupt."""
    dv_r = dense_v[rows]
    if dv_r.dtype == jnp.bfloat16:
        dv_r = dv_r.astype(jnp.float32)
    return td.compress(dv_r, dense_w[rows], compression, cap)


@functools.partial(jax.jit, static_argnames=("compression", "cap"))
def digest_export_uniform(dense_v: jax.Array, depths: jax.Array,
                          rows: jax.Array, compression: float, cap: int
                          ) -> tuple[jax.Array, jax.Array]:
    """digest_export for the depth-vector (uniform) dense build: the 0/1
    weights of the gathered rows are reconstructed ON DEVICE from the
    per-row depths (they never crossed the host->device link)."""
    d = dense_v.shape[1]
    sub_depths = depths[rows].astype(jnp.int32)
    # weights in f32 regardless of the value staging dtype: bf16 cannot
    # represent integer counts above 256, and compress() accumulates
    # them (cumsum/total) — bf16 weights would corrupt exported digests
    dw = (jnp.arange(d, dtype=jnp.int32)[None, :]
          < sub_depths[:, None]).astype(jnp.float32)
    return td.compress(dense_v[rows].astype(jnp.float32), dw,
                       compression, cap)


@functools.partial(jax.jit, static_argnames=("compression", "cap"))
def partial_digests(dense_v: jax.Array, dense_w: jax.Array,
                    compression: float, cap: int
                    ) -> tuple[jax.Array, jax.Array]:
    """One batched compress of a dense `[U, W]` sample matrix into per-row
    partial digests `[U, cap]` — the hot-key pre-reduction: an arbitrarily
    deep backlog collapses into <= cap weighted points per row, which
    re-stage as ordinary samples (weight-preserving, order-invariant)."""
    return td.compress(dense_v, dense_w, compression, cap)


# ---------------------------------------------------------------------------
# Set (HLL) lane kernels — device-resident register state (meshed tiers)
# ---------------------------------------------------------------------------

def _set_lane_scatter(lanes_regs: jax.Array, rows: jax.Array,
                      idx: jax.Array, rank: jax.Array,
                      lane: int) -> jax.Array:
    """Scatter-max staged (set row, register index, rank) triples into lane
    `lane` of the `[R_s, S, m]` register state — the device half of
    Sketch.Insert (`samplers/samplers.go:242-244`).  Padding entries with
    rank 0 are no-ops (max against an empty register)."""
    return lanes_regs.at[lane, rows, idx].max(rank)


def _set_lane_merge_rows(lanes_regs: jax.Array, rows: jax.Array,
                         regmat: jax.Array, lane: int) -> jax.Array:
    """Register-wise max of imported full register rows `[n, m]` into lane
    `lane` (Set.Merge, `samplers/samplers.go:299-311`).  All-zero padding
    rows are no-ops."""
    return lanes_regs.at[lane, rows].max(regmat)


# In-place (donating) updates for the common case, plus COPYING twins.
# SetArena.sync picks per call (see lane_donation_ok): the PJRT CPU
# runtime double-frees donated sharded-update buffers that race an
# in-flight reader on another executable — observed as corrupted set
# estimates and interpreter segfaults under the overlapped flush
# pipeline (tests/test_parallel.py conservation stress) — and a
# dispatched-but-not-fetched flush additionally holds a snapshot the
# update must never scribble over on ANY backend.
set_lane_scatter = functools.partial(
    jax.jit, static_argnames=("lane",),
    donate_argnums=(0,))(_set_lane_scatter)
set_lane_scatter_copy = functools.partial(
    jax.jit, static_argnames=("lane",))(_set_lane_scatter)
set_lane_merge_rows = functools.partial(
    jax.jit, static_argnames=("lane",),
    donate_argnums=(0,))(_set_lane_merge_rows)
set_lane_merge_rows_copy = functools.partial(
    jax.jit, static_argnames=("lane",))(_set_lane_merge_rows)


@functools.lru_cache(maxsize=None)
def lane_donation_ok() -> bool:
    """Whether the in-place (donating) lane-update kernels are safe on
    this backend.  PJRT:CPU mismanages donation of sharded u8 update
    chains when another executable is concurrently in flight (the
    symptom is silent register corruption, sometimes a hard segfault);
    the TPU runtime — where donation is the production norm — is fine.
    Cached once: the backend cannot change within a process."""
    return jax.default_backend() != "cpu"


@jax.jit
def set_reset_rows(lanes_regs: jax.Array, rows: jax.Array) -> jax.Array:
    """Zero the given set rows in every lane.  NOT donating: the flush
    snapshot may still reference the pre-reset buffer while emission runs
    outside the aggregator lock."""
    return lanes_regs.at[:, rows].set(0)


@jax.jit
def set_regs_pack(set_regs: jax.Array, rows: jax.Array) -> jax.Array:
    """Flat [n * m] u8 readback of merged HLL registers for forwarding
    (Set.Metric marshal, `samplers/samplers.go:279-295`)."""
    return set_regs[rows].reshape(-1)


@jax.jit
def set_gather_rows(lanes_regs: jax.Array, rows: jax.Array) -> jax.Array:
    """[n, m] u8 readback of the lane-union registers for the given rows —
    the flush-side read of resident set arenas (flush_resident_arenas).
    Unmeshed resident state has one lane, so the lane max is a no-op; the
    meshed form is the same reduction flush_body performs.  NOT donating:
    a dispatched-but-unfetched flush pins the lanes (snapshot_lanes)."""
    return jnp.max(lanes_regs, axis=0)[rows]


# ---------------------------------------------------------------------------
# Resident-delta scatter kernels (flush_resident_arenas)
# ---------------------------------------------------------------------------
#
# The device half of the delta-flush dense build: the host streams fixed-
# size (row, pos, value[, weight]) delta chunks into HBM DURING the
# interval (DigestArena.stream_resident), and at flush time the dense
# sample matrix is assembled ON DEVICE — zeros [U, D] plus one scatter per
# chunk — so the flush critical path uploads only the dense-id map and the
# un-streamed tail, never the full key space.  Chunk `rows` are arena-row
# ids; `dense_id` maps them to this flush's compacted dense rows, with
# INT32_MAX marking rows outside the flush (and the padding sentinel slot
# at index capacity), which mode="drop" discards without a host round
# trip.  Positions are the host's per-row arrival cursors, byte-identical
# to build_dense's stable-sort ordinals — the bit-parity contract.

_RESIDENT_DROP = 2**31 - 1  # dense_id value for rows absent from the flush


def _resident_scatter(dense_v: jax.Array, dense_id: jax.Array,
                      rows: jax.Array, pos: jax.Array,
                      vals: jax.Array) -> jax.Array:
    """Scatter one value-only delta chunk (uniform interval: the weight
    matrix never exists, occupancy rides the per-row depth vector)."""
    r = dense_id[rows]
    return dense_v.at[r, pos].set(vals, mode="drop")


def _resident_scatter_w(dense_v: jax.Array, dense_w: jax.Array,
                        dense_id: jax.Array, rows: jax.Array,
                        pos: jax.Array, vals: jax.Array,
                        wts: jax.Array
                        ) -> tuple[jax.Array, jax.Array]:
    """Scatter a weighted delta chunk into the (values, weights) pair."""
    r = dense_id[rows]
    return (dense_v.at[r, pos].set(vals, mode="drop"),
            dense_w.at[r, pos].set(wts, mode="drop"))


def _resident_scatter_w1(dense_v: jax.Array, dense_w: jax.Array,
                         dense_id: jax.Array, rows: jax.Array,
                         pos: jax.Array, vals: jax.Array
                         ) -> tuple[jax.Array, jax.Array]:
    """Weighted-build scatter of a chunk streamed while the interval was
    still uniform: its weights are exactly 1.0 and were never uploaded —
    they materialize on device (exact in every eval dtype)."""
    r = dense_id[rows]
    ones = jnp.ones(vals.shape, dense_w.dtype)
    return (dense_v.at[r, pos].set(vals, mode="drop"),
            dense_w.at[r, pos].set(ones, mode="drop"))


# Donating twins consume the dense accumulator chain in place (the
# production TPU shape); the copying twins are the CPU-backend fallback —
# the SAME PJRT:CPU donation race documented at lane_donation_ok applies
# to the resident dense chain (a scatter's donated input racing the
# previous flush's still-in-flight executable), so resident_donation_ok
# gates every assembly the way SetArena.sync gates lane updates.
resident_scatter = jax.jit(_resident_scatter, donate_argnums=(0,))
resident_scatter_copy = jax.jit(_resident_scatter)
resident_scatter_w = jax.jit(_resident_scatter_w, donate_argnums=(0, 1))
resident_scatter_w_copy = jax.jit(_resident_scatter_w)
resident_scatter_w1 = jax.jit(_resident_scatter_w1, donate_argnums=(0, 1))
resident_scatter_w1_copy = jax.jit(_resident_scatter_w1)


def resident_donation_ok() -> bool:
    """Donation gate for the resident dense-assembly chain — one policy
    with the lane kernels (see lane_donation_ok): in-place on TPU,
    copying kernels on PJRT:CPU."""
    return lane_donation_ok()


# One-shot measured staged-vs-resident probe state (ROADMAP #2
# remainder: marginal links — tunnel-attached chips — pick the faster
# assembly path empirically, not by backend name).  Module-level dict
# rather than an lru_cache so /debug/vars can INSPECT the decision
# without forcing a measurement (http_api.link_probe_stats).
_LINK_PROBE: dict = {"measured": False, "probes": 0}
_PROBE_ROWS = 256          # synthetic dense chunk: [rows, depth] f32
_PROBE_DEPTH = 64
_PROBE_CHUNKS = 4          # per-chunk dispatch is what the stream pays
_PROBE_REPS = 3            # best-of timing after a compile warmup


def _measure_link_probe() -> dict:
    """Time the two ways a flush gets its dense matrix into device
    memory: (a) RESIDENT — the interval's delta chunks scatter into a
    device-born accumulator (per-chunk upload of slim COO arrays +
    scatter dispatch); (b) STAGED — the host builds the dense matrix
    and uploads it whole at flush time.  On a real accelerator the
    staged path pays the full dense upload on the flush critical path,
    so (a) wins; on PJRT:CPU "upload" is a memcpy and (a) is pure
    scatter-dispatch overhead, so (b) wins — the measurement reproduces
    the old backend-name heuristic where that heuristic was right, and
    decides marginal links by data.  Small fixed shapes: one compile +
    microseconds of steady-state per process, cached forever."""
    import time

    import numpy as np

    rows = np.tile(np.arange(_PROBE_ROWS, dtype=np.int32),
                   _PROBE_DEPTH // 4)
    pos = np.repeat(np.arange(_PROBE_DEPTH // 4, dtype=np.int32),
                    _PROBE_ROWS)
    vals = np.linspace(0.0, 1.0, rows.size, dtype=np.float32)
    dense_id = jnp.arange(_PROBE_ROWS, dtype=jnp.int32)

    def resident_once():
        dv = resident_dense_zeros((_PROBE_ROWS, _PROBE_DEPTH),
                                  jnp.float32)
        for _ in range(_PROBE_CHUNKS):
            dv = resident_scatter_copy(
                dv, dense_id, jnp.asarray(rows), jnp.asarray(pos),
                jnp.asarray(vals))
        return dv.block_until_ready()

    def staged_once():
        dense = np.zeros((_PROBE_ROWS, _PROBE_DEPTH), np.float32)
        for _ in range(_PROBE_CHUNKS):
            dense[rows, pos] = vals
        return jax.device_put(dense).block_until_ready()

    resident_once(), staged_once()     # compile/warm outside the clock
    res_s = stg_s = float("inf")
    for _ in range(_PROBE_REPS):
        t0 = time.perf_counter()
        resident_once()
        res_s = min(res_s, time.perf_counter() - t0)
        t0 = time.perf_counter()
        staged_once()
        stg_s = min(stg_s, time.perf_counter() - t0)
    # device assembly must win CLEARLY: near-parity links keep the
    # staged path (no compile-churn exposure for a wash)
    return {"ok": res_s < 0.8 * stg_s,
            "backend": jax.default_backend(),
            "resident_us": round(res_s * 1e6, 1),
            "staged_us": round(stg_s * 1e6, 1),
            "forced": False}


def resident_link_ok() -> bool:
    """Whether this backend's host<->device link makes flush-time
    device assembly (resident delta stream) faster than the staged
    host-dense-build + upload — decided by a ONE-SHOT measured probe
    (cached per process; `/debug/vars -> resident_link_probe`).
    `VENEUR_TPU_RESIDENT_LINK=0|1` pins the answer without measuring
    (hermetic CI cells).  When False, the digest/moments
    device-assembly half of flush_resident_arenas degrades to the
    staged (chunk-pipelined) flush; the resident SET lanes (u8
    scatter-max, readback-on-checkpoint) stay active everywhere.
    Tests force the device-assembly path via the arenas'
    resident_device_assembly override."""
    if _LINK_PROBE["measured"]:
        return _LINK_PROBE["ok"]
    import os
    forced = os.environ.get("VENEUR_TPU_RESIDENT_LINK")
    if forced is not None and forced != "":
        _LINK_PROBE.update(ok=forced not in ("0", "false", "no"),
                           backend=jax.default_backend(),
                           forced=True, measured=True)
        _LINK_PROBE["probes"] += 1
        return _LINK_PROBE["ok"]
    _LINK_PROBE.update(_measure_link_probe())
    _LINK_PROBE["measured"] = True
    _LINK_PROBE["probes"] += 1
    return _LINK_PROBE["ok"]


def link_probe_stats() -> dict:
    """The cached probe decision for /debug/vars — never forces a
    measurement (`measured: false` until something consulted the
    link)."""
    return dict(_LINK_PROBE)


@functools.partial(jax.jit, static_argnames=("shape", "dtype"))
def resident_dense_zeros(shape, dtype) -> jax.Array:
    """Device-side zero dense accumulator — the resident build's starting
    buffer is born in HBM; nothing crosses the host link for it."""
    return jnp.zeros(shape, dtype)
