"""The serving-path SPMD programs: lane ingest + the per-flush digest reduce.

This wires the sharded flush (veneur_tpu/parallel/flush_step.py) into the
*production* aggregation tier: `DigestArena` keeps its centroid state as
lane-striped device tensors `[R, K, C]` (R ingest lanes x K keys x C
centroid slots), sharded over a (shard, replica) `Mesh` when one is
configured —

  - the **shard** axis partitions the key space K (the device analog of the
    reference's fnv1a-hash worker sharding, `server.go:997-1011` /
    `worker.go:34-50`, and of the proxy's consistent-hash ring);
  - the **replica** axis partitions the R ingest lanes, so each replica
    group accumulates a subset of lanes' partial digests and the flush
    reduces them with an `all_gather` over ICI followed by one batched
    compress — the collective form of the gRPC ImportMetric merge loop
    (`worker.go:402-459`).

Three programs:

  * `lane_ingest`   — fold one dense sample wave `[K, W]` into lane r of the
                      striped state (the device half of `DigestArena.sync`).
                      Striping waves across lanes both feeds the replica
                      axis and cuts the sequential kernel-launch depth for a
                      hot key by R (each lane's chain is independent).
  * `make_flush`    — build the per-flush evaluation: gather lanes over the
                      replica axis, merge into one digest per key, evaluate
                      all percentiles/aggregates at once.  With `mesh=None`
                      this is the same math under plain `jit` on the default
                      device, so single-chip and multi-chip serving share
                      one code path.
  * `reset_rows`    — zero the touched rows across every lane after flush
                      (the map-swap of `worker.go:462-481`; rows persist,
                      state is interval-scoped).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veneur_tpu.parallel.mesh import REPLICA_AXIS, SHARD_AXIS
from veneur_tpu.sketches import tdigest as td


class ServingFlushOutputs(NamedTuple):
    mean: jax.Array       # [K, C] merged centroids (forwarding export)
    weight: jax.Array     # [K, C]
    quantiles: jax.Array  # [K, P]
    counts: jax.Array     # [K] total weight
    sums: jax.Array       # [K] weighted sum


# ---------------------------------------------------------------------------
# Shardings
# ---------------------------------------------------------------------------

def lane_sharding(mesh: Optional[Mesh]):
    """[R, K, C] lane-striped state: lanes over 'replica', keys over
    'shard'."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(REPLICA_AXIS, SHARD_AXIS, None))


def row_sharding(mesh: Optional[Mesh], ndim: int = 1):
    """[K, ...] per-key arrays: keys over 'shard'."""
    if mesh is None:
        return None
    return NamedSharding(mesh, P(SHARD_AXIS, *([None] * (ndim - 1))))


def put(x, sharding):
    x = jnp.asarray(x)
    return x if sharding is None else jax.device_put(x, sharding)


# ---------------------------------------------------------------------------
# Lane ingest
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("lane", "compression"),
                   donate_argnums=(0, 1))
def lane_ingest(lanes_mean: jax.Array, lanes_weight: jax.Array,
                values: jax.Array, vweights: jax.Array,
                lane: int, compression: float
                ) -> tuple[jax.Array, jax.Array]:
    """Fold a dense sample wave `[K, W]` into lane `lane` of `[R, K, C]`.

    Device half of `MergingDigest.Add`/`mergeAllTemps`
    (`merging_digest.go:115-224`) batched over all keys; min/max/rsum are
    tracked host-side by the arena (they are authoritative there — see
    DigestArena docstring) so only centroids live here.
    """
    cap = lanes_mean.shape[2]
    cat_m = jnp.concatenate([lanes_mean[lane], values], axis=1)
    cat_w = jnp.concatenate([lanes_weight[lane], vweights], axis=1)
    nm, nw = td.compress(cat_m, cat_w, compression, cap)
    return lanes_mean.at[lane].set(nm), lanes_weight.at[lane].set(nw)


@functools.partial(jax.jit, static_argnames=("compression", "cap"))
def partial_digests(dense_v: jax.Array, dense_w: jax.Array,
                    compression: float, cap: int
                    ) -> tuple[jax.Array, jax.Array]:
    """One batched compress of a dense `[U, W]` sample matrix into per-row
    partial digests `[U, cap]` — stage 1 of the hot-key ingest path (the
    tree form of `mergeAllTemps`: any W collapses in a single launch
    instead of a W/wave-width sequential chain)."""
    return td.compress(dense_v, dense_w, compression, cap)


@jax.jit
def reset_rows(lanes_mean: jax.Array, lanes_weight: jax.Array,
               rows: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Zero the given key rows in every lane.  NOT donating: the flush
    snapshot may still reference the pre-reset buffers while emission runs
    outside the aggregator lock."""
    return (lanes_mean.at[:, rows].set(0.0),
            lanes_weight.at[:, rows].set(0.0))


# ---------------------------------------------------------------------------
# Flush evaluation
# ---------------------------------------------------------------------------

def reduce_eval(lanes_mean, lanes_weight, d_min, d_max, d_rsum,
                percentiles, compression, replica_axis,
                state_mean=None, state_weight=None) -> ServingFlushOutputs:
    """THE digest-flush core, shared by the serving path and the benchmark
    flush_step: all_gather lanes over the replica axis -> one batched
    compress (optionally folding a persistent [K, C] state in) -> evaluate
    quantiles/counts/sums for every key at once.

    `replica_axis` names the mesh axis to gather over (None = single
    device).  The merged min/max/rsum come from the caller's authoritative
    scalars (re-ingested centroid means never reach the true extremes —
    `worker.go:402-459` semantics); pass zeros for rsum if the caller
    tracks it host-side (no device computation consumes it).
    """
    if replica_axis is not None:
        lanes_mean = jax.lax.all_gather(
            lanes_mean, replica_axis, axis=0, tiled=True)
        lanes_weight = jax.lax.all_gather(
            lanes_weight, replica_axis, axis=0, tiled=True)
    k = lanes_mean.shape[1]
    cap = lanes_mean.shape[2]
    flat_m = jnp.transpose(lanes_mean, (1, 0, 2)).reshape(k, -1)
    flat_w = jnp.transpose(lanes_weight, (1, 0, 2)).reshape(k, -1)
    if state_mean is not None:
        flat_m = jnp.concatenate([state_mean, flat_m], axis=1)
        flat_w = jnp.concatenate([state_weight, flat_w], axis=1)
    mm, mw = td.compress(flat_m, flat_w, compression, cap)
    merged = td.TDigestState(mean=mm, weight=mw,
                             min=d_min, max=d_max, rsum=d_rsum)
    return ServingFlushOutputs(
        mean=mm, weight=mw,
        quantiles=td.quantile(merged, percentiles),
        counts=td.total_weight(merged),
        sums=td.sum_values(merged))


def make_flush(mesh: Optional[Mesh],
               compression: float = td.DEFAULT_COMPRESSION):
    """Build the per-flush program.

    Returns fn(lanes_mean [R,K,C], lanes_weight, d_min [K], d_max,
    percentiles [P]) -> ServingFlushOutputs.  With a mesh, the function is a
    shard_map'd SPMD program (keys sharded, lanes gathered over the replica
    axis); without, the identical math under plain jit.  rsum stays
    host-side (hmean is emitted from host scalars; no device computation
    needs it).
    """
    def body_for(axis):
        def body(lanes_mean, lanes_weight, d_min, d_max, percentiles):
            return reduce_eval(lanes_mean, lanes_weight, d_min, d_max,
                               jnp.zeros_like(d_min), percentiles,
                               compression, axis)
        return body

    if mesh is None:
        return jax.jit(body_for(None))

    spec_lanes = P(REPLICA_AXIS, SHARD_AXIS, None)
    spec_k = P(SHARD_AXIS)
    spec_kc = P(SHARD_AXIS, None)
    fn = jax.shard_map(
        body_for(REPLICA_AXIS), mesh=mesh,
        in_specs=(spec_lanes, spec_lanes, spec_k, spec_k, P(None)),
        out_specs=ServingFlushOutputs(
            mean=spec_kc, weight=spec_kc, quantiles=spec_kc,
            counts=spec_k, sums=spec_k),
        check_vma=False)
    return jax.jit(fn)
