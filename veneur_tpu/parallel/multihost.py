"""Multi-host (DCN) scaling for the sharded aggregation tier.

The reference scales across hosts with gRPC forwarding + the proxy's
consistent-hash ring (SURVEY §2.3); the TPU-native global tier scales the
same state over multiple accelerator hosts with `jax.distributed`: after
`init_multihost`, `jax.devices()` returns every chip in the cluster, and
`mesh.make_mesh` builds the (shard, replica) mesh over all of them.

Axis/topology mapping (why the layout is DCN-friendly):

  * `jax.devices()` orders devices process-by-process, and the mesh
    reshape is row-major, so when `replicas` DIVIDES the per-host device
    count each replica group is a contiguous intra-host run.  The
    flush's only collective (the replica-axis `all_gather` in
    `parallel/serving.py flush_body`) then rides ICI; `make_mesh` warns
    when a configured replica count would straddle hosts;
  * the `shard` axis (key-space partition) spans hosts but needs NO
    collective — each key's digests live on exactly one shard, the
    device analog of the proxy ring assigning each key to one global.
    Cross-host traffic stays where the reference keeps it: the gRPC
    forward/import edge.

**Lockstep contract.** Multi-controller serving is SPMD: every process
runs the same flush program on the same global shapes.  The framework
enforces the mechanics — `serving.put` builds global arrays from each
process's shard view, `serving.fetch` batches readbacks into one DCN
all-gather per flush, and the aggregator agrees on touched-family flags
and dense dimensions with a single small gather before each flush — but
the deployment must provide: (a) a consistent key-registration order
across processes (the control plane's analog of the proxy ring's
membership view), (b) pre-sized set arenas (one-sided growth would
diverge global shapes), and (c) a synchronized flush schedule
(`synchronize_with_interval`).  Contract (a) is now tripwired: the
per-flush gather carries each arena's key-set and key->row fingerprints
(`core/arena.py key_checksum`), and controllers holding the same keys
with different row assignments raise a crisp per-family lockstep error
instead of silently merging unrelated timeseries; ring-style asymmetric
registration (a key present only on its owning controller) remains
legal.  The multi-process mesh serves the GLOBAL
tier; local/forwarding tiers stay single-process and reach it over the
gRPC forward edge, exactly like the reference's proxy ring
(tests/test_multihost.py exercises two real jax.distributed processes
end to end).

Single-host single-process remains the default; none of this is required
until a deployment grows past one accelerator host.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("veneur_tpu.parallel.multihost")

_initialized = False


def init_multihost(coordinator_address: str,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Join the JAX distributed cluster (idempotent).

    With TPU metadata available (GKE/TPU-VM environments), the arguments
    beyond the coordinator are auto-detected; pass them explicitly
    elsewhere.  Must run before any other JAX call in the process."""
    global _initialized
    if _initialized:
        return
    import jax

    # XLA:CPU runs cross-process collectives only through the gloo
    # transport ("Multiprocess computations aren't implemented on the
    # CPU backend" otherwise) — select it whenever the process is
    # pinned to the CPU platform, BEFORE the backend initializes.  TPU
    # processes keep their native DCN transport untouched.
    platforms = (jax.config.jax_platforms
                 or os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" in str(platforms).lower():
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except Exception as e:  # older jaxlib without the option
            logger.warning("could not select gloo CPU collectives: %s",
                           e)

    kwargs = {"coordinator_address": coordinator_address}
    if num_processes is not None and num_processes >= 0:
        kwargs["num_processes"] = num_processes
    if process_id is not None and process_id >= 0:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    _initialized = True
    logger.info("joined distributed cluster: process %d/%d, "
                "%d global devices (%d local)",
                jax.process_index(), jax.process_count(),
                len(jax.devices()), len(jax.local_devices()))


def maybe_init_from_config(cfg) -> None:
    """Server bootstrap hook: join the cluster when the config names a
    coordinator (no-op otherwise)."""
    if getattr(cfg, "distributed_coordinator", ""):
        init_multihost(
            cfg.distributed_coordinator,
            num_processes=cfg.distributed_num_processes or None,
            process_id=(cfg.distributed_process_id
                        if cfg.distributed_process_id >= 0 else None))
