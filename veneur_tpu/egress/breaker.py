"""Per-sink circuit breaker for the egress data plane.

The same state machine the proxy's destination set runs per address
(`proxy/destinations.py` `_Breaker` + its `_admit`/`_record_*` logic),
packaged as a self-contained class so the egress lanes can reuse the
CONTRACT without dragging in the ring: `threshold` consecutive failures
trip the breaker OPEN; while open, `admit()` refuses work (the lane
spills straight to its durable spool instead of burning attempts
against a dead backend); after `reset_s` (doubling per consecutive
trip, capped at 8x) the next `admit()` becomes the HALF-OPEN probe —
one real delivery attempt.  Probe success closes the breaker; probe
failure re-opens it with a longer cooldown.

One deliberate divergence from the proxy's dial breaker: there a mere
successful dial must NOT reset the consecutive-failure count (a
half-broken peer can accept dials and kill every RPC).  An egress
success IS a delivered flush — real progress — so `record_success`
always resets the failure run.
"""

from __future__ import annotations

import threading
import time


class CircuitBreaker:
    """Failure state for one egress sink.  Thread-safe: the lane worker
    and the spool replayer both consult it."""

    # cooldown doubles per consecutive trip, capped at this multiple
    # (the proxy's BREAKER_MAX_BACKOFF_X contract)
    MAX_BACKOFF_X = 8

    def __init__(self, threshold: int = 3, reset_s: float = 5.0):
        self.threshold = max(1, int(threshold))
        self.reset_s = float(reset_s)
        self._lock = threading.Lock()
        self.failures = 0       # consecutive failures since last success
        self.trips = 0          # times the breaker has opened
        self.open_until = 0.0   # monotonic deadline; 0 = not open
        self.half_open = False  # a probe delivery is in flight

    def state(self, now: float | None = None) -> str:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.half_open:
                return "half_open"
            if self.open_until > now:
                return "open"
            if self.open_until:
                return "probe_due"
            return "closed"

    def admit(self) -> bool:
        """May this delivery run now?  False while open; an expired
        cooldown admits ONE delivery (the half-open probe)."""
        with self._lock:
            now = time.monotonic()
            if self.half_open:
                return False            # a probe is already in flight
            if self.open_until > now:
                return False
            if self.open_until:
                self.half_open = True   # this delivery is the probe
            return True

    def record_failure(self) -> bool:
        """One failed delivery attempt.  Returns True when this failure
        tripped (or re-tripped) the breaker open."""
        with self._lock:
            self.failures += 1
            self.half_open = False
            if self.failures >= self.threshold or self.trips:
                # past the threshold (or re-failing a half-open probe):
                # open with exponential cooldown
                self.trips += 1
                backoff = min(2 ** (self.trips - 1), self.MAX_BACKOFF_X)
                self.open_until = (time.monotonic()
                                   + self.reset_s * backoff)
                return True
            return False

    def record_success(self) -> bool:
        """One delivered flush.  Returns True when this success CLOSED
        an engaged (tripped/half-open) breaker."""
        with self._lock:
            engaged = bool(self.trips or self.half_open)
            self.failures = 0
            self.trips = 0
            self.open_until = 0.0
            self.half_open = False
            return engaged

    def retry_in_s(self, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            return max(0.0, self.open_until - now)

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {"state": self.state(now), "failures": self.failures,
                "trips": self.trips,
                "retry_in_s": round(self.retry_in_s(now), 3)}
