"""Egress data plane: async sink fan-out with per-sink breakers,
bounded retries, and spool-backed durable delivery (ROADMAP #8).

See egress/plane.py for the architecture; egress/breaker.py holds the
per-sink circuit breaker (the proxy destination-set contract, reused).
"""

from veneur_tpu.egress.breaker import CircuitBreaker
from veneur_tpu.egress.plane import (EgressJob, EgressPlane, SinkLane,
                                     decode_metrics, encode_metrics)

__all__ = ["CircuitBreaker", "EgressJob", "EgressPlane", "SinkLane",
           "decode_metrics", "encode_metrics"]
