"""The egress data plane: async sink fan-out off the flush critical path.

The reference fans each flush out to ~15 pluggable sinks inside the
flush goroutine (`flusher.go:97-113`, `sinks/datadog/datadog.go:158`);
this repo's twin used to do the same under `_flush_serial` — one slow
or blackholed backend held the flush serialization lock and became the
new p99 (ROADMAP #8).  This module gives egress the machinery the
forward path already earned:

  * a bounded per-sink queue (`_flush_locked` hands the rendered
    interval over and returns; filtering, serialization and HTTP all
    run on per-sink lane workers),
  * per-sink circuit breakers (egress/breaker.py — the proxy
    destination-set contract) + bounded retries with seeded backoff
    (the forward client's `RetryPolicy`, reused verbatim),
  * durable spill: when a sink's retries exhaust (or its breaker is
    open), the filtered payload is serialized into that sink's own
    `ForwardSpool` segment (forward/spool.py, reused verbatim) and a
    background replayer re-delivers oldest-first once the backend
    recovers — the spool's ledger closure
    (`spilled == replayed + expired + dropped + pending`) surfaces at
    `/debug/vars -> egress`,
  * tracing: on sampled intervals every sink flush becomes a
    `flush.sink.<name>` span on the interval's own trace, with one
    `egress.attempt` child per delivery attempt (a breaker trip is
    causally visible in the critical-path table) and `egress.replay`
    spans continuing the original interval's context across the
    outage.

Failpoint: `egress.sink` fires per metric-lane delivery attempt
(initial and replay), so a chaos arm can blackhole a backend with
error/delay/drop actions and the unit tests can drive the full
degradation chain deterministically.

Job lifetime contract (enforced by the vnlint resource-pairing rule):
a job claimed from a lane queue (`claim_job`) must be settled
(`settle_job`) on every path — delivered, spilled, or dropped with
accounting — so `settle()` (and the flush-on-shutdown drain) can wait
on the pending count without a lost-job leak.
"""

from __future__ import annotations

import json
import logging
import os
import queue as queue_mod
import random
import re
import threading
import time
from typing import Callable, Optional

from veneur_tpu import failpoints
from veneur_tpu import sinks as sink_mod
from veneur_tpu.egress.breaker import CircuitBreaker
from veneur_tpu.forward.client import RetryPolicy
from veneur_tpu.forward.spool import ForwardSpool, RetryableReplayError
from veneur_tpu.samplers.samplers import InterMetric
from veneur_tpu.trace import recorder as trace_rec

logger = logging.getLogger("veneur_tpu.egress")

# egress spool payload version (the codec below, one record per job)
_PAYLOAD_VERSION = 1


def encode_metrics(metrics) -> bytes:
    """Serialize a filtered metric payload for the durable spool.  The
    sink re-delivery path needs full InterMetric rows back, so the
    codec is a plain JSON row list (routing allowlists are dropped —
    filtering already happened before the spill)."""
    rows = [[m.name, m.timestamp, m.value, list(m.tags), m.type,
             m.message, m.hostname] for m in metrics]
    return json.dumps([_PAYLOAD_VERSION, rows],
                      separators=(",", ":")).encode()


def decode_metrics(body: bytes) -> list[InterMetric]:
    version, rows = json.loads(body.decode())
    if version != _PAYLOAD_VERSION:
        raise ValueError(f"unknown egress payload version {version}")
    return [InterMetric(name=r[0], timestamp=r[1], value=r[2],
                        tags=list(r[3]), type=r[4], message=r[5],
                        hostname=r[6]) for r in rows]


def emit_http_phases(sink, sink_tags, statsd) -> None:
    """Per-POST HTTP phase self-metrics for poster-backed sinks — the
    reference traces DNS/connect/TTFB on every sink POST
    (`http/http.go:23-100`); the poster's tracing adapter records them
    and this emits `sink.http.{connect,ttfb,total}_ms` +
    `sink.http.connections_used_total` by state."""
    poster = getattr(sink, "_poster", None)
    if poster is None or not hasattr(poster, "drain_phase_stats"):
        return
    new_conns = reused = 0
    for rec in poster.drain_phase_stats():
        if rec["reused"]:
            reused += 1
        else:
            new_conns += 1
            statsd.timing("sink.http.connect_ms",
                          rec["connect_ms"], tags=sink_tags)
        statsd.timing("sink.http.ttfb_ms", rec["ttfb_ms"],
                      tags=sink_tags)
        statsd.timing("sink.http.total_ms", rec["total_ms"],
                      tags=sink_tags)
    if new_conns:
        statsd.count("sink.http.connections_used_total", new_conns,
                     tags=sink_tags + ["state:new"])
    if reused:
        statsd.count("sink.http.connections_used_total", reused,
                     tags=sink_tags + ["state:reused"])


class EgressJob:
    """One sink's share of one flush interval."""

    __slots__ = ("metrics", "events", "statsd", "interval",
                 "trace_id", "parent_span_id", "traced")

    def __init__(self, metrics, events, statsd, interval: int,
                 trace_id: int = 0, parent_span_id: int = 0,
                 traced: bool = False):
        self.metrics = metrics
        self.events = events
        self.statsd = statsd
        self.interval = interval
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.traced = traced


def _safe_dirname(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", name) or "sink"


class SinkLane:
    """One sink's bounded queue, worker thread, breaker and spool."""

    def __init__(self, plane: "EgressPlane", kind: str, spec, sink,
                 spool: Optional[ForwardSpool] = None):
        self.plane = plane
        self.kind = kind                 # "metric" | "span"
        self.spec = spec
        self.sink = sink
        self.name = sink.name()
        self.label = f"{kind}:{self.name}"
        self.sink_tags = [f"sink_name:{self.name}",
                          f"sink_kind:{spec.kind if spec else sink.kind()}"]
        self.queue: queue_mod.Queue = queue_mod.Queue(
            maxsize=plane.queue_depth)
        self.breaker = CircuitBreaker(plane.breaker_threshold,
                                      plane.breaker_reset_s)
        self.spool = spool
        self._rng = random.Random(plane.retry.seed)
        self._spill_seq = 0
        self._stats_lock = threading.Lock()
        self.enqueued = 0            # jobs accepted onto the queue
        self.delivered = 0           # jobs fully delivered
        self.flushed_points = 0      # metric points delivered
        self.retried = 0             # retry attempts taken
        self.errors = 0              # failed delivery attempts
        self.queue_dropped_points = 0  # points dropped on a full queue
        self.dropped_points = 0      # exhausted + spool-less drops
        self.stragglers = 0          # deliveries slower than an interval
        self.busy_since = 0.0        # perf_counter at claim; 0 = idle
        self._thread: Optional[threading.Thread] = None

    def _count(self, field: str, n: int = 1) -> None:
        with self._stats_lock:
            setattr(self, field, getattr(self, field) + n)

    # -- enqueue (the flush path's handoff; never blocks) ------------------

    def submit(self, job: EgressJob) -> bool:
        """Hand one interval's job to this lane.  Returns False (after
        accounting the loss) when the queue is full — a sink that
        cannot keep up drops whole intervals VISIBLY instead of
        wedging the flush ticker."""
        self.plane.job_opened()
        try:
            self.queue.put_nowait(job)
        except queue_mod.Full:
            self.plane.job_closed()
            # only metric lanes lose actual points on a bounce (span
            # sinks buffer internally; a skipped periodic flush loses
            # nothing) — a phantom point here would pollute the
            # testbed's visible-loss denominator
            pts = len(job.metrics) if self.kind == "metric" else 0
            if pts:
                self._count("queue_dropped_points", pts)
            job.statsd.count("egress.queue_full_total", 1,
                             tags=self.sink_tags)
            logger.warning(
                "egress %s: queue full (%d deep); dropped interval %d "
                "(%d points, accounted)", self.label,
                self.plane.queue_depth, job.interval, pts)
            return False
        self._count("enqueued")
        return True

    # -- worker ------------------------------------------------------------

    def start(self, replayers: bool = True) -> None:
        if replayers and self.spool is not None:
            # the replayer starts HERE (not at construction, and not
            # on a pre-start() lazy submit) so a recovered spool never
            # re-delivers into a sink that has not been start()ed yet;
            # start_replayer is idempotent, so the full start() after
            # a lazy one still arms it
            self.spool.start_replayer(self._replay_deliver)
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"egress-{self.label}")
        self._thread.start()

    def claim_job(self) -> Optional[EgressJob]:
        """Pop the next job (None on an empty poll).  Pairs with
        settle_job on every path — the egress-queue handoff lifetime
        the resource-pairing rule enforces."""
        try:
            return self.queue.get(timeout=0.1)
        except queue_mod.Empty:
            return None

    def settle_job(self, job: Optional[EgressJob]) -> None:
        """Close one claimed job's lifetime (delivered, spilled or
        dropped — the outcome was accounted by the delivery path)."""
        if job is not None:
            self.plane.job_closed()

    def _run(self) -> None:
        while not self.plane.stopping.is_set():
            job = self.claim_job()
            try:
                if job is not None:
                    self._deliver_job(job)
            except Exception:
                # the backstop that keeps the lane alive on a delivery
                # BUG — counted as an error episode so the crash is a
                # visible loss channel.  The points are NOT added to
                # dropped_points here: the delivery path may have
                # already accounted them (flushed or spilled) before
                # the crash, and a double count would break the ledger.
                self._count("errors")
                logger.exception("egress %s: delivery crashed",
                                 self.label)
            finally:
                self.settle_job(job)

    # -- delivery ----------------------------------------------------------

    def _deliver_job(self, job: EgressJob) -> None:
        statsd = job.statsd
        t0 = time.perf_counter()
        with self._stats_lock:
            self.busy_since = t0
        span = None
        if job.traced and job.trace_id:
            span = trace_rec.continue_span(
                f"flush.sink.{self.name}", job.trace_id,
                job.parent_span_id,
                tags={"sink": self.name, "kind": self.kind,
                      "interval": str(job.interval)})
        try:
            if self.kind == "metric":
                self._deliver_metric(job, statsd, span)
            else:
                self._deliver_span_flush(statsd, span)
        finally:
            wall = time.perf_counter() - t0
            with self._stats_lock:
                self.busy_since = 0.0
                if wall > self.plane.interval_s:
                    # episode count for /debug/vars; the statsd series
                    # (flush.stragglers_total, old in-lock deadline
                    # semantics: one count per interval while a sink is
                    # still running) is emitted by the server's
                    # interval accounting from busy_for_s — which also
                    # catches a delivery that never returns at all
                    self.stragglers += 1
            if span is not None:
                span.finish()
                self.plane.record_span(span)

    def _deliver_metric(self, job: EgressJob, statsd, span) -> None:
        filtered, counts = sink_mod.filter_metrics_for_sink(
            self.spec, self.plane.routing_enabled, job.metrics,
            excluded_tags=self.plane.excluded_tags_for(self.name))
        start = time.perf_counter()
        try:
            # status counts are emitted whether or not delivery lands
            # (a raising sink must not hide what filtering decided)
            for status in ("skipped", "max_name_length", "max_tags",
                           "max_tag_length", "flushed"):
                statsd.count("flushed_metrics", counts.get(status, 0),
                             tags=self.sink_tags + [f"status:{status}"])
            try:
                self.sink.flush_other_samples(job.events)
            except Exception as e:
                self._count("errors")
                statsd.count("flush.sink_errors_total", 1,
                             tags=self.sink_tags)
                logger.error("sink %s flush_other_samples failed: %s",
                             self.name, e)
            self._attempt_flush(filtered, job, statsd, span)
        finally:
            statsd.timing("sink.metric_flush_total_duration_ms",
                          (time.perf_counter() - start) * 1e3,
                          tags=self.sink_tags)
            emit_http_phases(self.sink, self.sink_tags, statsd)

    def _attempt_flush(self, filtered, job: EgressJob, statsd,
                       span) -> None:
        """Bounded-retry delivery under the breaker; exhaustion (or an
        open breaker) spills to the durable spool."""
        retry_idx = 0
        while True:
            if not self.breaker.admit():
                self._spill_or_drop(filtered, job, statsd,
                                    "breaker_open", span)
                return
            aspan = (span.child("egress.attempt",
                                tags={"attempt": str(retry_idx + 1),
                                      "points": str(len(filtered))})
                     if span is not None else None)
            try:
                failpoints.inject("egress.sink")
                result = (self.sink.flush(filtered)
                          or sink_mod.MetricFlushResult())
                self._record_delivered(result, statsd)
                return
            except Exception as e:
                self._count("errors")
                if aspan is not None:
                    aspan.error = True
                    aspan.tags["cause"] = type(e).__name__
                    fp = getattr(e, "failpoint", None)
                    if fp:
                        aspan.tags["failpoint"] = str(fp)
                    # stamp the failure NOW — the finally also finishes
                    # (idempotently) but only after the backoff sleep
                    aspan.finish()
                tripped = self.breaker.record_failure()
                if tripped:
                    self._breaker_event("egress.breaker.open", e)
                if (tripped or self.breaker.state() != "closed"
                        or retry_idx >= self.plane.retry.attempts - 1):
                    statsd.count("flush.sink_errors_total", 1,
                                 tags=self.sink_tags)
                    logger.error("sink %s flush failed after %d "
                                 "attempt(s): %s", self.name,
                                 retry_idx + 1, e)
                    self._spill_or_drop(filtered, job, statsd,
                                        "retries_exhausted", span)
                    return
                self._count("retried")
                statsd.count("egress.retries_total", 1,
                             tags=self.sink_tags)
                delay = self.plane.retry.delay_s(retry_idx, self._rng)
                logger.info("sink %s flush attempt %d failed (%s); "
                            "retrying in %.0f ms", self.name,
                            retry_idx + 1, e, delay * 1e3)
                time.sleep(delay)
                retry_idx += 1
            finally:
                if aspan is not None:
                    aspan.finish()
                    self.plane.record_span(aspan)

    def _record_delivered(self, result, statsd) -> None:
        statsd.count(sink_mod.METRICS_FLUSHED_TOTAL, result.flushed,
                     tags=self.sink_tags)
        statsd.count(sink_mod.METRICS_DROPPED_TOTAL, result.dropped,
                     tags=self.sink_tags)
        self._count("delivered")
        self._count("flushed_points", result.flushed)
        if self.breaker.record_success():
            self._breaker_event("egress.breaker.close", None)
            logger.info("sink %s circuit CLOSED (delivery succeeded)",
                        self.name)

    def _breaker_event(self, name: str, cause) -> None:
        snap = self.breaker.snapshot()
        tags = {"sink": self.name, "failures": snap["failures"],
                "trips": snap["trips"],
                "retry_in_s": snap["retry_in_s"]}
        if cause is not None:
            tags["cause"] = type(cause).__name__
            logger.warning(
                "sink %s circuit OPEN (%s consecutive failures, trip "
                "#%s, retry in %.1fs); spilling to the egress spool",
                self.name, snap["failures"], snap["trips"],
                snap["retry_in_s"])
        trace_rec.event_span(self.plane.recorder, name, tags)

    def _spill_or_drop(self, filtered, job: EgressJob, statsd,
                       cause: str, span) -> None:
        """Exhausted (or breaker-refused) payload: spill to this sink's
        durable spool when one is configured, else drop with
        accounting — never silent."""
        pts = len(filtered)
        if pts == 0:
            return
        if self.spool is not None:
            with self._stats_lock:
                self._spill_seq += 1
                seq = self._spill_seq
            tid = span.trace_id if span is not None else job.trace_id
            sid = span.span_id if span is not None else job.parent_span_id
            body = encode_metrics(list(filtered))
            if self.spool.append((self.name, job.interval, seq), body,
                                 pts, trace_id=tid, span_id=sid):
                statsd.count("egress.spilled_total", pts,
                             tags=self.sink_tags + [f"cause:{cause}"])
                logger.info(
                    "egress %s: spilled %d points of interval %d to "
                    "the spool (%s); background replay will "
                    "re-deliver", self.label, pts, job.interval, cause)
                return
        self._count("dropped_points", pts)
        statsd.count("egress.dropped_total", pts,
                     tags=self.sink_tags + [f"cause:{cause}"])
        logger.warning("egress %s: dropping %d points of interval %d "
                       "(%s, no spool)", self.label, pts,
                       job.interval, cause)

    def _replay_deliver(self, rec, body: bytes) -> None:
        """Spool replay: decode the recorded payload and re-flush it
        under the breaker's half-open discipline.  A sink failure
        keeps the record for the next tick (RetryableReplayError);
        records leave the spool only via delivery or visible expiry —
        except an undecodable payload, which propagates plainly so the
        spool drops it with accounting instead of wedging the queue
        head until expiry."""
        # decode BEFORE the breaker admit: a decode failure must not
        # strand the half-open probe flag
        metrics = decode_metrics(body)
        if not self.breaker.admit():
            raise RetryableReplayError(
                f"egress sink {self.name}: breaker open")
        span = None
        if rec.trace_id:
            span = trace_rec.continue_span(
                "egress.replay", rec.trace_id, rec.span_id,
                tags={"sink": self.name,
                      "interval": str(rec.ident[1]),
                      "points": str(rec.n_metrics)})
        try:
            failpoints.inject("egress.sink")
            result = (self.sink.flush(metrics)
                      or sink_mod.MetricFlushResult())
        except Exception as e:
            if span is not None:
                span.error = True
            self._count("errors")
            if self.breaker.record_failure():
                self._breaker_event("egress.breaker.open", e)
            raise RetryableReplayError(str(e)) from e
        finally:
            if span is not None:
                span.finish()
                self.plane.record_span(span)
        self._count("flushed_points", result.flushed)
        # the reference-compatible per-sink delivery series must count
        # replayed deliveries too, or an outage leaves a permanent
        # hole in sink.metrics_flushed_total that never backfills
        statsd = self.plane.statsd()
        statsd.count(sink_mod.METRICS_FLUSHED_TOTAL, result.flushed,
                     tags=self.sink_tags)
        statsd.count(sink_mod.METRICS_DROPPED_TOTAL, result.dropped,
                     tags=self.sink_tags)
        if self.breaker.record_success():
            self._breaker_event("egress.breaker.close", None)
            logger.info("sink %s circuit CLOSED (replay delivered)",
                        self.name)

    def _deliver_span_flush(self, statsd, span) -> None:
        """One span sink's periodic flush (SpanWorker.Flush,
        worker.go:657-678) — async like metric egress, but span sinks
        buffer internally, so there is no payload to retry or spool."""
        start = time.perf_counter()
        try:
            self.sink.flush()
            self._count("delivered")
        except Exception as e:
            self._count("errors")
            statsd.count("flush.sink_errors_total", 1,
                         tags=self.sink_tags)
            logger.error("span sink %s flush failed: %s", self.name, e)
        finally:
            statsd.timing("worker.span.flush_duration_ns",
                          (time.perf_counter() - start) * 1e9,
                          tags=[f"sink:{self.name}"])
            emit_http_phases(self.sink, self.sink_tags, statsd)

    def stats(self) -> dict:
        with self._stats_lock:
            out = {
                "kind": self.kind,
                "queued": self.queue.qsize(),
                "enqueued": self.enqueued,
                "delivered": self.delivered,
                "flushed_points": self.flushed_points,
                "retried": self.retried,
                "errors": self.errors,
                "queue_dropped_points": self.queue_dropped_points,
                "dropped_points": self.dropped_points,
                "stragglers": self.stragglers,
                # wall seconds the CURRENT delivery has been running
                # (0 = idle): a hung sink.flush shows up here — and in
                # flush.stragglers_total via the server's interval
                # accounting — even though it never completes
                "busy_for_s": round(
                    (time.perf_counter() - self.busy_since)
                    if self.busy_since else 0.0, 3),
            }
        out["breaker"] = self.breaker.snapshot()
        if self.spool is not None:
            out["spool"] = self.spool.stats()
        return out

    def close(self, drain: bool) -> None:
        if self.spool is not None:
            self.spool.close(drain=drain)


class EgressPlane:
    """All of a server's sink lanes plus the shared handoff contract.

    `submit_interval` is the only flush-path entry point: it enqueues
    one job per lane and returns — no filtering, serialization or I/O
    happens under the caller's lock.  `settle` waits for the pending
    job count to hit zero (tests and the graceful-shutdown drain);
    `stats` is the `/debug/vars -> egress` payload, whose spool ledger
    closes exactly (`spilled == replayed + expired + dropped +
    pending`)."""

    def __init__(self, interval_s: float = 10.0, queue_depth: int = 128,
                 retry: Optional[RetryPolicy] = None,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 5.0,
                 spool_dir: str = "",
                 spool_max_bytes: int = 64 << 20,
                 spool_max_age_s: float = 600.0,
                 spool_fsync: str = "rotate",
                 spool_replay_interval_s: float = 0.5,
                 routing_enabled: bool = False,
                 excluded_tags_for: Optional[Callable] = None,
                 recorder=None,
                 statsd_fn: Optional[Callable] = None):
        self.interval_s = float(interval_s)
        self.queue_depth = max(1, int(queue_depth))
        self.retry = retry or RetryPolicy()
        self.breaker_threshold = breaker_threshold
        self.breaker_reset_s = breaker_reset_s
        self.spool_dir = spool_dir
        self.spool_max_bytes = spool_max_bytes
        self.spool_max_age_s = spool_max_age_s
        self.spool_fsync = spool_fsync
        self.spool_replay_interval_s = spool_replay_interval_s
        self.routing_enabled = routing_enabled
        self.excluded_tags_for = excluded_tags_for or (lambda name: None)
        self.recorder = recorder
        # self-metrics client for deliveries with no flush-path job to
        # carry one (spool replays); defaults to a no-op client
        self._statsd_fn = statsd_fn
        self.lanes: list[SinkLane] = []
        self.stopping = threading.Event()
        self._start_lock = threading.Lock()
        self._started = False
        # open jobs across every lane (incremented on submit, closed by
        # settle_job / a queue-full bounce); settle() waits on it
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._pending_zero = threading.Condition(self._pending_lock)

    def statsd(self):
        from veneur_tpu import scopedstatsd
        if self._statsd_fn is not None:
            return scopedstatsd.ensure(self._statsd_fn())
        return scopedstatsd.ensure(None)

    # -- registration ------------------------------------------------------

    def add_metric_sink(self, spec, sink) -> SinkLane:
        spool = None
        if self.spool_dir:
            # keyed by registration ORDER as well as name: two sinks
            # with a colliding name (e.g. two datadog sinks to
            # different endpoints) must never interleave appends into
            # one segment dir or cross-replay each other's payloads.
            # Registration order is config order, so a revived server
            # with the same config maps each lane back to its dir.
            idx = sum(1 for l in self.lanes if l.kind == "metric")
            spool = ForwardSpool(
                os.path.join(self.spool_dir,
                             f"{idx}-{_safe_dirname(sink.name())}"),
                max_bytes=self.spool_max_bytes,
                max_age_s=self.spool_max_age_s,
                fsync=self.spool_fsync,
                replay_interval_s=self.spool_replay_interval_s)
        lane = SinkLane(self, "metric", spec, sink, spool=spool)
        self.lanes.append(lane)
        return lane

    def add_span_sink(self, sink) -> SinkLane:
        lane = SinkLane(self, "span", None, sink)
        self.lanes.append(lane)
        return lane

    # -- lifecycle ---------------------------------------------------------

    def start(self, replayers: bool = True) -> None:
        """Start the lane workers.  `replayers=False` is the lazy
        pre-`Server.start()` form: queued jobs drain, but recovered
        spool records wait for the full start (sinks may not be
        start()ed yet); the full start arms the replayers even when
        the workers were lazily started."""
        with self._start_lock:
            if self._started and not replayers:
                return
            self._started = True
            for lane in self.lanes:
                lane.start(replayers=replayers)

    def job_opened(self) -> None:
        with self._pending_lock:
            self._pending += 1

    def job_closed(self) -> None:
        with self._pending_zero:
            self._pending -= 1
            if self._pending <= 0:
                self._pending_zero.notify_all()

    def record_span(self, span) -> None:
        if self.recorder is not None:
            self.recorder.record_span(span)

    # -- the flush path's handoff ------------------------------------------

    def submit_interval(self, metrics, events, statsd, interval: int,
                        trace_id: int = 0, parent_span_id: int = 0,
                        traced: bool = False) -> None:
        """Enqueue one job per lane and return immediately.  Lanes are
        lazily started so a pre-`start()` flush (tests, tooling) still
        delivers — asynchronously, like every other flush."""
        if not self.lanes:
            return
        if not self._started:
            self.start(replayers=False)
        for lane in self.lanes:
            lane.submit(EgressJob(
                metrics if lane.kind == "metric" else None,
                events, statsd, interval,
                trace_id=trace_id, parent_span_id=parent_span_id,
                traced=traced))

    # -- quiescence / teardown ---------------------------------------------

    def settle(self, timeout_s: float = 10.0) -> bool:
        """Wait until every submitted job has been settled (delivered,
        spilled or dropped-with-accounting).  Does NOT wait for spool
        replay — a blackholed backend's pending records drain on their
        own clock.  Returns False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._pending_zero:
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._pending_zero.wait(remaining)
        return True

    def close(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop the lanes.  `drain` settles queued jobs first and
        fsyncs the spool tails (graceful shutdown); a simulated crash
        passes False — queued jobs die with the process and the spools
        keep their on-disk pending records for the revived instance."""
        if drain:
            self.settle(timeout_s=timeout_s)
        self.stopping.set()
        for lane in self.lanes:
            t = lane._thread
            if t is not None:
                t.join(timeout=1.0)
        for lane in self.lanes:
            lane.close(drain=drain)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The `/debug/vars -> egress` payload: per-sink lanes plus the
        aggregated ledger.  The spool ledger closure — in metric
        POINTS, `spilled + recovered == replayed + expired +
        spool_dropped + pending_points` — holds by construction (each
        lane's ForwardSpool maintains it; `pending` counts records,
        `pending_points` the points inside them)."""
        per_sink = {}
        agg = {"flushed": 0, "retried": 0, "errors": 0,
               "queue_dropped": 0, "dropped": 0, "stragglers": 0,
               "spilled": 0, "recovered": 0, "replayed": 0,
               "expired": 0, "spool_dropped": 0, "pending": 0,
               "pending_points": 0}
        breakers = {}
        ledger_closed = True
        for lane in self.lanes:
            st = lane.stats()
            per_sink[lane.label] = st
            agg["flushed"] += st["flushed_points"]
            agg["retried"] += st["retried"]
            agg["errors"] += st["errors"]
            agg["queue_dropped"] += st["queue_dropped_points"]
            agg["dropped"] += st["dropped_points"]
            agg["stragglers"] += st["stragglers"]
            if lane.kind == "metric":
                breakers[lane.name] = st["breaker"]
            sp = st.get("spool")
            if sp is not None:
                agg["spilled"] += sp["spilled_points"]
                agg["recovered"] += sp["recovered_points"]
                agg["replayed"] += sp["replayed_points"]
                agg["expired"] += sp["expired_points"]
                agg["spool_dropped"] += sp["dropped_points"]
                agg["pending"] += sp["pending_records"]
                agg["pending_points"] += sp["pending_points"]
                # per-lane closure over ONE consistent spool snapshot;
                # records a reopen recovered from a previous process's
                # spill are part of the inflow side
                ledger_closed = ledger_closed and (
                    sp["spilled_points"] + sp["recovered_points"]
                    == sp["replayed_points"] + sp["expired_points"]
                    + sp["dropped_points"] + sp["pending_points"])
        agg["ledger_closed"] = ledger_closed
        agg["breakers"] = breakers
        agg["per_sink"] = per_sink
        return agg
