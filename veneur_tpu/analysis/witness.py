"""Runtime lock witness: observed acquisition-order edges +
held-while-blocking events, cross-validated against the static
lock-order graph.

The static pass (analysis/callgraph.py + rules/lockorder.py) claims to
model every acquired-while-holding edge in the package.  This module
closes the loop with runtime evidence: opt-in wrappers on the NAMED
locks — the same canonical identities the static side uses
(`Server._flush_serial`, `MetricAggregator.lock`, `Destinations._lock`,
`failpoints._lock`, ...) — record, per thread, which locks are held
when another is acquired.  While the testbed chaos matrix runs, every
real interleaving leaves an edge.

The comparator then cross-validates in both directions:

  observed edge NOT in the static graph   -> an ANALYZER GAP: the
        call-graph resolution missed a path reality takes.  The check
        fails loud (`ok: False`); the fix belongs in callgraph.py, not
        in the witness.
  static cycle whose edges are ALL observed -> promoted from
        "potential deadlock" to CONFIRMED HAZARD: both witness chains
        are real interleavings, only scheduling luck separates the
        process from the deadlock.

Held-while-blocking events (a wrapped lock held longer than
`blocking_threshold_s`) are the runtime mirror of
sync-under-lock/blocking-propagation: they name which locks actually
sit across long waits, with the acquire site, so a static suppression
can be re-audited against measured hold times.

Overhead when installed: one thread-local list append/pop plus a dict
increment per acquisition — testbed-grade, not production-default;
nothing is installed unless `install_*` is called.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional

_THIS_FILE = "analysis/witness"


def _caller_site() -> str:
    """Innermost project frame below the witness (first non-witness
    frame when the acquisition comes from outside the package)."""
    import os
    f = sys._getframe(2)
    fallback = "?"
    while f is not None:
        fname = f.f_code.co_filename.replace("\\", "/")
        if _THIS_FILE not in fname:
            if "veneur_tpu" in fname:
                short = fname.split("veneur_tpu/", 1)[-1]
                return f"{short}:{f.f_lineno}"
            if fallback == "?":
                fallback = f"{os.path.basename(fname)}:{f.f_lineno}"
        f = f.f_back
    return fallback


class WitnessLock:
    """A named lock proxy: same blocking semantics as the wrapped lock,
    plus edge/hold recording on the owning LockWitness."""

    __slots__ = ("name", "_inner", "_reg")

    def __init__(self, name: str, inner, reg: "LockWitness"):
        self.name = name
        self._inner = inner
        self._reg = reg

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._reg._on_acquire(self.name, _caller_site())
        return ok

    def release(self) -> None:
        self._reg._on_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class LockWitness:
    """The edge/event registry one witnessed process (or testbed
    cluster) shares across all its wrapped locks."""

    def __init__(self, blocking_threshold_s: float = 0.05):
        self.blocking_threshold_s = blocking_threshold_s
        self._tls = threading.local()
        # registry state guarded by a PLAIN lock (never witnessed)
        self._mu = threading.Lock()
        self._edges: dict[tuple[str, str], int] = {}
        self._edge_sites: dict[tuple[str, str], str] = {}
        self._held_blocking: dict[str, dict] = {}
        self.acquisitions = 0

    # -- recording (hot path) ----------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, name: str, site: str) -> None:
        st = self._stack()
        if st:
            held_names = {h[0] for h in st}
            with self._mu:
                self.acquisitions += 1
                for src in held_names:
                    key = (src, name)
                    self._edges[key] = self._edges.get(key, 0) + 1
                    self._edge_sites.setdefault(key, site)
        else:
            with self._mu:
                self.acquisitions += 1
        st.append((name, time.perf_counter(), site))

    def _on_release(self, name: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == name:
                _, t0, site = st.pop(i)
                held = time.perf_counter() - t0
                if held > self.blocking_threshold_s:
                    with self._mu:
                        ev = self._held_blocking.setdefault(
                            name, {"count": 0, "max_s": 0.0,
                                   "site": site})
                        ev["count"] += 1
                        ev["max_s"] = max(ev["max_s"], held)
                return
        # release of a lock this thread never acquired (cross-thread
        # handoff): nothing to unwind, the inner lock still releases

    # -- wrapping ----------------------------------------------------------

    def wrap(self, obj, attr: str, name: str) -> bool:
        """Replace `obj.attr` with a witnessed proxy; install BEFORE
        any thread contends on the lock (mid-traffic replacement would
        briefly split mutual exclusion across two objects)."""
        cur = getattr(obj, attr, None)
        if cur is None or isinstance(cur, WitnessLock):
            return False
        setattr(obj, attr, WitnessLock(name, cur, self))
        return True

    # -- observation API ---------------------------------------------------

    def observed_edges(self) -> set[tuple[str, str]]:
        with self._mu:
            return set(self._edges)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "acquisitions": self.acquisitions,
                "edges": [
                    {"src": a, "dst": b, "count": n,
                     "site": self._edge_sites.get((a, b), "?")}
                    for (a, b), n in sorted(self._edges.items())],
                "held_blocking": {
                    k: dict(v) for k, v in
                    sorted(self._held_blocking.items())},
            }


# -- install helpers: the canonical identity map -------------------------

def install_server(server, reg: LockWitness) -> None:
    """Wrap the named locks of one core.Server (and its aggregator /
    arenas / native plane / timeline / forwarder).  Names MUST match
    the static pass's canonical identities or the comparison is
    meaningless — that contract is pinned by
    tests/test_lock_witness.py."""
    reg.wrap(server, "_flush_serial", "Server._flush_serial")
    reg.wrap(server, "_events_lock", "Server._events_lock")
    reg.wrap(server, "_proto_lock", "Server._proto_lock")
    reg.wrap(server, "_stream_conns_lock", "Server._stream_conns_lock")
    agg = getattr(server, "aggregator", None)
    if agg is not None:
        reg.wrap(agg, "lock", "MetricAggregator.lock")
        reg.wrap(agg, "_compile_lock", "MetricAggregator._compile_lock")
        for fam in ("digests", "sets", "counters", "gauges", "status"):
            ar = getattr(agg, fam, None)
            if ar is not None:
                reg.wrap(ar, "lock", "_ArenaBase.lock")
    native = getattr(server, "native", None)
    if native is not None:
        reg.wrap(native, "_drain_lock", "NativeIngest._drain_lock")
    tl = getattr(server, "flush_timeline", None)
    if tl is not None:
        reg.wrap(tl, "_lock", "FlushTimeline._lock")
    fwd = getattr(server, "forwarder", None)
    if fwd is not None:
        reg.wrap(fwd, "_stats_lock", "ForwardClient._stats_lock")


def install_proxy(proxy, reg: LockWitness) -> None:
    reg.wrap(proxy, "_stats_lock", "Proxy._stats_lock")
    dest = getattr(proxy, "destinations", None)
    if dest is not None:
        reg.wrap(dest, "_lock", "Destinations._lock")
        reg.wrap(dest, "_reshard_serial",
                 "Destinations._reshard_serial")
    gs = getattr(proxy, "grpc_stats", None)
    if gs is not None:
        reg.wrap(gs, "_lock", "GrpcStats._lock")


def install_failpoints(reg: LockWitness):
    """Wrap the failpoint registry lock and every Failpoint armed from
    now on (configure() is patched to wrap the new instance's _flock).
    Returns an uninstaller restoring both; idempotent."""
    from veneur_tpu import failpoints

    if isinstance(failpoints._lock, WitnessLock):
        return lambda: None
    orig_lock = failpoints._lock
    failpoints._lock = WitnessLock("failpoints._lock", orig_lock, reg)
    orig_configure = failpoints.configure

    def configure(name, action, **kwargs):
        fp = orig_configure(name, action, **kwargs)
        if not isinstance(fp._flock, WitnessLock):
            fp._flock = WitnessLock("Failpoint._flock", fp._flock, reg)
        return fp

    failpoints.configure = configure

    def uninstall() -> None:
        failpoints._lock = orig_lock
        failpoints.configure = orig_configure

    return uninstall


# -- the static/observed comparison --------------------------------------

def compare(static_graph: dict, observed) -> dict:
    """Cross-validate observed edges against the static graph.

    `static_graph` is `ConcurrencyIndex.to_graph_dict()` (or the JSON
    loaded back); `observed` is a LockWitness, its snapshot() dict, or
    a bare edge iterable.  Fails loud (`ok: False`) on any observed
    edge the static graph lacks — an analyzer gap, not a runtime bug —
    and promotes fully-observed static cycles to confirmed hazards."""
    if isinstance(observed, LockWitness):
        snap = observed.snapshot()
        obs = observed.observed_edges()
    elif isinstance(observed, dict):
        snap = observed
        obs = {(e["src"], e["dst"]) for e in observed.get("edges", [])}
    else:
        snap = {"edges": [], "held_blocking": {}}
        obs = {tuple(e) for e in observed}
    static_edges = {(e["src"], e["dst"])
                    for e in static_graph.get("edges", [])}
    sites = {(e["src"], e["dst"]): e.get("site", "?")
             for e in snap.get("edges", [])}
    gaps = sorted(obs - static_edges)
    confirmed = []
    for cyc in static_graph.get("cycles", []):
        cedges = {tuple(e) for e in cyc.get("edges", [])}
        if cedges and cedges <= obs:
            confirmed.append(cyc)
    return {
        "ok": not gaps,
        "gaps": [{"src": a, "dst": b, "site": sites.get((a, b), "?")}
                 for a, b in gaps],
        "confirmed_cycles": confirmed,
        "observed_edges": len(obs),
        "static_edges": len(static_edges),
        "held_blocking": snap.get("held_blocking", {}),
    }


def static_graph(paths=None) -> dict:
    """Build the static lock-order graph for the comparison (default:
    the installed veneur_tpu package — the same tree the witness
    instruments)."""
    from veneur_tpu.analysis import callgraph
    _ctx, idx = callgraph.build_index(paths)
    return idx.to_graph_dict()
