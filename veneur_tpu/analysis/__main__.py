"""CLI: `python -m veneur_tpu.analysis [paths...]`.

Exit status: 0 = clean (no unsuppressed findings), 1 = findings,
2 = bad invocation.  `--json` writes the machine-readable report
(scripts/check.py consumes it); stdout stays human-oriented.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    from veneur_tpu.analysis import engine as engine_mod
    from veneur_tpu.analysis import rules as rules_mod

    ap = argparse.ArgumentParser(
        prog="python -m veneur_tpu.analysis",
        description="vnlint: TPU-hazard static analysis for this repo")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the veneur_tpu "
                         "package)")
    ap.add_argument("--json", metavar="FILE",
                    help="write the JSON findings report here "
                         "('-' = stdout)")
    ap.add_argument("--rules", metavar="R1,R2",
                    help="run only these rules")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule names + descriptions and exit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    ap.add_argument("--emit-graph", metavar="FILE",
                    help="write the whole-program lock-order graph "
                         "(nodes, edges with witness chains, cycles) "
                         "as JSON ('-' = stdout); the static side of "
                         "the runtime lock-witness comparison")
    ap.add_argument("--emit-schema", metavar="FILE",
                    help="write the telemetry schema registry (every "
                         "emitted series + /debug/vars key + ledger) "
                         "as JSON ('-' = stdout); commit it at "
                         "analysis/telemetry_schema.json")
    ap.add_argument("--check-schema", metavar="FILE",
                    help="compare the freshly-extracted telemetry "
                         "schema against this committed artifact; "
                         "exit 1 on drift (the artifact-sync gate)")
    ap.add_argument("--changed-only", metavar="GIT_REF",
                    help="report findings only for files changed vs "
                         "this git ref (plus untracked files); the "
                         "whole tree is still parsed so cross-module "
                         "rules keep the full picture")
    args = ap.parse_args(argv)

    every = rules_mod.all_rules()
    if args.list_rules:
        for r in every:
            print(f"{r.name:18s} {r.description}")
        return 0
    rules = every
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in every}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in every if r.name in wanted]

    changed = None
    if args.changed_only:
        import os
        import subprocess
        try:
            changed = engine_mod.changed_paths(
                args.changed_only,
                (args.paths or [os.getcwd()])[0])
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"--changed-only: {e}", file=sys.stderr)
            return 2

    eng = engine_mod.LintEngine(rules=rules)
    report = eng.run(args.paths or None, changed_only=changed)

    schema_rc = 0
    if args.emit_schema or args.check_schema:
        from veneur_tpu.analysis import telemetry
        # reuse the run's schema when the telemetry-schema rule built
        # one over these modules; else build it fresh from the same
        # parsed tree
        schema = getattr(eng.last_context, "_telemetry_schema", None)
        if schema is None:
            schema = telemetry.build_schema_for_tree(args.paths or None)
        if args.emit_schema:
            telemetry.write_schema(schema, args.emit_schema)
        if args.check_schema:
            try:
                committed = telemetry.load_schema(args.check_schema)
            except (OSError, ValueError) as e:
                print(f"--check-schema: {e}", file=sys.stderr)
                return 2
            if telemetry.schema_fingerprint(committed) != \
                    telemetry.schema_fingerprint(schema):
                print("telemetry schema DRIFT: the committed artifact "
                      f"{args.check_schema} no longer matches the "
                      "tree; regenerate with --emit-schema "
                      f"{args.check_schema}", file=sys.stderr)
                schema_rc = 1
            else:
                print(f"telemetry schema in sync "
                      f"({len(schema['emits'])} emits, "
                      f"{len(schema['debug_vars'])} debug-vars keys, "
                      f"{len(schema['ledgers'])} ledgers)")

    if args.emit_graph:
        import json

        from veneur_tpu.analysis import callgraph
        # reuse the run's parsed modules (and, when the concurrency
        # rules ran, their cached index) — no second parse of the tree
        idx = callgraph.index_for(eng.last_context)
        payload = json.dumps(idx.to_graph_dict(root=report.root),
                             indent=2, sort_keys=True) + "\n"
        if args.emit_graph == "-":
            sys.stdout.write(payload)
        else:
            with open(args.emit_graph, "w", encoding="utf-8") as fh:
                fh.write(payload)

    shown = [f for f in report.findings
             if args.show_suppressed or not f.suppressed]
    for f in shown:
        print(f.format())
    n_bad = len(report.unsuppressed)
    n_sup = sum(f.suppressed for f in report.findings)
    print(f"vnlint: {report.files_scanned} files, "
          f"{n_bad} finding(s), {n_sup} suppressed")
    if args.json:
        payload = report.to_json(indent=2) + "\n"
        if args.json == "-":
            sys.stdout.write(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload)
    return 1 if (n_bad or schema_rc) else 0


if __name__ == "__main__":
    sys.exit(main())
