"""Shared AST helpers for the vnlint rules."""

from __future__ import annotations

import ast
from typing import Iterator, Optional

_PARENT = "_vnlint_parent"


def add_parents(tree: ast.AST) -> None:
    """Attach a parent pointer to every node (walk order is irrelevant;
    each node has exactly one parent in an AST)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            setattr(child, _PARENT, node)


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, _PARENT, None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_function(node: ast.AST):
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def dotted(node: ast.AST) -> Optional[str]:
    """'self.flush_fn.depth_variant' for an Attribute/Name chain, None
    for anything dynamic (calls, subscripts) along the chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def call_func_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def int_tuple(node: ast.expr) -> Optional[tuple[int, ...]]:
    """Resolve a literal donate_argnums-style expression to a tuple of
    ints.  An IfExp (`(0, 1) if donate else ()`) resolves to the UNION
    of its branches — the conservative read for donation analysis."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        out = []
        for elt in node.elts:
            if (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                    and not isinstance(elt.value, bool)):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.IfExp):
        a = int_tuple(node.body)
        b = int_tuple(node.orelse)
        if a is None and b is None:
            return None
        return tuple(sorted(set(a or ()) | set(b or ())))
    return None


_DTYPE_PREFIXES = ("self.", "np.", "jnp.", "numpy.", "onp.", "_np.",
                   "jax.numpy.")


def normalize_dtype_text(text: str) -> str:
    """Canonical comparison form for a dtype-source expression: module
    aliases and `self.` receivers stripped, so `self.digests.eval_dtype`
    and `eval_dtype` read via a local compare equal only when the
    trailing attribute path matches."""
    t = text.strip()
    changed = True
    while changed:
        changed = False
        for p in _DTYPE_PREFIXES:
            if t.startswith(p):
                t = t[len(p):]
                changed = True
    return t


def node_source(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.10
        return "<expr>"


def is_constant_num(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool))
