"""Whole-program concurrency index: interprocedural call graph +
canonical lock identities + lock regions.

The five original vnlint rules are lexical — every concurrency bug this
repo shipped (PR-1 donation race, PR-3 pin leak, PR-6 closed-channel
accounting gap) crossed a function boundary they cannot see.  This
module is the shared substrate the interprocedural rules (lock-order,
blocking-propagation) and the runtime lock-witness comparator build on:

  1. a symbol index over the whole package — classes (incl. nested),
     methods, module functions, with best-effort type inference for
     `self.x` attributes (constructor calls, annotations, known
     parameter names) and locals (assignments, parameter annotations,
     return annotations like `-> "PendingFlush"`);
  2. CANONICAL LOCK IDENTITIES: every `threading.Lock/RLock/Condition`
     bound to an attribute or module global gets one stable name —
     `MetricAggregator.lock`, `Server._flush_serial`,
     `Destinations._lock`, `failpoints._lock`, `_ArenaBase.lock` (the
     arena lock is named for the class that ASSIGNS it, so every arena
     family shares one identity).  `Condition(self._lock)` aliases to
     the wrapped lock's identity.  The runtime witness
     (analysis/witness.py) uses the SAME names, which is what makes
     static-vs-observed edges comparable at all;
  3. per-function lock regions: `with <lock>:` blocks, bare
     `lock.acquire()` (held to end of function; a lexically unmatched
     acquire marks the function as RETURNING WITH THE LOCK HELD, and
     callers extend their held set across the call — the
     `reshard_begin`/`reshard_commit` window), and the `*_locked`
     naming convention (body runs with the CALLER's lock; modeled as a
     pseudo-lock so intra-function rules fire even without a caller in
     the analyzed tree);
  4. call resolution: `self.m()`, `self.attr.m()` via attr types,
     typed locals, module functions, `serving.x` cross-module forms,
     constructors (incl. `with Ctor():` entering `__enter__`/
     `__exit__`), callback attributes bound at construction sites
     (`Destinations(handoff=self._reshard_handoff)`), and a
     unique-method fallback for names defined exactly once
     project-wide (generic names blocklisted);
  5. derived analyses: BLOCKING REACHABILITY (a function that reaches
     `.result()` / `time.sleep` / a device sync through any call chain
     is blocking — lockguard's table, made transitive) and the
     ACQUIRED-WHILE-HOLDING GRAPH whose cycles are potential
     deadlocks, each edge carrying a witness chain (holder function,
     call chain, acquisition site).

Everything here is deterministic: iteration orders are sorted, chains
prefer the first (shortest-first) discovery, and the exported graph
(`to_graph_dict`) is byte-stable across runs for the committed
artifact.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from veneur_tpu.analysis import astutil

# pseudo-lock prefix for the `*_locked` convention: the body runs with
# the caller's lock held, but which one is the caller's business — the
# pseudo entry makes held-set rules fire inside the function itself
# while staying OUT of the lock-order graph (callers contribute the
# real identity through the call chain).
CONVENTION_PREFIX = "*"

_LOCK_CTOR_NAMES = {"Lock", "RLock", "Condition"}

# receiver/parameter names whose project type is unambiguous by
# convention; used only when no stronger evidence (annotation,
# constructor call) exists
_PARAM_TYPE_HINTS = {
    "agg": "MetricAggregator",
    "aggregator": "MetricAggregator",
    "server": "Server",
    "srv": "Server",
    "proxy": "Proxy",
}

# method names too generic for the unique-definition fallback: a
# project-unique `def get` is far more likely to collide with dicts,
# sockets and numpy than to be the real callee
_GENERIC_METHOD_NAMES = {
    "get", "put", "close", "open", "start", "stop", "run", "send",
    "recv", "read", "write", "wait", "join", "items", "keys",
    "values", "append", "extend", "pop", "popleft", "add", "update",
    "clear", "copy", "acquire", "release", "submit", "result", "set",
    "sum", "mean", "min", "max", "count", "index", "insert", "remove",
    "sort", "format", "split", "strip", "encode", "decode", "lower",
    "upper", "startswith", "endswith", "tolist", "astype", "reshape",
    "ravel", "view", "any", "all", "nonzero", "cumsum", "fileno",
    "sendto", "recvfrom", "bind", "listen", "accept", "connect",
    "group", "match", "search", "sub", "findall", "exists", "mkdir",
    "is_set", "locked", "empty", "full", "qsize", "get_nowait",
    "put_nowait", "cancel", "done", "flush",
}

_MAX_CHAIN_DEPTH = 8


@dataclass
class Acquisition:
    lock: str
    line: int
    # locks already held when this acquisition happens (lexically
    # within the same function), innermost last; pseudo-locks included
    held: tuple[tuple[str, int], ...]


@dataclass
class CallSite:
    text: str                  # dotted call text ("self.agg.flush")
    line: int
    col: int
    held: tuple[tuple[str, int], ...]
    callees: tuple["FunctionInfo", ...] = ()


@dataclass
class FunctionInfo:
    qname: str                 # "Server.flush" / "failpoints.inject"
    name: str
    relpath: str
    module_stem: str
    node: ast.AST
    cls: Optional["ClassInfo"] = None
    acquisitions: list[Acquisition] = field(default_factory=list)
    calls: list[CallSite] = field(default_factory=list)
    # direct blocking ops (lockguard's table): (label, line)
    blocking_direct: list[tuple[str, int]] = field(default_factory=list)
    # canonical locks this function acquires/releases WITHOUT a
    # balancing counterpart in its own body (reshard_begin/commit)
    leaves_held: tuple[str, ...] = ()
    releases: tuple[str, ...] = ()


@dataclass
class ClassInfo:
    name: str
    qname: str                 # nested classes: "Outer._CompileGuard"
    relpath: str
    module_stem: str
    bases: list[str] = field(default_factory=list)
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    nested: dict[str, "ClassInfo"] = field(default_factory=dict)
    attr_locks: dict[str, str] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)
    # callback attributes: attr -> candidate methods bound at
    # construction sites ("Destinations(handoff=self._reshard_handoff)")
    attr_callables: dict[str, list[FunctionInfo]] = field(
        default_factory=dict)
    # __init__ parameters assigned verbatim to self.<attr>
    ctor_param_attrs: dict[str, str] = field(default_factory=dict)


def _ann_type_name(node) -> Optional[str]:
    """Best-effort class name from an annotation / ctor expression:
    `Server`, `"PendingFlush"`, `Optional[Proxy]`, `mod.Cls`."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split("[")[0].split(".")[-1].strip("\"' ")
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        # Optional[X] / list[X]: the Optional case is the useful one
        base = astutil.dotted(node.value) or ""
        if base.rsplit(".", 1)[-1] == "Optional":
            return _ann_type_name(node.slice)
    return None


def _lock_ctor(call: ast.Call) -> bool:
    name = astutil.call_func_name(call) or ""
    return name.rsplit(".", 1)[-1] in _LOCK_CTOR_NAMES


class ConcurrencyIndex:
    """Built once per lint run (cached on the ProjectContext) and
    shared by every interprocedural rule."""

    def __init__(self):
        self.classes: dict[str, list[ClassInfo]] = {}   # simple name
        self.functions: list[FunctionInfo] = []
        # (stem, fname) -> FunctionInfo for module-level functions
        self.module_funcs: dict[tuple[str, str], FunctionInfo] = {}
        # stem -> {global name -> canonical lock id}
        self.module_locks: dict[str, dict[str, str]] = {}
        self.methods_by_name: dict[str, list[FunctionInfo]] = {}
        self._reach_memo: dict[int, dict] = {}
        self._block_memo: dict[int, Optional[tuple]] = {}
        self._env_memo: dict[int, dict] = {}
        # bumped whenever a reach/blocking traversal bails on a cycle
        # or the depth cap: results computed under truncation are
        # INCOMPLETE and must not be memoized (a poisoned memo would
        # silently drop edges for every later caller)
        self._truncations = 0
        self.unresolved_calls = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, modules) -> "ConcurrencyIndex":
        idx = cls()
        for mod in modules:
            idx._index_module(mod)
        for mod in modules:
            idx._index_class_attrs(mod)
        # callback bindings need attr/ctor info, so third pass
        for mod in modules:
            idx._index_callback_bindings(mod)
        for fn in idx.functions:
            idx._scan_explicit_acquires(fn)
        for fn in idx.functions:
            idx._walk_function(fn)
        for fn in idx.functions:
            fn.calls = [
                CallSite(cs.text, cs.line, cs.col, cs.held,
                         tuple(idx._resolve_call_text(cs.text, fn)))
                for cs in fn.calls]
        return idx

    def _index_module(self, mod) -> None:
        stem = mod.stem
        self.module_locks.setdefault(stem, {})

        def index_class(node: ast.ClassDef, outer: Optional[ClassInfo]):
            qname = (f"{outer.qname}.{node.name}" if outer
                     else node.name)
            ci = ClassInfo(
                name=node.name, qname=qname, relpath=mod.relpath,
                module_stem=stem,
                bases=[b for b in
                       (astutil.dotted(x) for x in node.bases) if b])
            self.classes.setdefault(node.name, []).append(ci)
            if outer is not None:
                outer.nested[node.name] = ci
            for child in node.body:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    fi = FunctionInfo(
                        qname=f"{qname}.{child.name}", name=child.name,
                        relpath=mod.relpath, module_stem=stem,
                        node=child, cls=ci)
                    ci.methods[child.name] = fi
                    self.functions.append(fi)
                    self.methods_by_name.setdefault(
                        child.name, []).append(fi)
                elif isinstance(child, ast.ClassDef):
                    index_class(child, ci)

        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                index_class(node, None)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                fi = FunctionInfo(
                    qname=f"{stem}.{node.name}", name=node.name,
                    relpath=mod.relpath, module_stem=stem, node=node)
                self.functions.append(fi)
                self.module_funcs[(stem, node.name)] = fi
                self.methods_by_name.setdefault(
                    node.name, []).append(fi)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_locks[stem][tgt.id] = \
                            f"{stem}.{tgt.id}"

    def _index_class_attrs(self, mod) -> None:
        """Second pass: `self.x = ...` assignments in every method of
        every class — lock identities, attribute types, and which ctor
        params land verbatim in attributes."""
        for cls_list in self.classes.values():
            for ci in cls_list:
                if ci.relpath != mod.relpath:
                    continue
                for meth in ci.methods.values():
                    params = self._param_types(meth)
                    is_ctor = meth.name == "__init__"
                    for node in ast.walk(meth.node):
                        if not isinstance(node, (ast.Assign,
                                                 ast.AnnAssign)):
                            continue
                        targets = (node.targets
                                   if isinstance(node, ast.Assign)
                                   else [node.target])
                        ann = (node.annotation
                               if isinstance(node, ast.AnnAssign)
                               else None)
                        pairs: list[tuple] = []
                        for tgt in targets:
                            # `self.agg, self.shape = agg, shape`
                            if isinstance(tgt, (ast.Tuple, ast.List)) \
                                    and isinstance(node.value,
                                                   ast.Tuple) \
                                    and len(tgt.elts) == len(
                                        node.value.elts):
                                pairs.extend(zip(tgt.elts,
                                                 node.value.elts))
                            else:
                                pairs.append((tgt, node.value))
                        for tgt, value in pairs:
                            if not (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                continue
                            self._record_self_attr(
                                ci, tgt.attr, value, ann, params,
                                is_ctor)

    def _record_self_attr(self, ci: ClassInfo, attr: str, value,
                          ann, params: dict[str, str],
                          is_ctor: bool) -> None:
        if isinstance(value, ast.Call) and _lock_ctor(value):
            ctor = (astutil.call_func_name(value) or "").rsplit(
                ".", 1)[-1]
            if ctor == "Condition" and value.args:
                # Condition(self._lock) guards the SAME underlying
                # lock: alias, don't mint a second identity
                inner = astutil.dotted(value.args[0])
                if inner and inner.startswith("self."):
                    wrapped = inner.split(".", 1)[1]
                    if wrapped in ci.attr_locks:
                        ci.attr_locks.setdefault(
                            attr, ci.attr_locks[wrapped])
                        return
            ci.attr_locks.setdefault(attr, f"{ci.name}.{attr}")
            return
        t = None
        if isinstance(value, ast.Call):
            callee = astutil.call_func_name(value) or ""
            simple = callee.rsplit(".", 1)[-1]
            if simple in self.classes:
                t = simple
        elif isinstance(value, ast.Name):
            t = params.get(value.id)
            if is_ctor:
                ci.ctor_param_attrs.setdefault(value.id, attr)
        if t is None and ann is not None:
            n = _ann_type_name(ann)
            if n in self.classes:
                t = n
        if t is not None:
            ci.attr_types.setdefault(attr, t)

    def _param_types(self, fn: FunctionInfo) -> dict[str, str]:
        out: dict[str, str] = {}
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            t = _ann_type_name(a.annotation)
            if t in self.classes:
                out[a.arg] = t
            elif a.arg in _PARAM_TYPE_HINTS \
                    and _PARAM_TYPE_HINTS[a.arg] in self.classes:
                out[a.arg] = _PARAM_TYPE_HINTS[a.arg]
        return out

    def _index_callback_bindings(self, mod) -> None:
        """`Destinations(handoff=self._reshard_handoff)` — when a
        constructor kwarg that the ctor assigns verbatim to an
        attribute is bound to a method reference, that method becomes a
        callee candidate for `self.<attr>(...)` inside the class."""
        for fn in self.functions:
            if fn.relpath != mod.relpath:
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = astutil.call_func_name(node) or ""
                target = self._class_by_name(
                    callee.rsplit(".", 1)[-1], fn.module_stem)
                if target is None:
                    continue
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    attr = target.ctor_param_attrs.get(kw.arg)
                    if attr is None:
                        continue
                    ref = astutil.dotted(kw.value)
                    bound = (self._resolve_method_ref(ref, fn)
                             if ref else None)
                    if bound is not None:
                        cands = target.attr_callables.setdefault(
                            attr, [])
                        if bound not in cands:
                            cands.append(bound)

    # -- symbol resolution -------------------------------------------------

    def _class_by_name(self, name: str,
                       prefer_stem: str) -> Optional[ClassInfo]:
        cands = self.classes.get(name)
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        same = [c for c in cands if c.module_stem == prefer_stem]
        return same[0] if len(same) == 1 else None

    def _mro_lookup(self, ci: ClassInfo, table: str, name: str,
                    _seen=None):
        _seen = _seen if _seen is not None else set()
        if ci.qname in _seen:
            return None
        _seen.add(ci.qname)
        got = getattr(ci, table).get(name)
        if got is not None:
            return got
        for base in ci.bases:
            bc = self._class_by_name(base.rsplit(".", 1)[-1],
                                     ci.module_stem)
            if bc is not None:
                got = self._mro_lookup(bc, table, name, _seen)
                if got is not None:
                    return got
        return None

    def resolve_method(self, ci: ClassInfo,
                       name: str) -> Optional[FunctionInfo]:
        return self._mro_lookup(ci, "methods", name)

    def _ctor_chain(self, ci: ClassInfo) -> list[FunctionInfo]:
        """Calling a class: its __init__ runs; a `with Ctor():` also
        enters __enter__/__exit__ (handled by the caller)."""
        init = self.resolve_method(ci, "__init__")
        return [init] if init is not None else []

    def _local_env(self, fn: FunctionInfo) -> dict[str, str]:
        """name -> project class name for locals with recoverable
        types; conflicting reassignments drop to untyped."""
        cached = self._env_memo.get(id(fn))
        if cached is not None:
            return cached
        env: dict[str, Optional[str]] = dict(self._param_types(fn))
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                pairs = [(t, node.value) for t in node.targets]
            elif isinstance(node, ast.AnnAssign):
                pairs = [(node.target, node.value)]
            else:
                continue
            for tgt, value in pairs:
                if not isinstance(tgt, ast.Name):
                    continue
                t = self._expr_type(value, fn, env)
                if isinstance(node, ast.AnnAssign) and t is None:
                    t = _ann_type_name(node.annotation)
                    if t not in self.classes:
                        t = None
                prev = env.get(tgt.id, "\x00")
                if prev == "\x00":
                    env[tgt.id] = t
                elif prev != t:
                    env[tgt.id] = None
        out = {k: v for k, v in env.items() if v}
        self._env_memo[id(fn)] = out
        return out

    def _expr_type(self, value, fn: FunctionInfo,
                   env: dict) -> Optional[str]:
        if value is None:
            return None
        if isinstance(value, ast.Call):
            callee = astutil.call_func_name(value) or ""
            simple = callee.rsplit(".", 1)[-1]
            if simple in self.classes \
                    and self._class_by_name(simple,
                                            fn.module_stem) is not None:
                return simple
            target = self._resolve_method_ref(callee, fn, env)
            if target is not None:
                ret = getattr(target.node, "returns", None)
                t = _ann_type_name(ret)
                if t in self.classes:
                    return t
            return None
        text = astutil.dotted(value)
        if text is None:
            return None
        parts = text.split(".")
        if parts[0] == "self" and fn.cls is not None:
            if len(parts) == 2:
                return self._mro_lookup(fn.cls, "attr_types", parts[1])
            if len(parts) == 3:
                # `dest = self.proxy.destinations`
                t = self._mro_lookup(fn.cls, "attr_types", parts[1])
                tc = (self._class_by_name(t, fn.module_stem)
                      if t else None)
                if tc is not None:
                    return self._mro_lookup(tc, "attr_types", parts[2])
            return None
        if len(parts) == 1:
            return env.get(parts[0])
        return None

    def _resolve_method_ref(self, text: Optional[str], fn: FunctionInfo,
                            env: Optional[dict] = None
                            ) -> Optional[FunctionInfo]:
        """A *reference* to a function/method (no call): used for
        callback bindings and call resolution alike."""
        if not text:
            return None
        cands = self._resolve_call_text(text, fn, env)
        return cands[0] if len(cands) == 1 else None

    def _resolve_call_text(self, text: Optional[str], fn: FunctionInfo,
                           env: Optional[dict] = None
                           ) -> list[FunctionInfo]:
        if not text:
            self.unresolved_calls += 1
            return []
        parts = text.split(".")
        # self.m() / self.attr.m() / self.NestedClass()
        if parts[0] == "self" and fn.cls is not None:
            if len(parts) == 2:
                m = self.resolve_method(fn.cls, parts[1])
                if m is not None:
                    return [m]
                nested = self._mro_lookup(fn.cls, "nested", parts[1])
                if nested is not None:
                    return self._ctor_chain(nested)
                cbs = self._mro_lookup(fn.cls, "attr_callables",
                                       parts[1])
                if cbs:
                    return list(cbs)
            elif len(parts) == 3:
                t = self._mro_lookup(fn.cls, "attr_types", parts[1])
                tc = (self._class_by_name(t, fn.module_stem)
                      if t else None)
                if tc is not None:
                    m = self.resolve_method(tc, parts[2])
                    if m is not None:
                        return [m]
            return self._unique_fallback(parts[-1])
        if len(parts) == 1:
            name = parts[0]
            mf = self.module_funcs.get((fn.module_stem, name))
            if mf is not None:
                return [mf]
            ci = self._class_by_name(name, fn.module_stem)
            if ci is not None:
                return self._ctor_chain(ci)
            return []          # builtin / imported: out of scope
        if len(parts) == 2:
            base, name = parts
            # module-qualified: serving.fetch, failpoints.inject
            mf = self.module_funcs.get((base, name))
            if mf is not None:
                return [mf]
            bc = self.classes.get(name)
            if base in self.module_locks and bc:
                ci = self._class_by_name(name, base)
                if ci is not None:
                    return self._ctor_chain(ci)
            # ClassName.method (unbound)
            ci = self._class_by_name(base, fn.module_stem)
            if ci is not None:
                m = self.resolve_method(ci, name)
                if m is not None:
                    return [m]
                nested = ci.nested.get(name)
                if nested is not None:
                    return self._ctor_chain(nested)
            # typed local receiver
            env = env if env is not None else self._local_env(fn)
            t = env.get(base)
            tc = self._class_by_name(t, fn.module_stem) if t else None
            if tc is not None:
                m = self.resolve_method(tc, name)
                if m is not None:
                    return [m]
            return self._unique_fallback(name)
        return self._unique_fallback(parts[-1])

    def _unique_fallback(self, name: str) -> list[FunctionInfo]:
        if name in _GENERIC_METHOD_NAMES or name.startswith("__") \
                or len(name) <= 3:
            self.unresolved_calls += 1
            return []
        cands = self.methods_by_name.get(name, [])
        if len(cands) == 1:
            return [cands[0]]
        self.unresolved_calls += 1
        return []

    # -- lock identity -----------------------------------------------------

    def resolve_lock_expr(self, node, fn: FunctionInfo,
                          env: dict) -> Optional[str]:
        """Canonical lock identity for a `with <expr>:` item or an
        explicit `<expr>.acquire()` receiver; None when the expression
        is neither a known lock nor lockish-looking."""
        from veneur_tpu.analysis.rules import lockguard
        text = astutil.dotted(node)
        if text is None:
            if isinstance(node, ast.Call):
                name = astutil.call_func_name(node)
                if lockguard._lockish(name):
                    return f"{fn.module_stem}.{name}()"
            return None
        parts = text.split(".")
        known: Optional[str] = None
        if parts[0] == "self" and fn.cls is not None:
            if len(parts) == 2:
                known = self._mro_lookup(fn.cls, "attr_locks", parts[1])
                if known is None and lockguard._lockish(text):
                    known = f"{fn.cls.name}.{parts[1]}"
            elif len(parts) == 3:
                t = self._mro_lookup(fn.cls, "attr_types", parts[1])
                tc = (self._class_by_name(t, fn.module_stem)
                      if t else None)
                if tc is not None:
                    known = self._mro_lookup(tc, "attr_locks", parts[2])
                if known is None and lockguard._lockish(text):
                    known = f"{t or '?'}.{parts[2]}"
        elif len(parts) == 1:
            known = self.module_locks.get(fn.module_stem,
                                          {}).get(parts[0])
            if known is None and lockguard._lockish(text):
                known = f"{fn.module_stem}.{parts[0]}"
        elif len(parts) == 2:
            known = self.module_locks.get(parts[0], {}).get(parts[1])
            if known is None:
                t = env.get(parts[0])
                tc = (self._class_by_name(t, fn.module_stem)
                      if t else None)
                if tc is not None:
                    known = self._mro_lookup(tc, "attr_locks", parts[1])
                if known is None and lockguard._lockish(text):
                    known = f"{t or fn.module_stem}.{parts[1]}"
        elif lockguard._lockish(text):
            known = f"?{fn.module_stem}:{text}"
        return known

    # -- per-function walk -------------------------------------------------

    def _scan_explicit_acquires(self, fn: FunctionInfo) -> None:
        """Lexically unmatched `X.acquire()` / `X.release()` on known
        locks: `reshard_begin` returns holding `_reshard_serial`,
        `reshard_commit` releases a lock it never acquired."""
        env = self._local_env(fn)
        counts: dict[str, int] = {}
        for node in ast.walk(fn.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("acquire", "release")):
                continue
            lock = self.resolve_lock_expr(node.func.value, fn, env)
            if lock is None:
                continue
            delta = 1 if node.func.attr == "acquire" else -1
            counts[lock] = counts.get(lock, 0) + delta
        fn.leaves_held = tuple(sorted(
            k for k, v in counts.items() if v > 0))
        fn.releases = tuple(sorted(
            k for k, v in counts.items() if v < 0))

    def _walk_function(self, fn: FunctionInfo) -> None:
        from veneur_tpu.analysis.rules import lockguard
        env = self._local_env(fn)
        host_lists = lockguard._host_list_names(fn.node)
        held: list[tuple[str, int]] = []
        if fn.name.endswith("_locked"):
            held.append((CONVENTION_PREFIX + fn.qname,
                         fn.node.lineno))

        def handle_call(call: ast.Call) -> None:
            text = astutil.dotted(call.func)
            label = lockguard._describe_call(call, host_lists)
            if label is not None:
                fn.blocking_direct.append((label, call.lineno))
            if text is None:
                if isinstance(call.func, ast.Attribute):
                    self.unresolved_calls += 1
                return
            fn.calls.append(CallSite(text, call.lineno,
                                     call.col_offset, tuple(held)))
            # explicit acquire/release sequencing within this body
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in ("acquire", "release"):
                lock = self.resolve_lock_expr(call.func.value, fn, env)
                if lock is not None:
                    if call.func.attr == "acquire":
                        fn.acquisitions.append(Acquisition(
                            lock, call.lineno, tuple(held)))
                        held.append((lock, call.lineno))
                    else:
                        for i in range(len(held) - 1, -1, -1):
                            if held[i][0] == lock:
                                del held[i]
                                break
                return
            # a call into a function that RETURNS holding a lock (or
            # that releases one) extends/shrinks the held set for the
            # remainder of this body — the cross-function
            # begin()/commit() window
            cands = self._resolve_call_text(text, fn, env)
            if len(cands) == 1:
                for lock in cands[0].leaves_held:
                    fn.acquisitions.append(Acquisition(
                        lock, call.lineno, tuple(held)))
                    held.append((lock, call.lineno))
                for lock in cands[0].releases:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == lock:
                            del held[i]
                            break

        def visit(node) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return      # deferred execution / new scope
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed_entries: list[tuple[str, int]] = []
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        visit(item.context_expr)
                        # `with Ctor():` also runs __enter__/__exit__
                        text = astutil.dotted(item.context_expr.func)
                        cands = self._resolve_call_text(text, fn, env) \
                            if text else []
                        if len(cands) == 1 \
                                and cands[0].name == "__init__" \
                                and cands[0].cls is not None:
                            for hook in ("__enter__", "__exit__"):
                                m = self.resolve_method(cands[0].cls,
                                                        hook)
                                if m is not None:
                                    fn.calls.append(CallSite(
                                        f"{cands[0].cls.name}.{hook}",
                                        item.context_expr.lineno,
                                        item.context_expr.col_offset,
                                        tuple(held)))
                    lock = self.resolve_lock_expr(item.context_expr,
                                                  fn, env)
                    if lock is not None:
                        fn.acquisitions.append(Acquisition(
                            lock, item.context_expr.lineno,
                            tuple(held)))
                        entry = (lock, item.context_expr.lineno)
                        held.append(entry)
                        pushed_entries.append(entry)
                for stmt in node.body:
                    visit(stmt)
                # remove exactly the entries THIS with pushed (by
                # identity): a bare `.acquire()` or a begin()-style
                # window opened inside the body appends entries that
                # must survive the with-block's exit — popping the
                # tail would release the wrong lock
                for entry in pushed_entries:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i] is entry:
                            del held[i]
                            break
                return
            if isinstance(node, ast.Call):
                handle_call(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.node.body:
            visit(stmt)

    # -- derived analyses --------------------------------------------------

    def reach_acquisitions(self, fn: FunctionInfo, _depth: int = 0,
                           _stack: Optional[set] = None) -> dict:
        """lock -> (call chain of qnames from `fn`, (relpath, line) of
        the acquisition): every lock acquired by `fn` or anything it
        can reach.  Shortest-first; memoized; cycle-safe."""
        memo = self._reach_memo.get(id(fn))
        if memo is not None:
            return memo
        _stack = _stack if _stack is not None else set()
        if id(fn) in _stack or _depth > _MAX_CHAIN_DEPTH:
            self._truncations += 1
            return {}
        _stack.add(id(fn))
        t0 = self._truncations
        out: dict[str, tuple] = {}
        for acq in fn.acquisitions:
            out.setdefault(acq.lock, ((), (fn.relpath, acq.line)))
        for cs in fn.calls:
            for callee in cs.callees:
                sub = self.reach_acquisitions(callee, _depth + 1,
                                              _stack)
                for lock, (chain, site) in sorted(sub.items()):
                    out.setdefault(
                        lock, ((callee.qname,) + chain, site))
        _stack.discard(id(fn))
        if self._truncations == t0:
            # complete traversal only: a cycle-/depth-truncated result
            # cached here would be replayed for callers that could
            # have seen the full reach
            self._reach_memo[id(fn)] = out
        return out

    def blocking_chain(self, fn: FunctionInfo, _depth: int = 0,
                       _stack: Optional[set] = None) -> Optional[tuple]:
        """(chain of qnames, blocking-op label, (relpath, line)) when
        `fn` reaches a blocking operation through any call chain; None
        otherwise."""
        if id(fn) in self._block_memo:
            return self._block_memo[id(fn)]
        _stack = _stack if _stack is not None else set()
        if id(fn) in _stack or _depth > _MAX_CHAIN_DEPTH:
            self._truncations += 1
            return None
        _stack.add(id(fn))
        t0 = self._truncations
        result: Optional[tuple] = None
        if fn.blocking_direct:
            label, line = fn.blocking_direct[0]
            result = ((), label, (fn.relpath, line))
        else:
            best: Optional[tuple] = None
            for cs in fn.calls:
                for callee in cs.callees:
                    sub = self.blocking_chain(callee, _depth + 1,
                                              _stack)
                    if sub is None:
                        continue
                    chain = (callee.qname,) + sub[0]
                    if best is None or len(chain) < len(best[0]):
                        best = (chain, sub[1], sub[2])
            result = best
        _stack.discard(id(fn))
        if self._truncations == t0:
            self._block_memo[id(fn)] = result
        return result

    # -- the lock-order graph ----------------------------------------------

    def lock_order_edges(self) -> dict:
        """(src, dst) -> list of witness dicts.  An edge means: `dst`
        is acquired somewhere while `src` is held — lexically nested,
        or through a call chain from inside `src`'s region."""
        edges: dict[tuple[str, str], list[dict]] = {}

        def add(src: str, dst: str, holder: FunctionInfo, line: int,
                chain: tuple, site: tuple) -> None:
            if src.startswith(CONVENTION_PREFIX):
                return
            wits = edges.setdefault((src, dst), [])
            if len(wits) < 3:
                w = {"holder": holder.qname,
                     "holder_site": f"{holder.relpath}:{line}",
                     "chain": list(chain),
                     "acquire_site": f"{site[0]}:{site[1]}"}
                if w not in wits:
                    wits.append(w)

        for fn in sorted(self.functions, key=lambda f: f.qname):
            for acq in fn.acquisitions:
                for src, line in acq.held:
                    add(src, acq.lock, fn, line, (),
                        (fn.relpath, acq.line))
            for cs in fn.calls:
                if not cs.held:
                    continue
                for callee in cs.callees:
                    for lock, (chain, site) in sorted(
                            self.reach_acquisitions(callee).items()):
                        for src, _line in cs.held:
                            add(src, lock, fn, cs.line,
                                (callee.qname,) + chain, site)
        return edges

    @staticmethod
    def find_cycles(edges: dict) -> list[list[str]]:
        """Cycles in the lock-order graph (potential deadlocks): one
        representative cycle per SCC with >1 node, plus self-loops.
        Deterministic output order."""
        adj: dict[str, set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        # Tarjan SCC, iterative for safety
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v0: str) -> None:
            work = [(v0, iter(sorted(adj[v0])))]
            index[v0] = low[v0] = counter[0]
            counter[0] += 1
            stack.append(v0)
            on.add(v0)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    sccs.append(sorted(scc))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)

        cycles: list[list[str]] = []
        for scc in sccs:
            if len(scc) > 1:
                cycles.append(scc)
            elif (scc[0], scc[0]) in edges:
                cycles.append(scc)
        return sorted(cycles)

    def to_graph_dict(self, root: str = "") -> dict:
        """The exportable lock-order graph: nodes, edges with witness
        chains, cycles — the committed artifact and the witness
        comparator's static side."""
        edges = self.lock_order_edges()
        cycles = self.find_cycles(edges)
        locks = sorted({x for e in edges for x in e}
                       | {acq.lock for fn in self.functions
                          for acq in fn.acquisitions
                          if not acq.lock.startswith(
                              CONVENTION_PREFIX)})
        return {
            "vnlint_lock_graph": 1,
            "root": root,
            "locks": locks,
            "edges": [
                {"src": a, "dst": b, "witnesses": wits}
                for (a, b), wits in sorted(edges.items())],
            "cycles": [
                {"locks": c,
                 "edges": [[a, b] for (a, b) in sorted(edges)
                           if a in c and b in c]}
                for c in cycles],
            "functions": len(self.functions),
            "unresolved_calls": self.unresolved_calls,
        }


def index_for(ctx) -> ConcurrencyIndex:
    """The per-run shared index, cached on the ProjectContext so the
    lock-order and blocking-propagation rules build it once."""
    idx = getattr(ctx, "_concurrency_index", None)
    if idx is None:
        idx = ConcurrencyIndex.build(ctx.modules)
        ctx._concurrency_index = idx
    return idx


def build_index(paths=None):
    """Standalone build over `paths` (default: the veneur_tpu package)
    — the witness comparator's entry point; returns (ProjectContext,
    ConcurrencyIndex).  Discovery/parsing is the engine's own
    (engine.load_modules), so the graph always covers exactly the tree
    the lint run sees."""
    from veneur_tpu.analysis import engine as engine_mod
    eng = engine_mod.LintEngine(rules=[])
    _root, modules, _failures = engine_mod.load_modules(
        paths, eng.known_rules)
    ctx = engine_mod.ProjectContext(modules)
    return ctx, index_for(ctx)
