"""Inline suppression parsing: `# vnlint: disable=<rules> (reason)`.

A suppression applies to findings on its own line; a comment-ONLY line
annotates the next source line (so long findings can carry a readable
rationale above them).  `disable-file=` applies to the whole file.  The
parenthesised reason is MANDATORY: a suppression without one does not
take effect and is itself reported (rule `bad-suppression`, which can
never be suppressed) — an unexplained mute is exactly the kind of
folklore this linter exists to kill.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

SUPPRESS_RE = re.compile(
    r"#\s*vnlint:\s*(disable(?:-file)?)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\- ]+?)\s*"
    r"(?:\((?P<reason>.+)\))?\s*$")

# Loose detector: anything that *tries* to talk to vnlint but fails the
# strict grammar must surface as bad-suppression, not silently lint.
ATTEMPT_RE = re.compile(r"#\s*vnlint\s*:")


@dataclass
class Suppressions:
    """Parsed suppression state for one file."""
    # line -> {rule -> reason}; the line a directive GOVERNS (already
    # shifted for comment-only lines)
    by_line: dict[int, dict[str, str]] = field(default_factory=dict)
    # rule -> reason for file-wide directives
    file_wide: dict[str, str] = field(default_factory=dict)
    # (line, message) for malformed / reasonless directives
    bad: list[tuple[int, str]] = field(default_factory=list)

    def match(self, rule: str, line: int) -> tuple[str, bool] | None:
        """(reason, is_file_wide) iff `rule` is suppressed at `line`
        (file-wide directives take precedence), else None — the ONE
        precedence implementation; the engine uses the kind to track
        which directives are live for the dead-suppression check."""
        reason = self.file_wide.get(rule)
        if reason is not None:
            return reason, True
        reason = self.by_line.get(line, {}).get(rule)
        if reason is not None:
            return reason, False
        return None

    def lookup(self, rule: str, line: int) -> str | None:
        """Reason iff `rule` is suppressed at `line`, else None."""
        got = self.match(rule, line)
        return got[0] if got is not None else None


def _comments(source: str, lines: list[str]) -> dict[int, str]:
    """line -> comment text, from REAL comment tokens only (a
    '# vnlint:' inside a docstring or string literal is prose, not a
    directive).  Falls back to a naive scan if tokenization fails —
    the engine reports syntax errors separately."""
    import io
    import tokenize
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        for i, raw in enumerate(lines, start=1):
            if raw.lstrip().startswith("#"):
                out[i] = raw.strip()
    return out


def parse(source: str, known_rules: set[str]) -> Suppressions:
    sup = Suppressions()
    lines = source.splitlines()
    comments = _comments(source, lines)
    # A directive on a comment-only line may be CONTINUED by further
    # comment-only lines (reason wrapped over several lines); the
    # directive then governs the first non-comment line after the run.
    for i in sorted(comments):
        raw = comments[i]
        if not ATTEMPT_RE.search(raw):
            continue
        comment_only = lines[i - 1].strip().startswith("#")
        m = SUPPRESS_RE.search(raw)
        end = i
        if m is None:
            # possibly a wrapped reason: directive line without the
            # closing paren — join following comment-only lines (works
            # for both the comment-only and the inline trailing form)
            joined, end = _join_comment_run(lines, comments, i)
            m = SUPPRESS_RE.search(joined)
            if m is None:
                sup.bad.append(
                    (i, "malformed vnlint directive (expected "
                        "'# vnlint: disable=<rule,...> (reason)')"))
                continue
        # an inline directive governs its own line; a comment-only one
        # governs the next SOURCE line after the comment run (further
        # commentary/blank lines in between don't swallow it)
        target_line = _next_code_line(lines, end) if comment_only else i
        kind = m.group(1)
        reason = (m.group("reason") or "").strip()
        rules = [r.strip() for r in m.group("rules").split(",")
                 if r.strip()]
        if not reason:
            sup.bad.append(
                (i, f"suppression of {', '.join(rules) or '<none>'} "
                    "has no reason — write "
                    "'# vnlint: disable=<rule> (why this is safe)'"))
            continue
        unknown = [r for r in rules if r not in known_rules]
        if unknown:
            sup.bad.append(
                (i, "suppression names unknown rule(s) "
                    f"{', '.join(unknown)}"))
            rules = [r for r in rules if r in known_rules]
        for r in rules:
            if kind == "disable-file":
                sup.file_wide[r] = reason
            else:
                sup.by_line.setdefault(target_line, {})[r] = reason
    return sup


def _join_comment_run(lines: list[str], comments: dict[int, str],
                      start: int) -> tuple[str, int]:
    """Join the directive comment at 1-based line `start` (comment-only
    OR trailing a statement) with the comment-ONLY lines that follow it
    — the wrapped-reason form — into one directive string; returns
    (joined text, last line of the run)."""
    parts = [comments[start]]
    end = start
    if ")" not in parts[0]:
        for ln in range(start + 1, len(lines) + 1):
            nxt = lines[ln - 1].strip()
            if not nxt.startswith("#"):
                break
            parts.append(nxt.lstrip("#").strip())
            end = ln
            if ")" in nxt:
                break
    return " ".join(parts), end


def _next_code_line(lines: list[str], after: int) -> int:
    """First non-blank, non-comment line after 1-based `after`."""
    for ln in range(after + 1, len(lines) + 1):
        s = lines[ln - 1].strip()
        if s and not s.startswith("#"):
            return ln
    return after + 1
