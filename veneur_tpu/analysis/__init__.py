"""vnlint: TPU-hazard static analysis for this repo.

An AST-based lint engine whose rules target the hazard classes this
codebase has actually shipped and root-caused, so review catches the
next instance instead of production:

  donation-aliasing   a binding donated to a jit/pmap program
                      (donate_argnums) is read again after dispatch
                      without a rebind — the PR-1 set-register donation
                      race (donated sharded lane-update chains read by
                      an in-flight flush: corrupted estimates,
                      interpreter segfaults)
  resource-pairing    acquire/release pairs (set-lane snapshot pins,
                      failpoint arm/disarm, PendingFlush
                      dispatch/emit) whose release is not reachable on
                      error paths — the PR-3 snapshot-pin leak on
                      failed dispatch/fetch paths
  prewarm-parity      prewarm call sites whose abstract signatures
                      (dtype descriptors / static args) match no live
                      flush call site of the same jitted callable —
                      the PR-3 prewarm-signature mismatch that caused
                      an uncovered in-flush XLA recompile
  sync-under-lock     implicit device→host syncs (.item(),
                      block_until_ready, np.asarray, fetch,
                      float(x[...]), PendingFlush.emit) and blocking
                      waits (futures.wait, .result(), time.sleep)
                      inside `with <lock>:` regions or `*_locked`
                      functions — flush-lock stalls that back up the
                      ingest path
  magic-literal       timeouts/retries/backoffs/intervals hard-coded
                      at call sites in forward/, proxy/ and testbed/
                      instead of flowing from config — the PR-4
                      hard-coded-timeout hunt

Two rules are WHOLE-PROGRAM (callgraph.py: interprocedural call graph
+ canonical lock identities), because every concurrency bug this repo
shipped crossed a function boundary:

  lock-order          cycles in the acquired-while-holding graph —
                      lexically nested or through any call chain
                      (including `begin()`/`commit()` windows that
                      return holding a lock); each cycle reports both
                      witness chains as a potential deadlock
  blocking-propagation  sync-under-lock made transitive: a function
                      that REACHES .result()/time.sleep/device sync
                      through any call chain is blocking, and calling
                      it under a lock fires with the full chain
  silent-loss         a pipeline discard path (swallowed except,
                      queue-full branch, discard-named function) that
                      reaches NO accounting increment — statsd count,
                      /debug/vars dict bump, or ledger-field write —
                      within the region or any resolved callee:
                      invisible data loss, the conservation
                      invariant's structural check
  telemetry-schema    the accounting surface itself: emit-site
                      collisions, promised-series drift, and ledger
                      drift against the telemetry schema registry
                      (analysis/telemetry.py; committed artifact
                      analysis/telemetry_schema.json, --emit-schema /
                      --check-schema, runtime-witnessed via
                      `dryrun_3tier.py --telemetry`)

The static lock-order graph is exported (`--emit-graph`; committed at
analysis/lock_order_graph.json) and cross-validated at runtime by the
lock witness (analysis/witness.py): testbed runs record the REAL
acquisition-order edges, an observed edge the graph lacks is an
analyzer gap (fails loud), and a static cycle whose edges are all
observed is a confirmed hazard.

Run it:

    python -m veneur_tpu.analysis                # lint veneur_tpu/
    python -m veneur_tpu.analysis path/ --json out.json
    python -m veneur_tpu.analysis --rules lock-order,blocking-propagation
    python -m veneur_tpu.analysis --emit-graph analysis/lock_order_graph.json

Suppress a finding (the reason is MANDATORY — a reasonless suppression
is itself an error, and a suppression whose governed line no longer
fires its rule is flagged `dead-suppression` so stale mutes expire):

    x = thing()  # vnlint: disable=sync-under-lock (flush lock is meant
                 #   to cover the device wait)

or on its own line above the offending one, or file-wide near the top:

    # vnlint: disable-file=magic-literal (bench driver, not production)

The engine emits a JSON findings report and exits nonzero on any
unsuppressed finding; `tests/test_vnlint.py` pins each rule to a
fixture reproducing its historical bug, and the repo's own lint-clean
state is a tier-1 test.
"""

from __future__ import annotations

from veneur_tpu.analysis.engine import (  # noqa: F401
    BAD_SUPPRESSION,
    Finding,
    LintEngine,
    Report,
    default_target,
    run_paths,
)
from veneur_tpu.analysis.rules import all_rules, rule_names  # noqa: F401
