"""telemetry-schema: the statically-extracted telemetry surface must be
internally consistent.

Three checks over the registry (analysis/telemetry.py) — emit-site
collisions (one series name, conflicting types or provably different
tag shapes), consumer drift (promised series / README references no
site emits), and ledger drift (closure equations referencing fields no
producer writes).  The registry itself is exported with
`python -m veneur_tpu.analysis --emit-schema` and committed at
`analysis/telemetry_schema.json`; artifact sync is a tier-1 test plus
`--check-schema`, exactly like the lock-order graph.
"""

from __future__ import annotations

import os

from veneur_tpu.analysis.engine import Finding, ProjectContext
from veneur_tpu.analysis.rules import Rule


def _site_anchor(site: str) -> tuple[str, int]:
    path, _, line = site.rpartition(":")
    if path and line.isdigit():
        return path, int(line)
    return site, 1


class TelemetrySchema(Rule):
    name = "telemetry-schema"
    description = ("emitted-series collision, promised-series drift, or "
                   "ledger-field drift in the telemetry schema "
                   "registry")

    def finalize(self, ctx: ProjectContext) -> list[Finding]:
        from veneur_tpu.analysis import telemetry
        readme = ""
        if ctx.root:
            cand = os.path.join(os.path.dirname(ctx.root), "README.md")
            if os.path.isfile(cand):
                readme = cand
        schema = telemetry.build_schema(ctx.modules, root=ctx.root,
                                        readme_path=readme)
        # cached for --emit-schema / --check-schema (same parse, same
        # tree — the artifact always matches what this run checked)
        ctx._telemetry_schema = schema
        findings = []
        for issue in telemetry.schema_issues(schema):
            path, line = _site_anchor(issue["site"])
            findings.append(Finding(
                self.name, path, line, 0,
                f"{issue['kind']}: {issue['message']}"))
        return findings
