"""donation-aliasing: read-after-donate on jit/pmap donated arguments.

The PR-1 bug class: a buffer handed to a `jax.jit(...,
donate_argnums=...)` program is XLA's to reuse the moment the call
DISPATCHES — on backends honoring donation the caller's array is dead,
and on PJRT:CPU a sharded donated update chain raced by an in-flight
reader double-frees (corrupted set estimates, interpreter segfaults).
Any later read of the donated binding without an intervening rebind is
therefore a latent race even when today's backend happens to tolerate
it.

Detection is a two-pass, project-wide dataflow sketch:

  collect   every binding of a donated callable — `f = jax.jit(g,
            donate_argnums=(0,))`, `functools.partial(jax.jit,
            donate_argnums=...)(g)` applied or decorating, jax.pmap
            likewise — indexed by (module stem, name) so call sites in
            other modules (`serving.set_lane_scatter`) resolve
  check     per function, statements in source order: a call through a
            donated callable taints the dotted name passed at each
            donated position; a Store to that name (including the
            enclosing `x = f(x)` rebind, because the value is visited
            before the target) clears the taint; a Load while tainted
            is the finding

Conditional aliases (`g = donating if ok else copying`) taint
conservatively — the donating branch COULD run.  Limitations (by
design, documented): control flow is not modeled, so a read textually
before the call inside the same loop body is missed, and reads through
a different alias of the same buffer are invisible.

PERSISTENT device buffers (the ISSUE-16 resident-arena class) extend
the contract across calls: a `self.*` attribute donated to a merge
step outlives the function, so "no later read in this function" is not
safety — the NEXT interval's flush reads the attribute, racing the
program that consumed its buffer.  A donated `self.*` binding still
tainted at function exit is therefore a finding even without an
explicit read; the corrected double-buffer form (`self.buf =
merge(self.buf, ...)`, rebinding the attribute to the program's fresh
output) clears the taint and stays quiet.
"""

from __future__ import annotations

import ast
from typing import Optional

from veneur_tpu.analysis import astutil
from veneur_tpu.analysis.engine import Finding, Module, ProjectContext
from veneur_tpu.analysis.rules import Rule

_JIT_NAMES = {"jit", "pmap"}


def _donate_positions(call: ast.Call) -> Optional[tuple[int, ...]]:
    """donate_argnums of a jax.jit/jax.pmap call expression, or None if
    this call donates nothing."""
    fname = astutil.call_func_name(call.func) if isinstance(
        call.func, ast.Call) else astutil.call_func_name(call)
    kw = astutil.keyword_arg(call, "donate_argnums")
    if kw is None:
        return None
    if fname is None:
        return None
    leaf = fname.rsplit(".", 1)[-1]
    if leaf in _JIT_NAMES:
        tup = astutil.int_tuple(kw)
        # unresolvable donate expression: assume the canonical arg-0
        return tup if tup else (0,)
    if leaf == "partial":
        # functools.partial(jax.jit, ..., donate_argnums=...)
        if call.args and astutil.dotted(call.args[0]) and \
                astutil.dotted(call.args[0]).rsplit(".", 1)[-1] \
                in _JIT_NAMES:
            tup = astutil.int_tuple(kw)
            return tup if tup else (0,)
    return None


def _donating_expr(node: ast.expr) -> Optional[tuple[int, ...]]:
    """Donated positions if `node` evaluates to a donated callable:
    a jit/pmap call with donate_argnums, or `partial(jax.jit,
    donate_argnums=...)(fn)` (partial applied to the target)."""
    if not isinstance(node, ast.Call):
        return None
    pos = _donate_positions(node)
    if pos is not None:
        return pos
    # partial(...)(fn): the donation kwargs live on the inner call
    if isinstance(node.func, ast.Call):
        return _donate_positions(node.func)
    return None


class DonationAliasing(Rule):
    name = "donation-aliasing"
    description = ("donated jit/pmap argument read again after dispatch "
                   "without a rebind (PR-1 donation race class)")

    def __init__(self):
        # (module_stem, name) -> donated positions
        self.registry: dict[tuple[str, str], tuple[int, ...]] = {}

    # -- pass 1 ------------------------------------------------------------

    def collect(self, module: Module, ctx: ProjectContext) -> None:
        for node in module.nodes(ast.Assign, ast.FunctionDef,
                                 ast.AsyncFunctionDef):
            if isinstance(node, ast.Assign):
                pos = _donating_expr(node.value)
                if pos is None:
                    continue
                for tgt in node.targets:
                    name = astutil.dotted(tgt)
                    if name:
                        self.registry[(module.stem,
                                       name.rsplit(".", 1)[-1])] = pos
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    pos = (_donating_expr(dec)
                           if isinstance(dec, ast.Call) else None)
                    if pos is not None:
                        self.registry[(module.stem, node.name)] = pos

    # -- pass 2 ------------------------------------------------------------

    def _resolve(self, expr: ast.expr, module: Module,
                 local_aliases: dict[str, tuple[int, ...]]
                 ) -> Optional[tuple[int, ...]]:
        """Donated positions for a callable expression at a call site."""
        direct = _donating_expr(expr)
        if direct is not None:
            return direct
        if isinstance(expr, ast.IfExp):
            a = self._resolve(expr.body, module, local_aliases)
            b = self._resolve(expr.orelse, module, local_aliases)
            if a is None and b is None:
                return None
            return tuple(sorted(set(a or ()) | set(b or ())))
        name = astutil.dotted(expr)
        if name is None:
            return None
        if name in local_aliases:
            return local_aliases[name]
        parts = name.split(".")
        leaf = parts[-1]
        # same-module binding (module-level or class-level)
        if (module.stem, leaf) in self.registry and len(parts) <= 2:
            # bare name, self.name, or <stem>.name
            if len(parts) == 1 or parts[0] in ("self", module.stem):
                return self.registry[(module.stem, leaf)]
        # cross-module: mod.attr where some scanned module has stem mod
        if len(parts) >= 2:
            stem = parts[-2]
            return self.registry.get((stem, leaf))
        return None

    def check(self, module: Module,
              ctx: ProjectContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            findings.extend(self._check_function(node, module))
        return findings

    def _check_function(self, fn, module: Module) -> list[Finding]:
        findings: list[Finding] = []
        tainted: dict[str, tuple[str, int]] = {}  # name -> (callee, line)
        aliases: dict[str, tuple[int, ...]] = {}

        def clear(name: str) -> None:
            for key in [k for k in tainted
                        if k == name or k.startswith(name + ".")
                        or name.startswith(k + ".")]:
                tainted.pop(key, None)

        def visit(node: ast.AST, toplevel_fn) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not toplevel_fn:
                return  # nested defs run later; out of scope
            if isinstance(node, ast.Assign):
                visit(node.value, toplevel_fn)
                pos = self._resolve(node.value, module, aliases)
                for tgt in node.targets:
                    self._visit_store(tgt, clear, visit, toplevel_fn)
                    name = astutil.dotted(tgt)
                    if name and pos is not None:
                        aliases[name] = pos
                return
            if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None:
                    visit(node.value, toplevel_fn)
                self._visit_store(node.target, clear, visit, toplevel_fn)
                return
            if isinstance(node, ast.NamedExpr):
                visit(node.value, toplevel_fn)
                clear(astutil.dotted(node.target) or "")
                return
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    name = astutil.dotted(tgt)
                    if name:
                        clear(name)
                return
            if isinstance(node, ast.Call):
                for child in ast.iter_child_nodes(node):
                    visit(child, toplevel_fn)
                pos = self._resolve(node.func, module, aliases)
                if pos is not None:
                    callee = (astutil.dotted(node.func)
                              or astutil.node_source(node.func))
                    for p in pos:
                        if p < len(node.args):
                            name = astutil.dotted(node.args[p])
                            if name:
                                tainted[name] = (callee, node.lineno)
                return
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                name = astutil.dotted(node)
                if name:
                    hit_key = name if name in tainted else None
                    if hit_key is None:
                        # a read of a PREFIX chain (e.g. `self` or
                        # `self.obj` when `self.obj.buf` is tainted)
                        # is fine; a read of a LONGER chain through the
                        # tainted buffer is not
                        for tname in tainted:
                            if name.startswith(tname + "."):
                                hit_key = tname
                                break
                    if hit_key is not None:
                        callee, line = tainted.pop(hit_key)
                        findings.append(Finding(
                            self.name, module.relpath, node.lineno,
                            node.col_offset,
                            f"`{name}` was donated to `{callee}` at "
                            f"line {line} and is read again here "
                            "without an intervening rebind/copy — the "
                            "dispatched program may already be reusing "
                            "its buffer (PR-1 donation race class)"))
                        return
                # still walk attribute bases (x.y loads x)
            for child in ast.iter_child_nodes(node):
                visit(child, toplevel_fn)

        for stmt in fn.body:
            visit(stmt, fn)
        # persistent-buffer pass (ISSUE-16 resident arenas): a donated
        # `self.*` attribute outlives this call — if it is still
        # tainted at function exit, the attribute references a buffer
        # the dispatched program owns, and the NEXT call's read races
        # it.  Locals die with the frame, so only self-rooted names
        # fire here.
        for name, (callee, line) in sorted(tainted.items()):
            if not name.startswith("self."):
                continue
            findings.append(Finding(
                self.name, module.relpath, line, 0,
                f"persistent device buffer `{name}` was donated to "
                f"`{callee}` and never rebound before function exit — "
                "the attribute keeps referencing the consumed buffer, "
                "so the next call's read races the dispatched program "
                "(resident-arena donation class); rebind it to the "
                "program's output (`self.buf = merge(self.buf, ...)`) "
                "or use the copying twin"))
        return findings

    @staticmethod
    def _visit_store(tgt: ast.expr, clear, visit, toplevel_fn) -> None:
        """A Store clears taint for the stored dotted name; tuple
        targets recurse; subscript stores evaluate their index
        expressions (Loads) but clear nothing."""
        name = astutil.dotted(tgt)
        if name:
            clear(name)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                DonationAliasing._visit_store(elt, clear, visit,
                                              toplevel_fn)
            return
        visit(tgt, toplevel_fn)
