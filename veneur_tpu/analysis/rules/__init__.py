"""vnlint rule registry.

A rule is three hooks over parsed modules:

    collect(module, ctx)   build cross-module indexes (optional)
    check(module, ctx)     per-module findings
    finalize(ctx)          project-wide findings once every module has
                           been collected (optional)

Adding a rule: subclass Rule in a new module here, set `name` (kebab
case — it is the suppression token) and `description`, implement the
hooks, and append it in `all_rules()`.  Pin it with a fixture pair in
tests/test_vnlint.py: one snippet where it MUST fire, the corrected
form where it must stay quiet.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from veneur_tpu.analysis.engine import Finding, Module, \
        ProjectContext


class Rule:
    name: str = ""
    description: str = ""

    def collect(self, module: "Module", ctx: "ProjectContext") -> None:
        pass

    def check(self, module: "Module",
              ctx: "ProjectContext") -> list["Finding"]:
        return []

    def finalize(self, ctx: "ProjectContext") -> list["Finding"]:
        return []


def all_rules() -> list[Rule]:
    from veneur_tpu.analysis.rules.blocking import BlockingPropagation
    from veneur_tpu.analysis.rules.conservation import SilentLoss
    from veneur_tpu.analysis.rules.donation import DonationAliasing
    from veneur_tpu.analysis.rules.literals import MagicLiteral
    from veneur_tpu.analysis.rules.lockguard import SyncUnderLock
    from veneur_tpu.analysis.rules.lockorder import LockOrder
    from veneur_tpu.analysis.rules.pairing import ResourcePairing
    from veneur_tpu.analysis.rules.prewarm import PrewarmParity
    from veneur_tpu.analysis.rules.telemetry_schema import \
        TelemetrySchema
    return [DonationAliasing(), ResourcePairing(), PrewarmParity(),
            SyncUnderLock(), LockOrder(), BlockingPropagation(),
            SilentLoss(), TelemetrySchema(), MagicLiteral()]


def rule_names() -> list[str]:
    return [r.name for r in all_rules()]
