"""blocking-propagation: sync-under-lock made transitive.

`sync-under-lock` (rules/lockguard.py) sees a DIRECT `.result()` /
`time.sleep` / device-sync call inside a lock region.  This rule makes
the property transitive over the whole-program call graph
(analysis/callgraph.py): a function that *reaches* a blocking
operation through any call chain is itself blocking, and CALLING it
while a lock is held fires — with the full chain printed, so the
report explains exactly how the wait gets under the lock.

Scope discipline vs sync-under-lock: this rule only fires on calls to
PROJECT functions that transitively block (chain length >= 1).  Direct
table matches (`time.sleep(...)` itself, `fut.result()` itself) stay
sync-under-lock findings — the two rules partition the hazard, they
never double-report one site.

Held regions are `with <lock>:` blocks, explicit `.acquire()` windows
(including a callee that RETURNS holding a lock, like
`reshard_begin`), and the bodies of `*_locked`-convention functions
(which run with their caller's lock held).
"""

from __future__ import annotations

from veneur_tpu.analysis import callgraph
from veneur_tpu.analysis.engine import Finding, Module, ProjectContext
from veneur_tpu.analysis.rules import Rule


def _held_name(lock: str) -> str:
    if lock.startswith(callgraph.CONVENTION_PREFIX):
        return (f"the caller's lock (`{lock[1:]}` is a *_locked-"
                "convention function)")
    return f"`{lock}`"


class BlockingPropagation(Rule):
    name = "blocking-propagation"
    description = ("call chain reaching a blocking wait/device sync "
                   "while a lock is held (transitive sync-under-lock)")

    def check(self, module: Module,
              ctx: ProjectContext) -> list[Finding]:
        idx = callgraph.index_for(ctx)
        findings: list[Finding] = []
        seen: set[tuple[int, int]] = set()
        for fn in idx.functions:
            if fn.relpath != module.relpath:
                continue
            for cs in fn.calls:
                if not cs.held or (cs.line, cs.col) in seen:
                    continue
                for callee in cs.callees:
                    bc = idx.blocking_chain(callee)
                    if bc is None:
                        continue
                    chain, label, site = bc
                    hops = " -> ".join((callee.qname,) + chain)
                    lock = cs.held[-1][0]
                    seen.add((cs.line, cs.col))
                    findings.append(Finding(
                        self.name, module.relpath, cs.line, cs.col,
                        f"`{cs.text}(...)` reaches {label} while "
                        f"holding {_held_name(lock)} — chain: {hops} "
                        f"-> {label} at {site[0]}:{site[1]}; the lock "
                        "is held across a wait every queued "
                        "acquirer pays"))
                    break
        return findings
