"""prewarm-parity: prewarm signatures must match a live call site.

The PR-3 bug class: `MetricAggregator.prewarm` AOT-compiled the
general flush program with a weight struct in the STAGING dtype while
the live flush uploaded weights in the EVAL dtype — the prewarmed jit
signature never matched, and the first production flush paid the
multi-second XLA compile inside a flush interval (exactly what prewarm
exists to prevent).  The mismatch is invisible at runtime until a
latency SLO blows; statically it is a comparison of dtype expressions.

Mechanics (project-wide, best-effort):

  collect   * prewarm sites: calls of `<callable>.lower(...)` /
              `.lower_donated(...)` (directly or through a local alias,
              incl. `a if donate else b` picking the donated twin)
              inside any function whose name contains "prewarm";
              positional `jax.ShapeDtypeStruct` args resolve — through
              simple local assignments — to a DTYPE DESCRIPTOR (the
              normalized source text of the dtype expression)
            * live sites: every other call whose canonical callable
              path (`self.` stripped, `_donated` suffixes folded)
              matches a prewarm site's; argument dtype descriptors
              resolve through `x.astype(D)`, `np.zeros(..., D)`,
              `np.asarray(x, D)`, `np.full(..., dtype=D)` and
              ShapeDtypeStruct locals
  finalize  for each prewarm site: among live sites of the same
            callable AND positional arity, every RESOLVED prewarm slot
            descriptor must appear among the live descriptors for that
            slot, and literal static kwargs shared by both sides must
            agree.  A prewarm site whose arity matches no live site at
            all is flagged too — it compiles a program production never
            launches while leaving the real shape uncovered.

Unresolvable descriptors (conditionals, cross-module builders) are
skipped, never guessed: the rule prefers silence to noise, and the
fixture in tests/test_vnlint.py pins the resolvable shape of the
historical bug.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from veneur_tpu.analysis import astutil
from veneur_tpu.analysis.engine import Finding, Module, ProjectContext
from veneur_tpu.analysis.rules import Rule

_LOWER = {"lower", "lower_donated"}


def _canon_callable(text: str) -> str:
    """Canonical callable path: strip `self.`, fold donated twins."""
    parts = [p[:-len("_donated")] if p.endswith("_donated") else p
             for p in text.split(".")]
    if parts and parts[0] == "self":
        parts = parts[1:]
    return ".".join(p for p in parts if p)


@dataclass
class Site:
    module: str
    line: int
    col: int
    key: str
    arity: int
    # slot index -> dtype descriptor (None = unresolved)
    slots: list
    static_kwargs: dict = field(default_factory=dict)


class _Env:
    """Last simple assignment per local name, in source order — enough
    to chase `dt = self.digests.eval_dtype` chains without a real
    dataflow engine."""

    def __init__(self, fn):
        self.assign: dict[str, ast.expr] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name in self.assign:
                    self.assign[name] = _AMBIGUOUS
                else:
                    self.assign[name] = node.value

    def resolve(self, expr: ast.expr, depth: int = 0
                ) -> Optional[ast.expr]:
        if depth > 8 or expr is _AMBIGUOUS:
            return None
        if isinstance(expr, ast.Name):
            nxt = self.assign.get(expr.id)
            if nxt is None or nxt is _AMBIGUOUS:
                return None
            return self.resolve(nxt, depth + 1) or nxt
        return expr


_AMBIGUOUS = ast.Constant(value=...)  # sentinel


def _dtype_descriptor(env: _Env, expr: ast.expr) -> Optional[str]:
    """Descriptor of the dtype SOURCE for an argument expression."""
    resolved = env.resolve(expr) if isinstance(expr, ast.Name) else expr
    if resolved is None:
        return None
    e = resolved
    if isinstance(e, ast.Call):
        fname = astutil.call_func_name(e) or ""
        leaf = fname.rsplit(".", 1)[-1]
        if leaf == "ShapeDtypeStruct" and (len(e.args) >= 2
                                           or astutil.keyword_arg(
                                               e, "dtype")):
            d = (e.args[1] if len(e.args) >= 2
                 else astutil.keyword_arg(e, "dtype"))
            return _dtype_text(env, d)
        if leaf == "astype" and e.args:
            return _dtype_text(env, e.args[0])
        if leaf in ("zeros", "ones", "empty", "full", "asarray",
                    "array"):
            kw = astutil.keyword_arg(e, "dtype")
            if kw is not None:
                return _dtype_text(env, kw)
            if leaf in ("zeros", "ones", "empty") and len(e.args) >= 2:
                return _dtype_text(env, e.args[1])
            if leaf in ("asarray", "array") and len(e.args) >= 2:
                return _dtype_text(env, e.args[1])
    return None


def _dtype_text(env: _Env, expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        chased = env.resolve(expr)
        if chased is not None and not isinstance(chased, ast.IfExp):
            expr = chased
        elif chased is None:
            return None
        else:
            return None  # conditional dtype: never guess
    if isinstance(expr, ast.IfExp):
        return None
    name = astutil.dotted(expr)
    if name is None:
        return None
    return astutil.normalize_dtype_text(name)


def _lower_target(env: _Env, call: ast.Call) -> Optional[str]:
    """Canonical callable key if `call` is a prewarm lowering call."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOWER:
        base = astutil.dotted(fn.value)
        if base is None and isinstance(fn.value, ast.Name):
            base = fn.value.id
        if base is not None:
            resolved = _resolve_alias(env, base)
            return resolved
    if isinstance(fn, ast.Name):
        resolved = _resolve_alias(env, fn.id)
        return resolved
    return None


def _resolve_alias(env: _Env, name: str) -> Optional[str]:
    """Chase `dg = self.f.lower_donated if d else self.f.lower` style
    aliases down to a canonical callable key, or canonicalize a direct
    dotted path that ends in a lower/donated leaf."""

    def canon_expr(e: ast.expr) -> Optional[str]:
        d = astutil.dotted(e)
        if d is None:
            return None
        parts = d.split(".")
        if parts[-1] in _LOWER:
            parts = parts[:-1]
        return _canon_callable(".".join(parts))

    top = name.split(".")[0]
    bound = env.assign.get(top)
    if bound is not None and bound is not _AMBIGUOUS \
            and name == top:
        if isinstance(bound, ast.IfExp):
            a = canon_expr(bound.body)
            b = canon_expr(bound.orelse)
            if a is not None and a == b:
                return a
            return None
        c = canon_expr(bound)
        if c is not None:
            return c
        return None
    # dotted path used directly
    parts = name.split(".")
    if parts[-1] in _LOWER:
        parts = parts[:-1]
    out = _canon_callable(".".join(parts))
    return out or None


class PrewarmParity(Rule):
    name = "prewarm-parity"
    description = ("prewarm abstract signature matches no live call "
                   "site of the same jitted callable (PR-3 in-flush "
                   "recompile class)")

    def __init__(self):
        self.prewarm_sites: list[Site] = []
        self.live_sites: dict[str, list[Site]] = {}

    def collect(self, module: Module, ctx: ProjectContext) -> None:
        for fn in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            env = _Env(fn)
            in_prewarm = "prewarm" in fn.name
            for call in (n for n in ast.walk(fn)
                         if isinstance(n, ast.Call)):
                if astutil.enclosing_function(call) is not fn:
                    continue
                if in_prewarm:
                    key = _lower_target(env, call)
                    is_lower = (isinstance(call.func, ast.Attribute)
                                and call.func.attr in _LOWER) or (
                        isinstance(call.func, ast.Name)
                        and self._alias_is_lowerish(env, call.func.id))
                    if key and is_lower:
                        self.prewarm_sites.append(self._site(
                            module, call, key, env))
                        continue
                self._collect_live(module, fn, env, call)

    @staticmethod
    def _alias_is_lowerish(env: _Env, name: str) -> bool:
        bound = env.assign.get(name)
        if bound is None or bound is _AMBIGUOUS:
            return False
        exprs = ([bound.body, bound.orelse]
                 if isinstance(bound, ast.IfExp) else [bound])
        for e in exprs:
            d = astutil.dotted(e)
            if d is None:
                return False
            leaf = d.rsplit(".", 1)[-1]
            if leaf not in _LOWER and not leaf.endswith("_donated") \
                    and "variant" not in leaf:
                return False
        return True

    def _collect_live(self, module: Module, fn, env: _Env,
                      call: ast.Call) -> None:
        fname = astutil.call_func_name(call)
        if fname is None:
            # alias call: `fn(dvd, depd, pct)` with fn = <ifexp>
            if isinstance(call.func, ast.Name):
                fname = call.func.id
            else:
                return
        if isinstance(call.func, ast.Name):
            resolved = _resolve_alias(env, call.func.id)
            key = resolved if resolved else _canon_callable(fname)
        else:
            parts = fname.split(".")
            if parts[-1] in _LOWER:
                return  # lowering outside prewarm: not a live launch
            key = _canon_callable(fname)
        if not key:
            return
        self.live_sites.setdefault(key, []).append(
            self._site(module, call, key, env))

    @staticmethod
    def _site(module: Module, call: ast.Call, key: str,
              env: _Env) -> Site:
        slots = [_dtype_descriptor(env, a) for a in call.args]
        kwargs = {}
        for kw in call.keywords:
            if kw.arg and isinstance(kw.value, ast.Constant):
                kwargs[kw.arg] = kw.value.value
        return Site(module.relpath, call.lineno, call.col_offset, key,
                    len(call.args), slots, kwargs)

    def finalize(self, ctx: ProjectContext) -> list[Finding]:
        findings: list[Finding] = []
        for pw in self.prewarm_sites:
            lives = self.live_sites.get(pw.key, [])
            if not lives:
                continue  # callable never launched in linted tree
            peers = [lv for lv in lives if lv.arity == pw.arity]
            if not peers:
                findings.append(Finding(
                    self.name, pw.module, pw.line, pw.col,
                    f"prewarm lowers `{pw.key}` with {pw.arity} "
                    "positional args but no live call site of that "
                    "callable has that arity — the compiled program "
                    "can never be the one production launches"))
                continue
            for i, desc in enumerate(pw.slots):
                if desc is None:
                    continue
                live_descs = {lv.slots[i] for lv in peers
                              if lv.slots[i] is not None}
                if live_descs and desc not in live_descs:
                    findings.append(Finding(
                        self.name, pw.module, pw.line, pw.col,
                        f"prewarm builds arg {i} of `{pw.key}` from "
                        f"dtype `{desc}` but live call sites build it "
                        f"from {sorted(live_descs)} — the prewarmed "
                        "signature will never match and the first "
                        "live flush pays the XLA compile (PR-3 "
                        "in-flush recompile)"))
            for kname, kval in pw.static_kwargs.items():
                live_vals = {lv.static_kwargs[kname] for lv in peers
                             if kname in lv.static_kwargs}
                if live_vals and kval not in live_vals:
                    findings.append(Finding(
                        self.name, pw.module, pw.line, pw.col,
                        f"prewarm passes static {kname}={kval!r} to "
                        f"`{pw.key}` but live call sites pass "
                        f"{sorted(map(repr, live_vals))} — distinct "
                        "static args compile distinct programs"))
        return findings
