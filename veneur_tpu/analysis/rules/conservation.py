"""silent-loss: data-discarding statements that reach no accounting.

The system's defining invariant — proven by every chaos arm since the
testbed landed — is "exact conservation or visibly-accounted loss":
any point the pipeline discards (queue full, retry exhaustion, spool
expiry, eviction, a swallowed delivery error) must land in a counter
that joins a ledger closure.  Runtime tests enforce that for the drop
sites that exist TODAY; a new drop site with no accounting compiles,
passes tier 1, and silently breaks the conservation story.  This rule
makes the invariant structural:

  discard sites (pipeline packages only — forward/, proxy/, sources/,
  egress/, sinks/, ingest/ plus the core server/aggregator files):

    * a swallowed `except` body (no re-raise) whose `try` has a
      payload-typed value in flight — the classic "log and lose" shape
      (`except queue.Full` is called out as the queue-full branch)
    * an early `return`/`continue` behind a `.full()` queue test
    * a function NAMED for discarding (`drop`/`evict`/`expire`/
      `discard`/`shed`/`reject` in its name) — the site other code
      trusts to do the accounting

  each site must REACH an accounting increment — a statsd counter emit
  (`statsd.count/incr`), a `/debug/vars`-style dict bump
  (`stats["dropped"] += n`), or a ledger-field write
  (`self.dropped_total += n`, `setattr(self, field, getattr(...) + n)`)
  — within the discard region itself or through any resolved callee
  (the PR-7 call graph), before the path leaves the function.  A
  finding prints the callees it checked, witness-chain style, so the
  report explains where the accounting was expected to be.

Precision notes: handlers for poll/teardown exceptions
(`queue.Empty`, `StopIteration`, `GeneratorExit`, `KeyboardInterrupt`)
never fire; predicate-named functions (`should_drop`, `is_expired`)
are exempt; a `raise` anywhere in the discard region defers the
accounting to the caller and stays quiet.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from veneur_tpu.analysis import astutil, callgraph
from veneur_tpu.analysis.engine import Finding, Module, ProjectContext
from veneur_tpu.analysis.rules import Rule

# pipeline scope: where a discard is a DATA-PLANE loss (an except in a
# bench script or test helper is not conservation-relevant)
_SCOPE_DIRS = {"forward", "proxy", "sources", "egress", "sinks",
               "ingest"}
_SCOPE_FILES = {"core/server.py", "core/aggregator.py",
                "core/arena.py", "core/cardinality.py", "http_api.py"}

# identifier words that mark a payload value (the thing whose loss
# must be accounted) when referenced inside a try body
_PAYLOAD_WORDS = {
    "metric", "metrics", "payload", "payloads", "pb", "pbs", "batch",
    "batches", "chunk", "chunks", "packet", "packets", "line", "lines",
    "sample", "samples", "span", "spans", "record", "records", "rec",
    "job", "jobs", "point", "points", "frame", "frames", "datagram",
    "datagrams", "msg", "message", "messages", "event", "events", "ml",
    "request", "filtered",
}

# identifier words that mark a counter/ledger field
_COUNTER_WORDS = {
    "total", "totals", "count", "counts", "counter", "counters",
    "dropped", "drops", "drop", "expired", "evicted", "errors",
    "skipped", "spilled", "replayed", "failed", "lost", "shed",
    "missed", "duplicates", "recorded", "bounced", "rejected",
    "retries", "retried", "invalid", "malformed", "received",
    "imported", "sent", "delivered", "flushed", "enqueued",
    "stragglers", "torn", "stats",
}

_DISCARD_FN_WORDS = {"drop", "evict", "expire", "discard", "shed",
                     "reject"}
_PREDICATE_PREFIXES = ("should_", "is_", "can_", "has_", "want_")

# handler types that are polling / teardown / fallback control flow,
# not loss: import fallbacks never consume a payload, and
# RetryableReplayError is the spool's KEEP-the-record signal (the
# payload stays queued for the next tick by contract)
_BENIGN_EXC = {"Empty", "StopIteration", "GeneratorExit",
               "KeyboardInterrupt", "SystemExit", "ImportError",
               "ModuleNotFoundError", "RetryableReplayError"}

_WORD_SPLIT = re.compile(r"[^a-zA-Z0-9]+")


def _words(name: str) -> set[str]:
    return {w.lower() for w in _WORD_SPLIT.split(name) if w}


def in_scope(relpath: str) -> bool:
    return (relpath.split("/", 1)[0] in _SCOPE_DIRS
            or relpath in _SCOPE_FILES)


def _mentioned_payloads(node) -> set[str]:
    """Payload words referenced anywhere under `node` (nested function
    definitions excluded — they run later, not on this path)."""
    found: set[str] = set()

    def visit(n) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return
        if isinstance(n, ast.Name):
            found.update(_words(n.id) & _PAYLOAD_WORDS)
        elif isinstance(n, ast.Attribute):
            found.update(_words(n.attr) & _PAYLOAD_WORDS)
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return found


def _counterish(name: Optional[str]) -> bool:
    return bool(name) and bool(_words(name) & _COUNTER_WORDS)


def _target_counterish(tgt) -> bool:
    if isinstance(tgt, ast.Attribute):
        return _counterish(tgt.attr)
    if isinstance(tgt, ast.Name):
        return _counterish(tgt.id)
    if isinstance(tgt, ast.Subscript):
        if isinstance(tgt.slice, ast.Constant) \
                and isinstance(tgt.slice.value, str) \
                and _counterish(tgt.slice.value):
            return True
        return _target_counterish(tgt.value)
    return False


def is_accounting_node(node) -> bool:
    """One AST node that makes the loss VISIBLE: a counter increment, a
    drop-tally write, a dropped-count result, or an error returned to
    the caller (who then owns the retry — an HTTP 4xx/5xx reply or a
    gRPC abort is not silent loss)."""
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in ("incr", "increment"):
                return True
            if attr == "count" and (
                    len(node.args) >= 2
                    or astutil.keyword_arg(node, "tags") is not None):
                return True
            if attr == "abort":     # grpc context.abort -> caller owns it
                return True
        name = astutil.call_func_name(node) or ""
        simple = name.rsplit(".", 1)[-1]
        if simple == "setattr" and len(node.args) == 3:
            # setattr(self, field, getattr(self, field) + n) — the
            # generic ledger-field bump helper shape
            for sub in ast.walk(node.args[2]):
                if isinstance(sub, ast.BinOp) \
                        and isinstance(sub.op, ast.Add):
                    return True
        if simple in ("reply", "_reply"):
            # an error status reported to the sender is accounted loss
            for a in node.args:
                if isinstance(a, ast.Constant) \
                        and isinstance(a.value, int) and a.value >= 400:
                    return True
        if simple.endswith("Result"):
            # `return MetricFlushResult(dropped=len(metrics))` — the
            # egress lane counts the result's drop tally
            for kw in node.keywords:
                if kw.arg and _counterish(kw.arg):
                    return True
        return False
    if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
        return _target_counterish(node.target)
    if isinstance(node, ast.Assign):
        # d[k] = d.get(k, 0) + n  /  dropped = len(lines) - flushed
        if not any(isinstance(sub, ast.BinOp)
                   and isinstance(sub.op, (ast.Add, ast.Sub))
                   for sub in ast.walk(node.value)):
            return False
        return any(_target_counterish(t) for t in node.targets)
    return False


def _region_has(region_stmts, pred) -> bool:
    for stmt in region_stmts:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if pred(node):
                return True
    return False


class SilentLoss(Rule):
    name = "silent-loss"
    description = ("pipeline discard path (swallowed except, queue-full "
                   "branch, discard-named function) reaching no "
                   "accounting increment — invisible data loss")

    # -- interprocedural accounting reach ---------------------------------

    def _fn_accounts(self, fn, idx, _depth: int = 0,
                     _stack: Optional[set] = None) -> bool:
        """Does `fn` (or anything it can reach) increment a counter?"""
        memo = self._memo
        if id(fn) in memo:
            return memo[id(fn)]
        _stack = _stack if _stack is not None else set()
        if id(fn) in _stack or _depth > callgraph._MAX_CHAIN_DEPTH:
            return False
        _stack.add(id(fn))
        out = False
        for node in ast.walk(fn.node):
            if is_accounting_node(node):
                out = True
                break
        if not out:
            for cs in fn.calls:
                for callee in cs.callees:
                    if self._fn_accounts(callee, idx, _depth + 1,
                                         _stack):
                        out = True
                        break
                if out:
                    break
        _stack.discard(id(fn))
        memo[id(fn)] = out
        return out

    def _region_accounts(self, fn_info, segments,
                         idx) -> tuple[bool, list[str]]:
        """(accounted, checked-callee qnames) for a discard region —
        one or more statement segments (e.g. an except body PLUS the
        try's finally, which also runs on the discard path)."""
        spans = []
        for stmts in segments:
            if not stmts:
                continue
            if _region_has(stmts, is_accounting_node):
                return True, []
            spans.append((stmts[0].lineno,
                          max(getattr(s, "end_lineno", s.lineno)
                              for s in stmts)))
        checked: list[str] = []
        if fn_info is not None:
            for cs in fn_info.calls:
                if not any(lo <= cs.line <= hi for lo, hi in spans):
                    continue
                for callee in cs.callees:
                    if self._fn_accounts(callee, idx):
                        return True, checked
                    if callee.qname not in checked:
                        checked.append(callee.qname)
        return False, checked

    # -- the per-module check ---------------------------------------------

    def check(self, module: Module,
              ctx: ProjectContext) -> list[Finding]:
        if not in_scope(module.relpath):
            return []
        idx = callgraph.index_for(ctx)
        self._memo = getattr(ctx, "_silent_loss_memo", None)
        if self._memo is None:
            self._memo = ctx._silent_loss_memo = {}
        fn_by_node = getattr(ctx, "_silent_loss_fns", None)
        if fn_by_node is None:
            fn_by_node = ctx._silent_loss_fns = {
                id(f.node): f for f in idx.functions}

        findings: list[Finding] = []
        findings.extend(self._check_handlers(module, idx, fn_by_node))
        findings.extend(self._check_full_bails(module, idx, fn_by_node))
        findings.extend(self._check_discard_fns(module, idx,
                                                fn_by_node))
        return findings

    def _fn_info_for(self, node, fn_by_node):
        fn_node = astutil.enclosing_function(node)
        return (fn_by_node.get(id(fn_node))
                if fn_node is not None else None)

    @staticmethod
    def _handler_exc_names(handler) -> set[str]:
        t = handler.type
        elts = t.elts if isinstance(t, ast.Tuple) else ([t] if t else [])
        names = set()
        for e in elts:
            text = astutil.dotted(e)
            if text:
                names.add(text.rsplit(".", 1)[-1])
        return names

    def _check_handlers(self, module, idx, fn_by_node) -> list[Finding]:
        findings = []
        for handler in module.nodes(ast.ExceptHandler):
            exc_names = self._handler_exc_names(handler)
            if exc_names and exc_names <= _BENIGN_EXC:
                continue
            # a re-raise (bare or wrapped) defers to the caller
            if _region_has(handler.body,
                           lambda n: isinstance(n, ast.Raise)):
                continue
            try_node = astutil.parent(handler)
            if not isinstance(try_node, ast.Try):
                continue
            payloads = set()
            for stmt in try_node.body:
                payloads |= _mentioned_payloads(stmt)
            if not payloads:
                continue
            fn_info = self._fn_info_for(handler, fn_by_node)
            # the try's finally also runs on the discard path — a
            # close/retire helper there may own the accounting
            ok, checked = self._region_accounts(
                fn_info, [handler.body, try_node.finalbody], idx)
            if ok:
                continue
            kind = ("queue-full branch"
                    if "Full" in exc_names else "swallowed except")
            via = (" — checked callees: " + ", ".join(checked[:4])
                   + " (none reach a counter)" if checked
                   else " — the handler body reaches no counter at "
                        "all")
            findings.append(Finding(
                self.name, module.relpath, handler.lineno,
                handler.col_offset,
                f"{kind} discards in-flight payload "
                f"({', '.join(sorted(payloads)[:4])}) with no "
                f"accounting increment{via}; emit a statsd count, bump "
                "a /debug/vars ledger field, or re-raise"))
        return findings

    def _check_full_bails(self, module, idx,
                          fn_by_node) -> list[Finding]:
        """`if q.full(): return/continue` — the lossy fast path of a
        bounded handoff must account the bounce."""
        findings = []
        for node in module.nodes(ast.If):
            is_full_test = any(
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "full"
                for sub in ast.walk(node.test))
            if not is_full_test:
                continue
            bails = [s for s in node.body
                     if isinstance(s, (ast.Return, ast.Continue))]
            if not bails:
                continue
            fn_info = self._fn_info_for(node, fn_by_node)
            if fn_info is None or not (
                    _words(fn_info.name) & _PAYLOAD_WORDS
                    or _mentioned_payloads(node)):
                continue
            ok, checked = self._region_accounts(
                fn_info, [node.body], idx)
            if ok:
                continue
            via = (" — checked callees: " + ", ".join(checked[:4])
                   if checked else "")
            findings.append(Finding(
                self.name, module.relpath, bails[0].lineno,
                bails[0].col_offset,
                "queue-full bail drops the payload with no accounting "
                f"increment{via}; count the bounce before returning"))
        return findings

    def _check_discard_fns(self, module, idx,
                           fn_by_node) -> list[Finding]:
        """A function NAMED for discarding is the site the rest of the
        code trusts to do the accounting — it must reach a counter."""
        findings = []
        for fn in idx.functions:
            if fn.relpath != module.relpath:
                continue
            if not (_words(fn.name) & _DISCARD_FN_WORDS):
                continue
            if fn.name.startswith(_PREDICATE_PREFIXES):
                continue
            if self._fn_accounts(fn, idx):
                continue
            findings.append(Finding(
                self.name, module.relpath, fn.node.lineno,
                fn.node.col_offset,
                f"`{fn.qname}` is named for discarding data but "
                "neither it nor any resolved callee increments a "
                "counter — eviction/expiry must be visibly accounted"))
        return findings
