"""lock-order: cycles in the whole-program acquired-while-holding graph.

The hazard: thread 1 holds lock A and (possibly through several call
frames) acquires lock B; thread 2 holds B and acquires A.  Neither
thread ever sees both acquisitions on one screen — the PR-6 reshard
window (`reshard_begin` returns holding `_reshard_serial`, the locked
work happens in the CALLER) is exactly the shape an intraprocedural
rule cannot check.

The rule builds the lock-order graph over canonical lock identities
(analysis/callgraph.py): an edge A -> B for every site where B is
acquired while A is held, lexically nested or via any call chain from
inside A's region.  Every cycle is reported ONCE as a potential
deadlock, with one witness chain per edge — the holder function, the
call chain to the acquisition, and the acquisition site — so the
report reads as the two interleavings that deadlock.

The full graph (all edges, cyclic or not) is exported by
`python -m veneur_tpu.analysis --emit-graph` and is the static side of
the runtime lock-witness comparison (analysis/witness.py): an edge the
witness observes at runtime that this graph lacks is an analyzer gap.
"""

from __future__ import annotations

from veneur_tpu.analysis import callgraph
from veneur_tpu.analysis.engine import Finding, ProjectContext
from veneur_tpu.analysis.rules import Rule


def _edge_text(src: str, dst: str, wits: list[dict]) -> str:
    w = wits[0]
    via = (" via " + " -> ".join(w["chain"])) if w["chain"] else ""
    return (f"`{src}` -> `{dst}` (held in {w['holder']} at "
            f"{w['holder_site']}{via}; acquired at "
            f"{w['acquire_site']})")


class LockOrder(Rule):
    name = "lock-order"
    description = ("cycle in the acquired-while-holding graph — two "
                   "threads taking the locks in opposite order "
                   "deadlock (whole-program, call-chain aware)")

    def finalize(self, ctx: ProjectContext) -> list[Finding]:
        idx = callgraph.index_for(ctx)
        edges = idx.lock_order_edges()
        findings: list[Finding] = []
        for cycle in idx.find_cycles(edges):
            cyc_edges = sorted(
                (a, b) for (a, b) in edges
                if a in cycle and b in cycle)
            parts = [_edge_text(a, b, edges[(a, b)])
                     for a, b in cyc_edges]
            # anchor the finding at the first witness's holder site so
            # a reviewed cycle can be suppressed where it is held
            first = edges[cyc_edges[0]][0]
            path, line = first["holder_site"].rsplit(":", 1)
            findings.append(Finding(
                self.name, path, int(line), 0,
                "lock-order cycle over {" + ", ".join(cycle) + "}: "
                + "; ".join(parts)
                + " — opposite-order interleavings deadlock"))
        return findings
