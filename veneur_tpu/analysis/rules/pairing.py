"""resource-pairing: acquire/release pairing on ALL paths, not just the
happy one.

The PR-3 bug class: `SetArena.snapshot_lanes()` pins the lane
registers (lane updates reroute through copying kernels) and the unpin
lived only on the straight-line path — a failed dispatch or fetch
leaked the pin, leaving the copying kernels engaged for the process
lifetime.  The same shape recurs for failpoint arm/disarm and
PendingFlush dispatch/emit (an un-emitted flush never fetches, so the
interval's accounting and the next dispatch's snapshot invariants are
both off).

Per acquire call site the rule demands ONE of:

  - the acquire is the context expression of a `with` (RAII);
  - a matching release in the `finally` of a try enclosing the window,
    or releases on BOTH an except handler and the normal path;
  - the release is chained in the same expression (`acquire().emit()`)
    or is the immediately following statement (nothing in between can
    raise);
  - the acquired value ESCAPES the function — returned, yielded, or
    stored into an attribute/subscript/collection, i.e. ownership
    moves to a peer that the matching release sites consume (the
    snapshot dict handed from `_snapshot_and_reset` to the emit path).

Anything else is a leak-on-exception and gets flagged at the acquire.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from veneur_tpu.analysis import astutil
from veneur_tpu.analysis.engine import Finding, Module, ProjectContext
from veneur_tpu.analysis.rules import Rule


@dataclass(frozen=True)
class PairSpec:
    label: str
    acquires: frozenset
    releases: frozenset
    # substring the dotted callee must contain for BARE-name acquire
    # calls ("configure" alone is too generic — require the failpoints
    # module in the chain, or the failpoints module itself)
    acquire_base_hint: str = ""

    def is_acquire(self, call: ast.Call, module_stem: str) -> bool:
        name = astutil.call_func_name(call)
        if name is None:
            return False
        parts = name.split(".")
        if parts[-1] not in self.acquires:
            return False
        if self.acquire_base_hint:
            base = ".".join(parts[:-1])
            if self.acquire_base_hint not in base \
                    and self.acquire_base_hint not in module_stem:
                return False
        return True

    def is_release(self, call: ast.Call) -> bool:
        name = astutil.call_func_name(call)
        return (name is not None
                and name.split(".")[-1] in self.releases)


PAIRS = (
    PairSpec("set-lane snapshot pin",
             frozenset({"snapshot_lanes"}), frozenset({"unpin_lanes"})),
    PairSpec("failpoint arm",
             frozenset({"configure"}),
             frozenset({"disarm", "clear"}),
             acquire_base_hint="failpoint"),
    PairSpec("pending flush",
             frozenset({"flush_dispatch"}), frozenset({"emit"})),
    # the elastic-reshard window (proxy/destinations.py): begin takes
    # the reshard serial lock and opens the record; an abandoned window
    # (no commit on an error path) wedges every future reshard AND
    # leaves the handoff accounting unpublished
    PairSpec("ring reshard window",
             frozenset({"reshard_begin"}),
             frozenset({"reshard_commit"})),
    # trace span lifetime (trace/, core/server.py flush tracing): a
    # span created via start_span()/client.span()/parent.child() that
    # is never finish()ed on an error path silently drops out of the
    # flight-recorder ring — the interval's trace loses a node and the
    # assembler reports a hole that was really an instrumentation leak.
    # with-RAII (Span.__exit__ finishes, error-flagged), finally
    # releases, immediate finish, and ownership escape all satisfy it.
    PairSpec("trace span",
             frozenset({"start_span", "span", "child"}),
             frozenset({"finish"})),
    # durable-spool segment handle (forward/spool.py): an open_segment
    # that can leak on an error path strands an fd AND leaves the
    # segment's tail un-fsynced — the crash-recovery scan then reads a
    # torn record where a graceful close would have committed it
    PairSpec("spool segment handle",
             frozenset({"open_segment"}),
             frozenset({"close_segment"})),
    # checkpoint tempfile (core/checkpoint.py): the atomic-rename
    # contract — every open_checkpoint_tmp must end in commit (fsync +
    # os.replace) or discard (unlink); a leaked tempfile is a
    # non-atomic checkpoint write, the exact crash-window bug the
    # format exists to prevent
    PairSpec("checkpoint tempfile",
             frozenset({"open_checkpoint_tmp"}),
             frozenset({"commit_checkpoint", "discard_checkpoint"})),
    # egress-queue job handoff (egress/plane.py): a job claimed from a
    # sink lane's queue (claim_job) must be settled (settle_job) on
    # EVERY path — delivered, spilled to the durable spool, or dropped
    # with accounting.  A lost job is silent metric loss AND a stuck
    # pending count that wedges settle()/the shutdown drain forever.
    PairSpec("egress job handoff",
             frozenset({"claim_job"}),
             frozenset({"settle_job"})),
    # process-separated testbed node lifetime (testbed/proccluster.py):
    # every spawn_node (a real OS subprocess with its own spool/
    # checkpoint dirs and log capture) must end in terminate_node
    # (graceful SIGTERM teardown / SIGKILL fault injection) or
    # harvest_node (post-mortem reap of an already-dead child) on ALL
    # paths — a leaked subprocess outlives the test run, holds its
    # ports, and turns every later cell's bind into an EADDRINUSE flake
    PairSpec("proc-cluster node",
             frozenset({"spawn_node"}),
             frozenset({"terminate_node", "harvest_node"})),
    # retention tier-segment handle (retention/spill.py): like the
    # spool's segment handle — an open_tier_segment leaked on an error
    # path strands an fd and leaves the segment tail un-fsynced, so
    # the revive scan reads a torn record where a graceful
    # close_tier_segment would have committed it
    PairSpec("tier segment handle",
             frozenset({"open_tier_segment"}),
             frozenset({"close_tier_segment"})),
)


def _stmt_of(node: ast.AST) -> ast.stmt:
    cur = node
    for anc in astutil.ancestors(node):
        if isinstance(anc, ast.stmt):
            return anc
        cur = anc
    return cur  # pragma: no cover


class ResourcePairing(Rule):
    name = "resource-pairing"
    description = ("acquire without release on error paths: snapshot "
                   "pins, failpoint arms, PendingFlush dispatch/emit, "
                   "reshard windows, trace span start/finish "
                   "(PR-3 pin-leak class)")

    def check(self, module: Module,
              ctx: ProjectContext) -> list[Finding]:
        findings: list[Finding] = []
        for fn in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            for spec in PAIRS:
                findings.extend(self._check_pair(fn, spec, module))
        return findings

    def _check_pair(self, fn, spec: PairSpec,
                    module: Module) -> list[Finding]:
        acquires = [n for n in ast.walk(fn)
                    if isinstance(n, ast.Call)
                    and spec.is_acquire(n, module.stem)
                    and astutil.enclosing_function(n) is fn]
        if not acquires:
            return []
        releases = [n for n in ast.walk(fn)
                    if isinstance(n, ast.Call) and spec.is_release(n)
                    and astutil.enclosing_function(n) is fn]
        out: list[Finding] = []
        for acq in acquires:
            verdict = self._classify(fn, acq, releases, spec)
            if verdict is not None:
                out.append(Finding(
                    self.name, module.relpath, acq.lineno,
                    acq.col_offset,
                    f"{spec.label}: "
                    f"`{astutil.call_func_name(acq)}` {verdict} "
                    f"(release: {'/'.join(sorted(spec.releases))}; "
                    "PR-3 snapshot-pin-leak class — release in a "
                    "finally, or hand the value off)"))
        return out

    def _classify(self, fn, acq: ast.Call, releases: list[ast.Call],
                  spec: PairSpec) -> Optional[str]:
        """None when safely paired, else the complaint text."""
        # chained release in the same expression:
        # self.flush_dispatch(...).emit()
        par = astutil.parent(acq)
        if isinstance(par, ast.Attribute) and par.attr in spec.releases:
            return None
        # `with acquire() as x:` — RAII
        if isinstance(par, ast.withitem):
            return None
        if self._escapes(acq):
            return None
        if not releases:
            # name-flow escape counts ONLY when the function holds no
            # release responsibility of its own: with release calls
            # present, handing the value to a callee does not excuse
            # the missing error-path release (the PIN_LEAK shape
            # passes the pin into the dispatch it protects)
            if self._name_escapes(fn, acq):
                return None
            return ("is acquired but never released in this function, "
                    "and its result does not escape")
        acq_stmt = _stmt_of(acq)
        prot_tries = [t for r in releases
                      for t in [self._protecting_try(fn, r)]
                      if t is not None]
        if prot_tries:
            # the protecting try must BEGIN before anything that can
            # raise, or the window between acquire and try leaks
            first_try = min(prot_tries, key=lambda t: t.lineno)
            if first_try.lineno <= (acq_stmt.end_lineno
                                    or acq_stmt.lineno):
                return None  # acquire itself inside the try
            if self._raisers_between(fn, acq_stmt, first_try, releases):
                return ("is released in a finally/except, but the "
                        "protecting try begins only AFTER other calls "
                        "that can raise — a failure in that window "
                        "leaks the acquire")
            return None
        # releases exist but only on the fall-through path: safe only
        # if nothing between acquire and the first release can raise
        rel_stmts = sorted((_stmt_of(r) for r in releases
                            if r.lineno >= acq.lineno),
                           key=lambda s: s.lineno)
        if not rel_stmts:
            return ("is released only BEFORE the acquire in source "
                    "order — no release is reachable after it")
        first_rel = rel_stmts[0]
        if self._raisers_between(fn, acq_stmt, first_rel, releases):
            return ("is released only on the fall-through path; an "
                    "exception between acquire and release leaks it")
        return None

    @staticmethod
    def _escapes(acq: ast.Call) -> bool:
        """Ownership transfer: the acquired value is returned/yielded,
        stored into an attribute/subscript/collection, or passed
        straight into another call."""
        node: ast.AST = acq
        for anc in astutil.ancestors(acq):
            if isinstance(anc, (ast.Return, ast.Yield, ast.YieldFrom)):
                return True
            if isinstance(anc, ast.Call) and node is not anc.func:
                return True  # argument to another call
            if isinstance(anc, ast.Assign):
                return any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in anc.targets)
            if isinstance(anc, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
                return True
            if isinstance(anc, ast.stmt):
                return False
            node = anc
        return False

    @staticmethod
    def _name_escapes(fn, acq: ast.Call) -> bool:
        """Ownership transfer THROUGH a local name: the acquire is
        assigned to a plain name whose value is later handed off —
        passed as an ARGUMENT to another call (the OpenTracing bridge's
        `span = self.start_span(...); return activate(span, ...)`),
        returned/yielded, or stored into an attribute/subscript/
        collection.  Using the name as a method receiver (`span.add()`)
        is NOT a transfer — the release sites still apply."""
        par = astutil.parent(acq)
        if not (isinstance(par, ast.Assign) and len(par.targets) == 1
                and isinstance(par.targets[0], ast.Name)):
            return False
        name = par.targets[0].id
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name) and node.id == name
                    and isinstance(node.ctx, ast.Load)
                    and node.lineno > par.lineno):
                continue
            anc = astutil.parent(node)
            if isinstance(anc, ast.Call) and node in anc.args:
                return True
            if isinstance(anc, ast.keyword):
                return True
            if isinstance(anc, (ast.Return, ast.Yield, ast.YieldFrom)):
                return True
            if isinstance(anc, (ast.Dict, ast.List, ast.Tuple,
                                ast.Set)):
                return True
            if isinstance(anc, ast.Assign) and any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in anc.targets):
                return True
        return False

    @staticmethod
    def _protecting_try(fn, release: ast.Call):
        """The Try whose finally (or except handler) holds this
        release, or None for a fall-through release."""
        handler = None
        for anc in astutil.ancestors(release):
            if anc is fn:
                return None
            if isinstance(anc, ast.Try):
                if any(ResourcePairing._contains(s, release)
                       for s in anc.finalbody):
                    return anc
                if handler is not None and handler in anc.handlers:
                    return anc
            if isinstance(anc, ast.ExceptHandler):
                handler = anc
        return None

    @staticmethod
    def _contains(tree: ast.AST, target: ast.AST) -> bool:
        return any(n is target for n in ast.walk(tree))

    @staticmethod
    def _raisers_between(fn, acq_stmt: ast.stmt, rel_stmt: ast.stmt,
                         releases: list[ast.Call]) -> bool:
        """Any call or raise strictly between acquire and release (by
        line span, excluding both statements and the release calls
        themselves)?"""
        lo = acq_stmt.end_lineno or acq_stmt.lineno
        hi = rel_stmt.lineno
        release_set = set(map(id, releases))
        for node in ast.walk(fn):
            if isinstance(node, (ast.Call, ast.Raise)) \
                    and id(node) not in release_set:
                line = node.lineno
                if lo < line < hi:
                    return True
        return False
