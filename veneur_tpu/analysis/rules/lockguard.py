"""sync-under-lock: device waits and blocking calls inside lock regions.

The hazard: the aggregator/server locks serialize the INGEST hot path.
A device→host sync (`.item()`, `block_until_ready`, `np.asarray` of a
device array, `serving.fetch`, `float(x[...])`, `PendingFlush.emit`)
or a blocking wait (`concurrent.futures.wait`, future `.result()`,
`time.sleep`, thread `.join(timeout=...)`, `urlopen`) executed while
one is held turns a multi-second XLA compile or a congested PCIe link
into dropped packets.  `Server._flush_locked` is the canonical region:
everything it awaits is time the flush serialization lock is
unavailable.

Lock regions are found lexically:

  - `with <expr>:` where the context expression's dotted name smells
    like a lock (`lock`, `mutex`, `flock`, `serial`, `_cv`) — each
    `with` item is checked independently;
  - whole bodies of functions named `*_locked` (the repo's convention
    for "caller holds the lock").

Nested function definitions inside a region are skipped (they execute
later, not under the lock).  The pattern table errs toward precision:
`np.asarray` of a staged host list is a false positive the suppression
syntax exists for, but generic `.send()`/`.wait()` (generators,
condvars) stay out entirely.
"""

from __future__ import annotations

import ast
import re

from veneur_tpu.analysis import astutil
from veneur_tpu.analysis.engine import Finding, Module, ProjectContext
from veneur_tpu.analysis.rules import Rule

_LOCKISH = re.compile(r"(^|[._])(_?lock|mutex|flock|serial|cv)\b|"
                      r"(^|[._])_?(lock|mutex)$", re.IGNORECASE)


def _lockish(name: str | None) -> bool:
    return bool(name and _LOCKISH.search(name))


_HOST_LITERALS = (ast.List, ast.ListComp, ast.Tuple, ast.Dict,
                  ast.GeneratorExp, ast.Constant)


def _host_list_names(fn) -> set[str]:
    """Names in `fn` whose every assignment is a list/tuple literal or
    comprehension — `np.asarray` of those is a host conversion, not a
    device fetch."""
    assigns: dict[str, bool] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            pairs = []
            for tgt in node.targets:
                if isinstance(tgt, (ast.Tuple, ast.List)) \
                        and isinstance(node.value, ast.Tuple) \
                        and len(node.value.elts) == len(tgt.elts):
                    pairs.extend(zip(tgt.elts, node.value.elts))
                else:
                    pairs.append((tgt, node.value))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            pairs = [(node.target, node.value)]
        else:
            continue
        for t, v in pairs:
            if isinstance(t, ast.Name):
                host = isinstance(v, _HOST_LITERALS)
                assigns[t.id] = assigns.get(t.id, True) and host
    return {n for n, host in assigns.items() if host}


def _describe_call(call: ast.Call, host_lists: set[str]) -> str | None:
    """The matched hazard, or None.  Returns a short label."""
    fname = astutil.call_func_name(call)
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        base = astutil.dotted(call.func.value) or ""
        if attr == "item" and not call.args:
            return "device sync `.item()`"
        if attr == "block_until_ready":
            return "device sync `.block_until_ready()`"
        if attr == "asarray" and base.rsplit(".", 1)[-1] in (
                "np", "numpy", "_np", "onp"):
            arg = call.args[0] if call.args else None
            if isinstance(arg, _HOST_LITERALS):
                return None  # literal/comprehension: host data
            if isinstance(arg, ast.Name) and arg.id in host_lists:
                return None  # provably a host-built list
            return f"host fetch `{base}.asarray(...)`"
        if attr == "device_get":
            return "device sync `jax.device_get(...)`"
        if attr == "fetch" and base.rsplit(".", 1)[-1] == "serving":
            return "device sync `serving.fetch(...)`"
        if attr == "emit" and "pend" in base.lower():
            return f"device wait `{base}.emit()` (PendingFlush fetch)"
        if attr == "wait" and "futures" in base:
            return f"blocking wait `{fname}(...)`"
        if attr == "result":
            return f"blocking future wait `{fname}(...)`"
        if attr == "sleep" and base.rsplit(".", 1)[-1] == "time":
            return "blocking `time.sleep(...)`"
        if attr == "join" and not isinstance(call.func.value,
                                             ast.Constant) \
                and "path" not in base \
                and astutil.keyword_arg(call, "timeout") is not None:
            return f"blocking thread join `{fname}(...)`"
        if attr == "urlopen":
            return "network call `urlopen(...)`"
        return None
    if isinstance(call.func, ast.Name):
        if call.func.id == "fetch":
            return "device sync `fetch(...)`"
        if call.func.id == "urlopen":
            return "network call `urlopen(...)`"
        if call.func.id == "float" and call.args and isinstance(
                call.args[0], ast.Subscript):
            return "device sync `float(<array>[...])`"
    return None


class SyncUnderLock(Rule):
    name = "sync-under-lock"
    description = ("implicit device→host sync or blocking call inside "
                   "a lock region (ingest-stall class)")

    def check(self, module: Module,
              ctx: ProjectContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in module.nodes(ast.With, ast.FunctionDef,
                                 ast.AsyncFunctionDef):
            if isinstance(node, ast.With):
                for item in node.items:
                    name = astutil.dotted(item.context_expr)
                    if name is None and isinstance(
                            item.context_expr, ast.Call):
                        # e.g. `with lock_for(x):` — look at the callee
                        name = astutil.call_func_name(item.context_expr)
                    if _lockish(name):
                        fn = astutil.enclosing_function(node)
                        hosts = _host_list_names(fn) if fn else set()
                        findings.extend(self._scan_region(
                            node.body, module, hosts,
                            f"lock region `with {name}:`"))
                        break
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    and node.name.endswith("_locked"):
                findings.extend(self._scan_region(
                    node.body, module, _host_list_names(node),
                    f"`{node.name}` (runs with the caller's lock "
                    "held)"))
        # dedup: a with-region inside a *_locked function reports once
        # (same call node = same line/col; the region description may
        # differ between the two scans, so it stays out of the key)
        seen: set[tuple[int, int]] = set()
        out = []
        for f in findings:
            k = (f.line, f.col)
            if k not in seen:
                seen.add(k)
                out.append(f)
        return out

    def _scan_region(self, body: list[ast.stmt], module: Module,
                     host_lists: set[str], where: str) -> list[Finding]:
        findings: list[Finding] = []

        def walk(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # deferred execution
            if isinstance(node, ast.Call):
                label = _describe_call(node, host_lists)
                if label is not None:
                    findings.append(Finding(
                        self.name, module.relpath, node.lineno,
                        node.col_offset,
                        f"{label} inside {where} — the lock is held "
                        "across a wait the ingest path may be queued "
                        "behind"))
            for child in ast.iter_child_nodes(node):
                walk(child)

        for stmt in body:
            walk(stmt)
        return findings
