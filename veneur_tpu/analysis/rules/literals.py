"""magic-literal: timeouts/retries/sizes hard-coded at call sites.

The PR-4 bug class: `proxy/connect.py` carried bare `30.0`/`5.0` send
and dial deadlines that had to be hunted down one by one before the
testbed could run sub-second chaos intervals.  Any tuning literal that
bypasses `config.py` (or a named module-level constant) is invisible
to operators and un-overridable by tests.

Scope: the wire-facing trees where the class actually bit —
`forward/`, `proxy/`, `testbed/`.  Flagged:

  - a numeric literal passed as a keyword argument whose name smells
    like tuning (`timeout`, `deadline`, `retry`, `attempts`,
    `backoff`, `interval`, `grace`, `cooldown`, `threshold`,
    `capacity`, `max_*`, `chunk`, `poll`);
  - `time.sleep(<literal>)` above 0.25 s (sub-quarter-second poll
    ticks are loop mechanics, not tuning).

Exempt, because they ARE the named-knob pattern the rule pushes
toward: function-signature defaults, fields of `*Config`/`*Spec`/
`*Policy`/`*Options` class bodies, constructor calls OF such classes,
assignments to UPPER_CASE module constants, and config plumbing calls
(`.get(...)`, `parse_duration(...)`, `min`/`max` clamps).
"""

from __future__ import annotations

import ast
import re

from veneur_tpu.analysis import astutil
from veneur_tpu.analysis.engine import Finding, Module, ProjectContext
from veneur_tpu.analysis.rules import Rule

_SCOPES = ("forward/", "proxy/", "testbed/", "ingest/")
_TUNING_KW = re.compile(
    r"(timeout|deadline|retr(y|ies)|attempt|backoff|interval|grace"
    r"|cooldown|threshold|capacity|max_|chunk|poll|expiry|ttl)",
    re.IGNORECASE)
_CONFIGISH = re.compile(r"(Config|Spec|Policy|Options)$")
_EXEMPT_FUNCS = {"get", "parse_duration", "min", "max", "setdefault"}
_SLEEP_FLOOR = 0.25


def _in_scope(relpath: str) -> bool:
    return any(f"/{s}" in f"/{relpath}" for s in _SCOPES)


class MagicLiteral(Rule):
    name = "magic-literal"
    description = ("tuning literal at a call site bypasses config.py "
                   "(PR-4 hard-coded-timeout class)")

    def check(self, module: Module,
              ctx: ProjectContext) -> list[Finding]:
        if not _in_scope(module.relpath):
            return []
        findings: list[Finding] = []
        exempt_spans = self._exempt_spans(module.tree)
        for call in module.nodes(ast.Call):
            if self._call_exempt(call):
                continue
            if any(lo <= call.lineno <= hi for lo, hi in exempt_spans):
                continue
            findings.extend(self._check_call(call, module))
        return findings

    @staticmethod
    def _exempt_spans(tree: ast.AST) -> list[tuple[int, int]]:
        """Line spans of signature-default lists and config-class
        bodies."""
        spans: list[tuple[int, int]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and _CONFIGISH.search(
                    node.name):
                spans.append((node.lineno,
                              node.end_lineno or node.lineno))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                args = node.args
                defaults = list(args.defaults) + [
                    d for d in args.kw_defaults if d is not None]
                for d in defaults:
                    spans.append((d.lineno, d.end_lineno or d.lineno))
        return spans

    @staticmethod
    def _call_exempt(call: ast.Call) -> bool:
        fname = astutil.call_func_name(call)
        if fname is None:
            return False
        leaf = fname.rsplit(".", 1)[-1]
        return leaf in _EXEMPT_FUNCS or bool(_CONFIGISH.search(leaf))

    def _check_call(self, call: ast.Call,
                    module: Module) -> list[Finding]:
        out: list[Finding] = []
        fname = astutil.call_func_name(call) or "<call>"
        for kw in call.keywords:
            if kw.arg and _TUNING_KW.search(kw.arg) \
                    and astutil.is_constant_num(kw.value) \
                    and kw.value.value != 0:
                out.append(Finding(
                    self.name, module.relpath, kw.value.lineno,
                    kw.value.col_offset,
                    f"`{kw.arg}={kw.value.value!r}` hard-coded at the "
                    f"`{fname}(...)` call site — route it through "
                    "config.py (or a named module constant) so "
                    "operators and tests can tune it (PR-4 timeout "
                    "class)"))
        leaf = fname.rsplit(".", 1)[-1]
        base = fname.rsplit(".", 1)[0] if "." in fname else ""
        if leaf == "sleep" and base in ("time", "") and call.args \
                and astutil.is_constant_num(call.args[0]) \
                and call.args[0].value > _SLEEP_FLOOR:
            out.append(Finding(
                self.name, module.relpath, call.lineno,
                call.col_offset,
                f"`{fname}({call.args[0].value!r})` hard-coded delay — "
                "name it or make it configurable (PR-4 timeout "
                "class)"))
        return out
