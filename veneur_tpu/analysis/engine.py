"""vnlint engine: file discovery, parsing, rule driving, suppression
application, JSON report.

Three passes over the target tree:

  1. parse     every .py file into a `Module` (AST + parent links +
               suppression directives); syntax errors become findings
               (rule `parse-error`) instead of crashes
  2. collect   each rule sees every module and builds project-wide
               indexes (donated callables, prewarm/live call sites) —
               cross-module hazards need the whole picture before any
               verdict
  3. check     per-module rule checks, then project-wide `finalize`
               checks; findings then meet the suppression table

Generated code (`protocol/gen/`) and bytecode caches are skipped.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Iterable, Optional

from veneur_tpu.analysis import astutil, suppress

BAD_SUPPRESSION = "bad-suppression"
DEAD_SUPPRESSION = "dead-suppression"
PARSE_ERROR = "parse-error"

_SKIP_DIR_NAMES = {"__pycache__", ".build", ".git", "testdata"}
_SKIP_REL_PARTS = ("protocol/gen",)


@dataclass
class Finding:
    rule: str
    path: str          # relative to the lint root
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message,
             "suppressed": self.suppressed}
        if self.suppressed:
            d["reason"] = self.reason
        return d

    def format(self) -> str:
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}{tail}")


class Module:
    """One parsed source file."""

    def __init__(self, path: str, relpath: str, source: str,
                 known_rules: set[str]):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        astutil.add_parents(self.tree)
        # ONE walk, shared by every rule: nodes bucketed by exact AST
        # class (plus the flat walk-order list for multi-class scans),
        # so N rules cost one tree traversal, not N
        self._all_nodes = list(ast.walk(self.tree))
        self._node_buckets: dict[type, list] = {}
        for node in self._all_nodes:
            self._node_buckets.setdefault(type(node), []).append(node)
        self.suppressions = suppress.parse(source, known_rules)
        # module stem for cross-module symbol resolution
        # ("serving.set_lane_scatter" -> stem "serving")
        base = os.path.basename(relpath)
        self.stem = ("__init__" if base == "__init__.py"
                     else base[:-3] if base.endswith(".py") else base)
        if self.stem == "__init__":
            # a package __init__ is addressed by its package name
            self.stem = os.path.basename(os.path.dirname(relpath))

    def nodes(self, *types) -> list:
        """All AST nodes of the given class(es), in walk order — the
        shared per-module index (one tree walk at parse time) every
        rule iterates instead of re-walking."""
        if len(types) == 1:
            return self._node_buckets.get(types[0], [])
        return [n for n in self._all_nodes if isinstance(n, types)]


@dataclass
class Report:
    root: str
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def unsuppressed(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            if not f.suppressed:
                out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "vnlint": 1,
            "root": self.root,
            "files_scanned": self.files_scanned,
            "unsuppressed_total": len(self.unsuppressed),
            "suppressed_total": sum(f.suppressed for f in self.findings),
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.findings],
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, **kw)


def default_target() -> str:
    """The package tree itself: `python -m veneur_tpu.analysis` with no
    arguments lints the production code (scripts/bench are drivers;
    lint them by passing their paths explicitly)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def discover(paths: Iterable[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIR_NAMES)
            rel = os.path.relpath(dirpath, p).replace(os.sep, "/")
            if any(part in rel for part in _SKIP_REL_PARTS):
                continue
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.abspath(
                        os.path.join(dirpath, fn)))
    # stable order, no duplicates
    seen: set[str] = set()
    out = []
    for f in files:
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


def load_modules(paths: Optional[Iterable[str]],
                 known_rules: set[str]) -> tuple:
    """Shared discovery + parse (the lint run and the callgraph's
    standalone build must see the SAME tree): returns (abs root,
    modules, failures) where failures is [(relpath, line, message)]
    for unparseable files."""
    targets = list(paths) if paths else [default_target()]
    root = (targets[0] if len(targets) == 1
            and os.path.isdir(targets[0]) else os.getcwd())
    modules: list[Module] = []
    failures: list[tuple[str, int, str]] = []
    for path in discover(targets):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            modules.append(Module(path, rel, src, known_rules))
        except (SyntaxError, UnicodeDecodeError, ValueError) as e:
            failures.append((rel, getattr(e, "lineno", 0) or 0,
                             f"could not parse: {e}"))
    return os.path.abspath(root), modules, failures


class LintEngine:
    def __init__(self, rules: Optional[list] = None):
        from veneur_tpu.analysis.rules import all_rules, rule_names
        self.rules = all_rules() if rules is None else rules
        # suppression directives validate against the FULL registry, not
        # the subset being run: `--rules magic-literal` must not turn
        # the tree's legitimate suppressions of other rules into
        # bad-suppression findings
        self.known_rules = (set(rule_names())
                            | {r.name for r in self.rules}
                            | {BAD_SUPPRESSION, DEAD_SUPPRESSION,
                               PARSE_ERROR})
        # the last run's ProjectContext: --emit-graph reuses it (and
        # any concurrency index the rules cached on it) instead of
        # re-parsing the tree
        self.last_context: Optional[ProjectContext] = None

    def run(self, paths: Optional[Iterable[str]] = None,
            changed_only: Optional[set] = None) -> Report:
        """Lint `paths`.  With `changed_only` (a set of absolute file
        paths — the `--changed-only <git-ref>` incremental mode), the
        WHOLE tree is still parsed and collected (cross-module rules
        need the full picture), but only findings anchored in changed
        files are reported."""
        root, modules, failures = load_modules(paths, self.known_rules)
        report = Report(root=root)
        for rel, line, msg in failures:
            report.findings.append(Finding(PARSE_ERROR, rel, line, 0,
                                           msg))
        report.files_scanned = len(modules)

        ctx = self.last_context = ProjectContext(modules, root=root)
        for rule in self.rules:
            for mod in modules:
                rule.collect(mod, ctx)
        raw: list[Finding] = []
        for rule in self.rules:
            for mod in modules:
                raw.extend(rule.check(mod, ctx))
            raw.extend(rule.finalize(ctx))

        # suppression application + bad-suppression surfacing.  Used
        # directives are tracked so a suppression whose governed line
        # no longer fires its rule surfaces as dead-suppression — a
        # stale mute rots into folklore exactly like a reasonless one.
        by_rel = {m.relpath: m for m in modules}
        used_line: set[tuple[str, int, str]] = set()
        used_file: set[tuple[str, str]] = set()
        for f in raw:
            mod = by_rel.get(f.path)
            if mod is not None:
                got = mod.suppressions.match(f.rule, f.line)
                if got is not None:
                    reason, file_wide = got
                    if file_wide:
                        used_file.add((f.path, f.rule))
                        # a line-level directive layered under the
                        # file-wide one still governs a REAL finding on
                        # its line: it is live, not dead, even though
                        # the file-wide reason won precedence
                        if f.rule in mod.suppressions.by_line.get(
                                f.line, {}):
                            used_line.add((f.path, f.line, f.rule))
                    else:
                        used_line.add((f.path, f.line, f.rule))
                    f.suppressed = True
                    f.reason = reason
            report.findings.append(f)
        ran = {r.name for r in self.rules}
        for mod in modules:
            for line, msg in mod.suppressions.bad:
                report.findings.append(Finding(
                    BAD_SUPPRESSION, mod.relpath, line, 0, msg))
            # dead suppressions: only judged for rules that actually
            # RAN (a --rules subset must not flag the tree's
            # suppressions of unselected rules as dead)
            for line, rules in sorted(mod.suppressions.by_line.items()):
                for rule_name, reason in sorted(rules.items()):
                    if rule_name in ran and \
                            (mod.relpath, line, rule_name) not in \
                            used_line:
                        report.findings.append(Finding(
                            DEAD_SUPPRESSION, mod.relpath, line, 0,
                            f"suppression of {rule_name} no longer "
                            "matches a finding on its line — the "
                            "suppressed code moved or was fixed; "
                            "delete the directive (stale reason: "
                            f"{reason})"))
            for rule_name, reason in sorted(
                    mod.suppressions.file_wide.items()):
                if rule_name in ran and \
                        (mod.relpath, rule_name) not in used_file:
                    report.findings.append(Finding(
                        DEAD_SUPPRESSION, mod.relpath, 1, 0,
                        f"file-wide suppression of {rule_name} "
                        "suppresses nothing — delete the directive "
                        f"(stale reason: {reason})"))
        if changed_only is not None:
            changed_rel = {os.path.relpath(p, root).replace(os.sep, "/")
                           for p in changed_only}
            # telemetry-schema findings are PROJECT-WIDE and often
            # anchor outside the changed set (README.md, the registry
            # module): a consumer-drift caused by deleting an emit in a
            # changed file must not vanish in incremental mode
            report.findings = [f for f in report.findings
                               if f.path in changed_rel
                               or f.rule == "telemetry-schema"]
        report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return report


class ProjectContext:
    """Cross-module state shared by the rules; each rule namespaces its
    own entries under an attribute it owns."""

    def __init__(self, modules: list[Module], root: str = ""):
        self.modules = modules
        self.root = root
        self.by_stem: dict[str, list[Module]] = {}
        for m in modules:
            self.by_stem.setdefault(m.stem, []).append(m)


def changed_paths(ref: str, root: str) -> set[str]:
    """Absolute paths of .py files changed vs `ref` (plus untracked
    ones) — the `--changed-only` working set.  Raises CalledProcessError
    outside a git repo; the CLI reports that as a bad invocation."""
    import subprocess
    top = subprocess.check_output(
        ["git", "-C", root, "rev-parse", "--show-toplevel"],
        text=True).strip()
    diff = subprocess.check_output(
        ["git", "-C", top, "diff", "--name-only", ref], text=True)
    untracked = subprocess.check_output(
        ["git", "-C", top, "ls-files", "--others",
         "--exclude-standard"], text=True)
    return {os.path.abspath(os.path.join(top, p))
            for p in diff.splitlines() + untracked.splitlines()
            if p.endswith(".py")}


def run_paths(paths: Optional[Iterable[str]] = None,
              rules: Optional[list] = None) -> Report:
    """Convenience one-shot: lint `paths` (default: the veneur_tpu
    package) and return the Report."""
    return LintEngine(rules=rules).run(paths)
