"""Telemetry schema registry: every emitted series and /debug/vars key,
statically extracted, committed as an artifact, and cross-validated
against the runtime ledgers.

The conservation story (exact delivery or visibly-accounted loss) is
only auditable if the ACCOUNTING SURFACE itself is closed: every
counter a drop can land in must be a series some dashboard can read,
every ledger equation must reference fields some code actually writes,
and a new series must not silently collide with an existing one under
a different type.  This module is the single source of that surface:

  1. EXTRACTION — every statsd self-metric emit site
     (`statsd.count/incr/gauge/histogram/timing/set`, `ssf_mod.*`) and
     every `/debug/vars` key (the `debug_vars(...)` builders in
     http_api.py and proxy/proxy.py, plus each ledger's `stats()`
     producer) is resolved to (name, type, tag shape, site).  F-string
     names become `*` patterns; names flowing from module constants
     (`sink_mod.METRICS_FLUSHED_TOTAL`) resolve through a project-wide
     constant table; anything truly dynamic is recorded as an explicit
     blind spot, never silently skipped.

  2. THE COMMITTED ARTIFACT — `analysis/telemetry_schema.json`, regrown
     with `python -m veneur_tpu.analysis --emit-schema <file>` and
     sync-tested in tier 1 exactly like `lock_order_graph.json`: a new
     emit site that is not re-committed fails the build.

  3. CHECKS (the `telemetry-schema` lint rule drives these):
       collisions      same series name emitted with different types
                       (or provably different tag-key shapes)
       consumer drift  promised series (PROMISED_SERIES here, any
                       module-level *PROMISED*/*_SERIES list, README
                       references) that no site emits
       ledger drift    a ledger closure equation referencing a field
                       its producer `stats()` never writes, or a
                       ledger /debug/vars key no builder exposes

  4. RUNTIME CROSS-VALIDATION — `TelemetryWitness` wraps each testbed
     server's statsd client (recording every emitted series) and
     snapshots the real `/debug/vars` dicts; `compare_runtime` then
     fails loud on any runtime-observed series or vars key the static
     schema lacks (an ANALYZER GAP, same contract as the lock
     witness), and asserts every declared ledger closure over the
     observed counters.
"""

from __future__ import annotations

import ast
import json
import os
import re
import threading
import weakref
from typing import Iterable, Optional

from veneur_tpu.analysis import astutil

SCHEMA_VERSION = 1

# emit-method -> series type, per client family
_STATSD_TYPES = {"count": "counter", "incr": "counter",
                 "gauge": "gauge", "histogram": "histogram",
                 "timing": "timing", "set": "set"}
_SSF_TYPES = {"count": "counter", "gauge": "gauge",
              "histogram": "histogram", "timing": "timing",
              "set_sample": "set", "status": "status"}

_SSF_RECEIVERS = ("ssf", "ssf_mod")

# a plausible series name: dotted lowercase words (what the drift scan
# accepts from promised lists and README back-ticks)
SERIES_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")

# series names PROMISED to dashboards and the test suite: the
# consumer-drift check fails if no emit site produces them, so renaming
# a series without updating its consumers is a lint error, not a silent
# dashboard hole.  (Module-level *PROMISED*/*_SERIES string lists
# anywhere in the tree join this set automatically.)
PROMISED_SERIES = [
    "egress.dropped_total",
    "egress.pending_records",
    "egress.queue_full_total",
    "egress.retries_total",
    "egress.spilled_total",
    "flush.sink_errors_total",
    "flush.stragglers_total",
    "flush.unique_timeseries_total",
    "forward.dropped_total",
    "forward.retries_total",
    "forward.spool.pending_records",
    "import.errors_total",
    "listen.parse_errors_total",
    "sink.metrics_flushed_total",
    "worker.metrics_processed_total",
]

# the runtime ledgers: where each lives under /debug/vars, which
# `stats()`/`snapshot()` method produces its fields, the closure
# equation (sum(lhs) == sum(rhs); None = membership only), and which
# series prefixes belong to it (longest prefix wins).
LEDGERS = {
    "forward": {
        "debug_vars": "forward",
        "producer": ("ForwardClient", "stats"),
        "closure": None,
        "prefixes": ("forward.",),
    },
    "forward_spool": {
        "debug_vars": "spool",
        "producer": ("ForwardSpool", "stats"),
        "closure": (("spilled_points", "recovered_points"),
                    ("replayed_points", "expired_points",
                     "dropped_points", "pending_points")),
        "prefixes": ("forward.spool.",),
    },
    "egress": {
        "debug_vars": "egress",
        "producer": ("EgressPlane", "stats"),
        "closure": (("spilled", "recovered"),
                    ("replayed", "expired", "spool_dropped",
                     "pending_points")),
        "prefixes": ("egress.", "sink.", "flushed_metrics",
                     "flush.sink_errors_total",
                     "flush.stragglers_total"),
    },
    "dedup": {
        "debug_vars": "dedup",
        "producer": ("DedupLedger", "stats"),
        "closure": None,
        "prefixes": ("import.",),
    },
    "cardinality": {
        "debug_vars": "cardinality",
        "producer": ("CardinalityGuard", "snapshot"),
        "closure": None,
        "prefixes": ("cardinality.",),
    },
    "span_sinks": {
        "debug_vars": "span_sinks",
        "producer": None,
        "closure": None,
        "prefixes": ("worker.span.", "spans."),
    },
    "retention": {
        # the retention block flattens TierSegmentStore.stats() at its
        # top level (zeros when no spill dir is configured) precisely
        # so this closure can be asserted field-by-field over
        # /debug/vars -> retention
        "debug_vars": "retention",
        "producer": ("TierSegmentStore", "stats"),
        "closure": (("spilled_points", "recovered_points"),
                    ("expired_points", "dropped_points",
                     "pending_points")),
        "prefixes": ("retention.",),
    },
}


# -- name / tag resolution -------------------------------------------------

def _const_table(modules) -> dict[str, Optional[str]]:
    """Simple name -> module-level string constant, project-wide.
    A name bound to different strings in different modules is
    ambiguous and resolves to None (never guess)."""
    out: dict[str, Optional[str]] = {}
    for mod in modules:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    prev = out.get(tgt.id, "\x00")
                    if prev == "\x00":
                        out[tgt.id] = node.value.value
                    elif prev != node.value.value:
                        out[tgt.id] = None
    return out


def _resolve_name(node, consts: dict) -> tuple[Optional[str], bool]:
    """(series name, is_pattern) for a series-name expression; `*`
    marks each dynamic segment.  (None, False) = unresolvable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        name = re.sub(r"\*+", "*", "".join(parts))
        return name, "*" in name
    if isinstance(node, (ast.Name, ast.Attribute)):
        text = astutil.dotted(node)
        if text:
            got = consts.get(text.rsplit(".", 1)[-1])
            if got is not None:
                return got, False
        return None, False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left, lpat = _resolve_name(node.left, consts)
        right, rpat = _resolve_name(node.right, consts)
        if left is None:
            left, lpat = "*", True
        if right is None:
            right, rpat = "*", True
        name = re.sub(r"\*+", "*", left + right)
        if name == "*":
            return None, False
        return name, lpat or rpat or "*" in name
    return None, False


def _tag_keys(node) -> list[str]:
    """Sorted tag KEYS for a `tags=` argument; "?" marks an
    unresolvable element (a variable tag list), so shape comparisons
    only bind when both sides are fully known."""
    if node is None:
        return []
    if isinstance(node, (ast.List, ast.Tuple)):
        keys: set[str] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                            str):
                keys.add(elt.value.split(":", 1)[0])
            elif isinstance(elt, ast.JoinedStr) and elt.values \
                    and isinstance(elt.values[0], ast.Constant) \
                    and ":" in str(elt.values[0].value):
                keys.add(str(elt.values[0].value).split(":", 1)[0])
            else:
                keys.add("?")
        return sorted(keys)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return sorted(set(_tag_keys(node.left))
                      | set(_tag_keys(node.right)))
    return ["?"]


def ledger_for_series(name: str) -> str:
    """Longest-prefix ledger membership for a series name ("" = none)."""
    best = ""
    best_len = -1
    for ledger, spec in LEDGERS.items():
        for p in spec["prefixes"]:
            if (name == p or name.startswith(p)) and len(p) > best_len:
                best, best_len = ledger, len(p)
    return best


# -- extraction ------------------------------------------------------------

def _is_statsd_recv(text: Optional[str]) -> bool:
    return bool(text) and (text == "statsd" or text.endswith(".statsd"))


def extract_emits(modules) -> tuple[list[dict], list[dict]]:
    """(emits, dynamic_emits): every self-metric emit call site in the
    tree.  `emits` carry resolved names (possibly `*` patterns);
    `dynamic_emits` are the explicit blind spots (name expression
    recorded verbatim) — the artifact lists them so an unmodellable
    emit is a visible fact, not a silent gap."""
    consts = _const_table(modules)
    emits: list[dict] = []
    dynamic: list[dict] = []
    for mod in modules:
        for call in mod.nodes(ast.Call):
            if not isinstance(call.func, ast.Attribute):
                continue
            attr = call.func.attr
            recv = astutil.dotted(call.func.value)
            if _is_statsd_recv(recv) and attr in _STATSD_TYPES:
                mtype = _STATSD_TYPES[attr]
            elif recv in _SSF_RECEIVERS and attr in _SSF_TYPES:
                mtype = _SSF_TYPES[attr]
            else:
                continue
            if not call.args:
                continue
            name, pattern = _resolve_name(call.args[0], consts)
            site = f"{mod.relpath}:{call.lineno}"
            if name is None:
                dynamic.append({
                    "expr": astutil.node_source(call.args[0]),
                    "type": mtype, "site": site})
                continue
            emits.append({
                "name": name, "pattern": pattern, "type": mtype,
                "tags": _tag_keys(astutil.keyword_arg(call, "tags")),
                "site": site, "ledger": ledger_for_series(name)})
    emits.sort(key=lambda e: (e["name"], e["site"]))
    dynamic.sort(key=lambda e: (e["expr"], e["site"]))
    return emits, dynamic


def _dict_keys_in(fn_node) -> list[tuple[str, int]]:
    """String keys written inside one function: dict-literal keys plus
    `<name>[<const str>] = ...` subscript stores."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value,
                                                              str):
                    out.append((k.value, k.lineno))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = (node.targets if isinstance(node, ast.Assign)
                    else [node.target])
            for tgt in tgts:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.slice, ast.Constant) \
                        and isinstance(tgt.slice.value, str):
                    out.append((tgt.slice.value, tgt.lineno))
    return out


def extract_debug_vars(modules) -> list[dict]:
    """Top-level /debug/vars keys per tier, from the shared
    `debug_vars(...)` builders (http_api.py = server tier,
    proxy/proxy.py = proxy tier).  A builder that SEEDS from a stats
    attribute (`stats = dict(proxy.stats)`) also contributes the keys
    of that attribute's dict-literal initializer anywhere in the same
    module — the proxy's per-request counters live there."""
    out: list[dict] = []
    for mod in modules:
        tier = {"http_api": "server", "proxy": "proxy"}.get(mod.stem)
        if tier is None:
            continue
        seeds_stats = False
        seen: set[str] = set()
        keys: list[tuple[str, int]] = []
        for fn in mod.nodes(ast.FunctionDef):
            if fn.name != "debug_vars":
                continue
            # TOP-LEVEL keys only: the dict literal assigned to `stats`
            # plus `stats[<const>] = ...` stores.  Nested dicts are a
            # ledger's internal shape, not part of the top-level key
            # space the runtime gap check validates — registering them
            # here would let a future genuinely-new top-level key named
            # like a nested one slip past the witness.
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) \
                                and tgt.id == "stats" \
                                and isinstance(node.value, ast.Dict):
                            for k in node.value.keys:
                                if isinstance(k, ast.Constant) and \
                                        isinstance(k.value, str):
                                    keys.append((k.value, k.lineno))
                        elif isinstance(tgt, ast.Subscript) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "stats" \
                                and isinstance(tgt.slice, ast.Constant) \
                                and isinstance(tgt.slice.value, str):
                            keys.append((tgt.slice.value, tgt.lineno))
            for call in ast.walk(fn):
                if isinstance(call, ast.Call) \
                        and isinstance(call.func, ast.Name) \
                        and call.func.id == "dict" and call.args:
                    text = astutil.dotted(call.args[0]) or ""
                    if text.endswith(".stats"):
                        seeds_stats = True
        if seeds_stats:
            for node in mod.nodes(ast.Assign):
                if isinstance(node.value, ast.Dict) and any(
                        isinstance(t, ast.Attribute)
                        and t.attr == "stats" for t in node.targets):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            keys.append((k.value, k.lineno))
        for key, line in keys:
            if key not in seen:
                seen.add(key)
                out.append({"tier": tier, "key": key,
                            "site": f"{mod.relpath}:{line}"})
    out.sort(key=lambda d: (d["tier"], d["key"]))
    return out


def extract_producer_fields(modules) -> dict[str, list[str]]:
    """ledger name -> dict keys its declared producer method writes
    (the fields a closure equation may legally reference)."""
    fields: dict[str, list[str]] = {}
    want = {spec["producer"]: name for name, spec in LEDGERS.items()
            if spec["producer"] is not None}
    for mod in modules:
        for cls in mod.nodes(ast.ClassDef):
            for child in cls.body:
                if not isinstance(child, ast.FunctionDef):
                    continue
                ledger = want.get((cls.name, child.name))
                if ledger is None:
                    continue
                keys = sorted({k for k, _ in _dict_keys_in(child)})
                fields[ledger] = keys
    return fields


def extract_consumers(modules) -> list[dict]:
    """Promised-series consumer references: module-level string lists
    whose name mentions PROMISED or ends in _SERIES, filtered to
    series-shaped entries."""
    out: list[dict] = []
    for mod in modules:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if not any(re.search(r"PROMISED|_SERIES$", n)
                       for n in names):
                continue
            if not isinstance(node.value, (ast.List, ast.Tuple)):
                continue
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str) \
                        and SERIES_RE.match(elt.value):
                    out.append({
                        "name": elt.value,
                        "consumer": f"{mod.relpath}:{node.lineno}"})
    out.sort(key=lambda c: (c["name"], c["consumer"]))
    return out


_README_TOKEN = re.compile(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)`")

# a README back-tick only counts as a SERIES reference when it carries
# a metric-ish suffix — span names (`egress.attempt`) and failpoint
# names (`egress.sink`) share the dotted grammar but are not series
_SERIES_SUFFIXES = ("_total", "_ms", "_ns", "_records", "_seconds",
                    "percentile")


def readme_consumers(readme_path: str,
                     first_segments: set[str]) -> list[dict]:
    """Back-ticked series references in the README whose first segment
    matches an emitted family (so `os.path` never counts): drift-checked
    like any other consumer."""
    if not os.path.isfile(readme_path):
        return []
    with open(readme_path, "r", encoding="utf-8") as f:
        text = f.read()
    out = []
    seen: set[str] = set()
    for m in _README_TOKEN.finditer(text):
        tok = m.group(1)
        if tok in seen or not SERIES_RE.match(tok):
            continue
        if not tok.endswith(_SERIES_SUFFIXES):
            continue
        if tok.split(".", 1)[0] not in first_segments:
            continue
        seen.add(tok)
        line = text.count("\n", 0, m.start()) + 1
        out.append({"name": tok, "consumer": f"README.md:{line}"})
    return sorted(out, key=lambda c: c["name"])


# -- the schema ------------------------------------------------------------

def build_schema(modules, root: str = "",
                 readme_path: str = "") -> dict:
    """The full registry over parsed Modules (engine.Module objects).
    Deterministic, byte-stable for the committed artifact."""
    emits, dynamic = extract_emits(modules)
    debug_vars = extract_debug_vars(modules)
    consumers = extract_consumers(modules)
    if readme_path:
        firsts = {e["name"].split(".", 1)[0] for e in emits
                  if not e["pattern"]}
        consumers = sorted(
            consumers + readme_consumers(readme_path, firsts),
            key=lambda c: (c["name"], c["consumer"]))
    producer_fields = extract_producer_fields(modules)
    ledgers = {}
    for name, spec in sorted(LEDGERS.items()):
        ledgers[name] = {
            "debug_vars": spec["debug_vars"],
            "closure": ([sorted(spec["closure"][0]),
                         sorted(spec["closure"][1])]
                        if spec["closure"] else None),
            "fields": producer_fields.get(name, []),
            "prefixes": sorted(spec["prefixes"]),
        }
    return {
        "vnlint_telemetry_schema": SCHEMA_VERSION,
        # basename only: an absolute root would make the committed
        # artifact churn with every contributor's checkout path
        "root": os.path.basename(root.rstrip("/")) if root else "",
        "emits": emits,
        "dynamic_emits": dynamic,
        "debug_vars": debug_vars,
        "ledgers": ledgers,
        "consumers": consumers,
    }


def build_schema_for_tree(paths=None, readme_path: str = "") -> dict:
    """Standalone build (the CLI / artifact-sync / runtime-comparator
    entry point): discovery + parsing are the lint engine's own, so the
    schema covers exactly the tree a lint run sees."""
    from veneur_tpu.analysis import engine as engine_mod
    eng = engine_mod.LintEngine(rules=[])
    root, modules, _failures = engine_mod.load_modules(
        paths, eng.known_rules)
    if not readme_path:
        cand = os.path.join(os.path.dirname(root), "README.md")
        readme_path = cand if os.path.isfile(cand) else ""
    return build_schema(modules, root=root, readme_path=readme_path)


def schema_fingerprint(schema: dict) -> dict:
    """The site-insensitive projection the artifact-sync check compares
    (line numbers drift with unrelated edits; names, types, tag shapes
    and ledger topology must not change silently)."""
    return {
        "emits": sorted({(e["name"], e["type"], tuple(e["tags"]),
                          e["pattern"], e["ledger"])
                         for e in schema["emits"]}),
        "dynamic": sorted({(d["expr"], d["type"])
                           for d in schema["dynamic_emits"]}),
        "debug_vars": sorted({(d["tier"], d["key"])
                              for d in schema["debug_vars"]}),
        "ledgers": {
            name: {"debug_vars": led["debug_vars"],
                   "closure": led["closure"],
                   "fields": list(led["fields"]),
                   "prefixes": list(led["prefixes"])}
            for name, led in schema["ledgers"].items()},
    }


def write_schema(schema: dict, path) -> None:
    payload = json.dumps(schema, indent=2, sort_keys=True) + "\n"
    if path == "-":
        import sys
        sys.stdout.write(payload)
        return
    with open(path, "w", encoding="utf-8") as f:
        f.write(payload)


def load_schema(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


# -- static checks ---------------------------------------------------------

def _pattern_re(pattern: str) -> re.Pattern:
    return re.compile("^" + ".*".join(
        re.escape(p) for p in pattern.split("*")) + "$")


def series_matcher(schema: dict):
    """Callable name -> matching emit dict (or None), exact names
    first, then `*` patterns."""
    exact: dict[str, dict] = {}
    patterns: list[tuple[re.Pattern, dict]] = []
    for e in schema["emits"]:
        if e["pattern"]:
            patterns.append((_pattern_re(e["name"]), e))
        else:
            exact.setdefault(e["name"], e)

    def match(name: str) -> Optional[dict]:
        hit = exact.get(name)
        if hit is not None:
            return hit
        for rx, e in patterns:
            if rx.match(name):
                return e
        return None

    return match


def schema_issues(schema: dict) -> list[dict]:
    """The three static checks: emit-site collisions, consumer drift,
    ledger drift.  Each issue carries the site to anchor a lint finding
    at."""
    issues: list[dict] = []
    by_name: dict[str, list[dict]] = {}
    for e in schema["emits"]:
        by_name.setdefault(e["name"], []).append(e)
    for name, sites in sorted(by_name.items()):
        types = sorted({e["type"] for e in sites})
        if len(types) > 1:
            where = ", ".join(f"{e['site']} ({e['type']})"
                              for e in sites)
            issues.append({
                "kind": "collision", "site": sites[0]["site"],
                "message": f"series `{name}` emitted with conflicting "
                           f"types {types} at {where} — one name, one "
                           "type, or dashboards aggregate garbage"})
            continue
        known_shapes = sorted({tuple(e["tags"]) for e in sites
                               if "?" not in e["tags"]})
        # subset shapes are compatible (a success-path emit with fewer
        # tags than its failure-path twin groups fine); only DISJOINT
        # dimensions split the series
        known_shapes = [s for s in known_shapes
                        if not any(set(s) < set(o)
                                   for o in known_shapes)]
        if len(known_shapes) > 1:
            where = ", ".join(
                f"{e['site']} (tags {sorted(e['tags'])})"
                for e in sites if "?" not in e["tags"])
            issues.append({
                "kind": "collision", "site": sites[0]["site"],
                "message": f"series `{name}` emitted with conflicting "
                           f"tag shapes at {where} — group-bys split "
                           "one series into disjoint halves"})
    match = series_matcher(schema)
    for c in schema["consumers"]:
        if match(c["name"]) is None:
            issues.append({
                "kind": "consumer-drift", "site": c["consumer"],
                "message": f"`{c['name']}` is promised to consumers "
                           f"({c['consumer']}) but no site emits it — "
                           "the series was renamed or removed without "
                           "its readers"})
    dv_keys = {d["key"] for d in schema["debug_vars"]}
    if not dv_keys:
        # the analyzed tree has no debug_vars builder at all (a lint
        # fixture, a partial tree): the declared ledgers aren't ITS
        # contract, so ledger drift is out of scope
        return issues
    for name, led in sorted(schema["ledgers"].items()):
        if led["debug_vars"] not in dv_keys:
            issues.append({
                "kind": "ledger-drift", "site": "analysis/telemetry.py",
                "message": f"ledger `{name}` claims /debug/vars key "
                           f"`{led['debug_vars']}` but no debug_vars "
                           "builder exposes it"})
        if led["closure"]:
            missing = [f for side in led["closure"] for f in side
                       if f not in led["fields"]]
            if missing:
                issues.append({
                    "kind": "ledger-drift",
                    "site": "analysis/telemetry.py",
                    "message": f"ledger `{name}` closure references "
                               f"field(s) {missing} its producer "
                               "never writes — the equation can "
                               "never be evaluated"})
    return issues


# -- runtime cross-validation ---------------------------------------------

class _RecordingStatsd:
    """Statsd-interface proxy: records (name, type) for the witness,
    then delegates to the real client (or a no-op)."""

    def __init__(self, witness: "TelemetryWitness", inner):
        from veneur_tpu import scopedstatsd
        self._w = witness
        self._inner = scopedstatsd.ensure(inner)

    def replace_inner(self, client) -> None:
        """Server.start() calls this when a `stats_address` client is
        built AFTER the witness wrapped a pre-start None — recording
        must compose with, not suppress, the configured client."""
        from veneur_tpu import scopedstatsd
        self._inner = scopedstatsd.ensure(client)

    def count(self, name, value, tags=None, rate=1.0):
        self._w.record(name, "counter")
        self._inner.count(name, value, tags=tags, rate=rate)

    def incr(self, name, tags=None, rate=1.0):
        self._w.record(name, "counter")
        self._inner.incr(name, tags=tags, rate=rate)

    def gauge(self, name, value, tags=None, rate=1.0):
        self._w.record(name, "gauge")
        self._inner.gauge(name, value, tags=tags, rate=rate)

    def histogram(self, name, value, tags=None, rate=1.0):
        self._w.record(name, "histogram")
        self._inner.histogram(name, value, tags=tags, rate=rate)

    def timing(self, name, ms, tags=None, rate=1.0):
        self._w.record(name, "timing")
        self._inner.timing(name, ms, tags=tags, rate=rate)

    def set(self, name, member, tags=None, rate=1.0):
        self._w.record(name, "set")
        self._inner.set(name, member, tags=tags, rate=rate)

    def close(self):
        self._inner.close()


class TelemetryWitness:
    """Runtime half of the schema cross-validation: a recording statsd
    client on every witnessed server plus /debug/vars snapshots, shared
    across a testbed cluster (or several chaos cells)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._series: dict[tuple[str, str], int] = {}
        # monotonic token -> (weakref, tier); vars snapshots keyed the
        # same way.  NOT id(obj): across a shared-witness chaos matrix
        # CPython reuses addresses, and a reused id would silently
        # overwrite a crashed node's final ledger snapshot — the most
        # interesting one.
        self._next_token = 0
        self._nodes: dict[int, tuple] = {}
        self._vars: dict[int, dict] = {}

    def record(self, name: str, mtype: str) -> None:
        with self._mu:
            key = (name, mtype)
            self._series[key] = self._series.get(key, 0) + 1

    def _register(self, obj, tier: str) -> None:
        with self._mu:
            for ref, _tier in self._nodes.values():
                if ref() is obj:
                    return          # idempotent re-install
            self._nodes[self._next_token] = (weakref.ref(obj), tier)
            self._next_token += 1

    def install_server(self, server) -> None:
        """Wrap `server.statsd` (install before traffic; every later
        flush records its emissions) and register the server for
        /debug/vars collection."""
        if not isinstance(server.statsd, _RecordingStatsd):
            server.statsd = _RecordingStatsd(self, server.statsd)
        self._register(server, "server")

    def install_proxy(self, proxy) -> None:
        self._register(proxy, "proxy")

    def collect(self) -> None:
        """Snapshot /debug/vars for every live witnessed node (latest
        snapshot wins; crashed/stopped nodes keep their last one)."""
        with self._mu:
            nodes = list(self._nodes.items())
        for key, (ref, tier) in nodes:
            obj = ref()
            if obj is None:
                continue
            try:
                if tier == "server":
                    from veneur_tpu import http_api
                    snap = http_api.debug_vars(obj)
                else:
                    from veneur_tpu.proxy import proxy as proxy_mod
                    snap = proxy_mod.debug_vars(obj)
            except Exception:
                continue    # a crashed node's last snapshot stands
            with self._mu:
                self._vars[key] = {"tier": tier, "vars": snap}

    # statsd wire type char -> the schema's type vocabulary
    _STATSD_TYPES = {"c": "counter", "g": "gauge", "h": "histogram",
                     "ms": "timing", "s": "set"}

    def record_statsd_payload(self, payload: bytes) -> None:
        """HTTP/UDP-scrape equivalent of the in-process recording
        client: parse a statsd datagram a witnessed SUBPROCESS tier
        sent to the harness's capture socket and record each line's
        (name, type).  Malformed lines are skipped — the witness
        records what was emitted, it is not a validator (the schema
        comparison will still flag unknown series)."""
        for line in payload.split(b"\n"):
            if not line:
                continue
            head, _, rest = line.decode(errors="replace") \
                .partition("|")
            name = head.split(":", 1)[0]
            tchar = rest.split("|", 1)[0]
            mtype = self._STATSD_TYPES.get(tchar)
            if not name or not mtype:
                continue
            # the wire carries the ScopedClient's reference-compatible
            # "veneur." namespace; the schema (and the in-process
            # recorder, which wraps the client ABOVE the namespace)
            # know series by their bare names
            if name.startswith("veneur."):
                name = name[len("veneur."):]
            self.record(name, mtype)

    def add_vars_snapshot(self, tier: str, snap: dict) -> None:
        """HTTP-scrape equivalent of collect(): register one tier's
        /debug/vars payload (already-parsed JSON) under a fresh token.
        The process-separated testbed scrapes every tier at teardown
        and feeds the snapshots here, so compare_runtime works
        identically against either cluster flavor."""
        with self._mu:
            self._vars[self._next_token] = {"tier": tier,
                                            "vars": dict(snap)}
            self._next_token += 1

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "series": [
                    {"name": n, "type": t, "count": c}
                    for (n, t), c in sorted(self._series.items())],
                "nodes": [dict(v) for v in self._vars.values()],
            }


def compare_runtime(schema: dict, observed) -> dict:
    """Cross-validate runtime observations against the static schema.

    `observed` is a TelemetryWitness or its snapshot() dict.  Fails
    loud (`ok: False`) on any observed series or /debug/vars key the
    schema lacks — an analyzer gap, not a runtime bug — and evaluates
    every declared ledger closure over the observed counters."""
    if isinstance(observed, TelemetryWitness):
        observed = observed.snapshot()
    match = series_matcher(schema)
    gaps: list[dict] = []
    matched = 0
    for s in observed.get("series", []):
        hit = match(s["name"])
        if hit is None:
            gaps.append({"kind": "series", "name": s["name"],
                         "detail": "observed series absent from the "
                                   "static schema"})
        elif not hit["pattern"] and hit["type"] != s["type"]:
            gaps.append({"kind": "series-type", "name": s["name"],
                         "detail": f"observed as {s['type']}, schema "
                                   f"says {hit['type']} "
                                   f"({hit['site']})"})
        else:
            matched += 1
    dv_by_tier: dict[str, set] = {}
    for d in schema.get("debug_vars", []):
        dv_by_tier.setdefault(d["tier"], set()).add(d["key"])
    ledgers: dict[str, dict] = {
        name: {"nodes": 0, "closed": True}
        for name, led in schema.get("ledgers", {}).items()
        if led["closure"]}
    for node in observed.get("nodes", []):
        tier, snap = node["tier"], node["vars"]
        known = dv_by_tier.get(tier, set())
        for key in snap:
            if key not in known:
                gaps.append({"kind": "debug-vars", "name": key,
                             "detail": f"{tier} /debug/vars key "
                                       "absent from the static "
                                       "schema"})
        for name, led in schema.get("ledgers", {}).items():
            if not led["closure"]:
                continue
            sub = snap.get(led["debug_vars"])
            if not isinstance(sub, dict):
                continue
            missing = [f for side in led["closure"] for f in side
                       if f not in sub]
            if missing:
                gaps.append({"kind": "ledger", "name": name,
                             "detail": f"closure field(s) {missing} "
                                       "absent from the observed "
                                       "ledger"})
                continue
            lhs = sum(sub[f] for f in led["closure"][0])
            rhs = sum(sub[f] for f in led["closure"][1])
            rec = ledgers[name]
            rec["nodes"] += 1
            if lhs != rhs:
                rec["closed"] = False
                rec["delta"] = lhs - rhs
    # dedup gap rows (several nodes can observe the same unknown key)
    seen: set[tuple] = set()
    uniq = []
    for g in gaps:
        k = (g["kind"], g["name"])
        if k not in seen:
            seen.add(k)
            uniq.append(g)
    open_ledgers = [n for n, r in ledgers.items()
                    if r["nodes"] and not r["closed"]]
    return {
        "ok": not uniq and not open_ledgers,
        "gaps": uniq,
        "ledgers": ledgers,
        "observed_series": len(observed.get("series", [])),
        "matched_series": matched,
        "nodes": len(observed.get("nodes", [])),
    }


def runtime_comparison(witness: TelemetryWitness,
                       paths=None) -> dict:
    """Build the static schema for the installed package and compare a
    witnessed run against it — the telemetry analog of
    chaos.witness_comparison."""
    return compare_runtime(build_schema_for_tree(paths), witness)
