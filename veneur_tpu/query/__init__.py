"""Live query plane: windowed quantiles served between flushes.

The pipeline's historical read path is the interval flush -> sink
fan-out; this package adds the ON-DEMAND read path (ROADMAP #6, after
"Data stream fusion for accurate quantile tracking and analysis",
arXiv 2101.06758): each histogram arena keeps a bounded ring of
per-interval mergeable sub-sketches next to its live state
(query/rings.py), and `GET /query` on every tier fuses the slots
covering a requested window on read — t-digest point-cloud merge for
the digest family, elementwise vector add + one maxent solve for the
moments family (whose window fusion is nearly free, arXiv 1803.01969)
— and evaluates quantiles through the existing eval twins.

Rotation rides the flush cut (core/aggregator.py flush_dispatch): the
ring slot IS the immutable flush snapshot the cut already produced, so
the ingest path gains no new lock and the flush path gains two deque
appends.  The staleness contract follows: an answer always covers data
up to the most recent completed cut, i.e. at most one slot behind now.
"""

from veneur_tpu.query.engine import QueryEngine, QueryError  # noqa: F401
from veneur_tpu.query.rings import WindowRing  # noqa: F401
