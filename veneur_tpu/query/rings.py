"""Window rings: bounded per-arena rings of mergeable sub-sketches.

One `WindowRing` per histogram family (digest and moments) lives next
to the arena's live interval state.  Rotation rides the flush cut —
the slot's payload IS the immutable snapshot `part` dict the cut
already produced for the flush program (touched rows, columnar
metadata, the consumed staged COO, and the exact host scalar copies;
the moments part additionally carries the ivec accumulator copies) —
so pushing a slot is two O(1) deque appends with zero copies, and the
ingest path acquires no new lock.

Slots finalize LAZILY on first read (a (name, tags) -> positions index
plus, for the digest family, a row-sorted view of the staged COO), so
the flush path never pays for a window nobody queried; the build cost
lands on the first query's latency and is cached for the slot's
lifetime.

Checkpoint contract: rings are NOT checkpointed.  A restore cold-starts
the ring — the first post-boot queries answer partial windows until
`query_window_slots` cuts have refilled it (documented in README
"Live query plane"; pinned by tests/test_query.py).  Windowed reads
are a freshness surface, not a durability surface: the durable state
(arena contents, spool, dedup ledger) already rides the checkpoint.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

import numpy as np


class WindowSlot:
    """One completed flush interval's mergeable sub-sketch for one
    histogram family: a reference to the flush cut's snapshot part plus
    the cut timestamps.  Immutable after construction except for the
    lazily-built (and then cached) read indexes."""

    # per-slot memo of key -> positions lookups (bounded: a slot lives
    # `query_window_slots` intervals, and the memo only grows with
    # DISTINCT queried keys, but a scripted scan over a huge key space
    # must not pin O(keys) python objects per slot)
    _MEMO_CAP = 4096

    __slots__ = ("part", "t_start", "t_end", "seq", "_lock",
                 "_memo", "_vec_memo", "_name_hash", "_sorted")

    def __init__(self, part: dict, t_start: float, t_end: float,
                 seq: int):
        self.part = part
        self.t_start = t_start
        self.t_end = t_end
        self.seq = seq
        self._lock = threading.Lock()
        self._memo: dict = {}
        # moments family: per-key fused wire vectors (an
        # assemble_vectors walk is O(capacity + the key's staged
        # points) — pay it once per key per slot, not per query)
        self._vec_memo: dict = {}
        self._name_hash: Optional[np.ndarray] = None
        self._sorted = None

    @property
    def n_keys(self) -> int:
        return len(self.part["rows"])

    @property
    def n_points(self) -> int:
        return len(self.part["staged"][0])

    def positions(self, name: str, jtags: str,
                  kind: Optional[str] = None) -> tuple:
        """Positions (indexes into the part's touched-row arrays) of
        the key (name, joined-sorted-tags), optionally filtered to one
        metric kind.  The name match is ONE vectorized object-array
        compare (never a python walk of the key space — at 100k keys a
        per-slot dict build held the GIL long enough to tax concurrent
        flushes by ~2x); only the (few) name hits pay python tag
        joins, and the result memoizes per slot."""
        mk = (name, jtags)
        hits = self._memo.get(mk)
        if hits is None:
            names = self.part["names"]
            # hash(name) column maintained by the arena at key
            # registration and snapshotted with the part (so a lookup
            # is ONE numeric compare; an object-array == holds the
            # GIL per element).  Fallback pass for parts predating
            # the column (str hashes are cached, so it is one cheap
            # walk, built once per slot).
            harr = self.part.get("name_hashes")
            if harr is None:
                harr = self._name_hash
                if harr is None:
                    with self._lock:
                        harr = self._name_hash
                        if harr is None:
                            harr = np.fromiter(
                                (hash(x) if x is not None else 0
                                 for x in names), np.int64,
                                len(names))
                            self._name_hash = harr
            cand = np.nonzero(harr == hash(name))[0] if len(names) \
                else ()
            tags = self.part["tags"]
            kinds = self.part["kinds"]
            out = []
            for pos in cand:
                # hash candidates verify the actual name (collisions)
                # and the joined-sorted tags
                t = tags[pos]
                jt = ",".join(sorted(t)) if t else ""
                if names[pos] == name and jt == jtags:
                    out.append((int(pos), kinds[pos]))
            hits = tuple(out)
            with self._lock:
                if len(self._memo) < self._MEMO_CAP:
                    self._memo[mk] = hits
        if kind is None:
            return tuple(p for p, _ in hits)
        return tuple(p for p, k in hits if k == kind)

    def _name_hash_col(self) -> np.ndarray:
        """The part's hash(name) column (or the lazily-built fallback
        for parts predating it)."""
        harr = self.part.get("name_hashes")
        if harr is None:
            harr = self._name_hash
            if harr is None:
                with self._lock:
                    harr = self._name_hash
                    if harr is None:
                        names = self.part["names"]
                        harr = np.fromiter(
                            (hash(x) if x is not None else 0
                             for x in names), np.int64, len(names))
                        self._name_hash = harr
        return harr

    def cube_positions(self, name: str, dim_tags: tuple,
                       kind: Optional[str] = None) -> tuple:
        """Every CUBE row of (metric name, dimension) in this slot:
        ``(position, joined-sorted-tags, kind)`` triples.  Cube rows
        share the base metric's name, so the same one-compare
        name-hash scan finds the candidates; the marker tag and the
        group's tag-NAME set separate them from the base key and from
        other dimensions' rows.  Memoized per slot like positions()."""
        from veneur_tpu.cubes.cube import CUBE_TAG, DIM_TAG_PREFIX
        mk = ("\x00cube", name, dim_tags)
        hits = self._memo.get(mk)
        if hits is None:
            names = self.part["names"]
            harr = self._name_hash_col()
            cand = np.nonzero(harr == hash(name))[0] if len(names) \
                else ()
            tags = self.part["tags"]
            kinds = self.part["kinds"]
            want = set(dim_tags)
            out = []
            for pos in cand:
                t = tags[pos]
                if not t or names[pos] != name or CUBE_TAG not in t:
                    continue
                gnames = {x.partition(":")[0] for x in t
                          if x != CUBE_TAG
                          and not x.startswith(DIM_TAG_PREFIX)}
                if gnames != want:
                    continue
                out.append((int(pos), ",".join(sorted(t)), kinds[pos]))
            hits = tuple(out)
            with self._lock:
                if len(self._memo) < self._MEMO_CAP:
                    self._memo[mk] = hits
        if kind is None:
            return hits
        return tuple(h for h in hits if h[2] == kind)

    def _ensure_sorted(self):
        srt = self._sorted
        if srt is None:
            with self._lock:
                srt = self._sorted
                if srt is None:
                    srows, svals, swts = self.part["staged"]
                    order = np.argsort(srows, kind="stable")
                    srt = (srows[order], svals[order], swts[order])
                    self._sorted = srt
        return srt

    def points_for(self, rows: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray]:
        """The slot's staged weighted points of the given ROW IDS
        (digest family: raw samples, imported centroids, and hot-row
        pre-reduction centroids all live in the staged COO).  First
        call sorts the COO by row; later reads are two binary searches
        per row."""
        _, vals, wts = self.staged_rows_for(rows)
        return vals, wts

    def staged_rows_for(self, rows: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray,
                                   np.ndarray]:
        """The staged COO subset (rows, vals, wts) of the given row
        ids — the moments fusion hands this reduced view to
        assemble_vectors so its per-point mask covers only the queried
        key's points, not the whole interval."""
        srows, svals, swts = self._ensure_sorted()
        rparts: list[np.ndarray] = []
        vparts: list[np.ndarray] = []
        wparts: list[np.ndarray] = []
        for r in rows:
            lo, hi = np.searchsorted(srows, [r, r + 1])
            if hi > lo:
                rparts.append(srows[lo:hi])
                vparts.append(svals[lo:hi])
                wparts.append(swts[lo:hi])
        if not vparts:
            z = np.zeros(0, np.float64)
            return z.astype(np.int64), z, z
        if len(vparts) == 1:
            return rparts[0], vparts[0], wparts[0]
        return (np.concatenate(rparts), np.concatenate(vparts),
                np.concatenate(wparts))

    def vector_memo(self, key: tuple, compute):
        """Per-slot memo of the moments family's fused wire vector for
        one query key (bounded like the positions memo)."""
        vec = self._vec_memo.get(key)
        if vec is None:
            vec = compute()
            with self._lock:
                if len(self._vec_memo) < self._MEMO_CAP:
                    self._vec_memo[key] = vec
        return vec


class WindowRing:
    """Bounded ring of `WindowSlot`s for one histogram family.

    `rotate` is called from the flush path (after the lock-held
    snapshot, outside the aggregator lock); `covering` is called from
    query threads.  The ring's own lock only guards the deque and the
    cut bookkeeping — it is never held while fusing or evaluating, and
    it never nests inside (or outside) any aggregator or arena lock."""

    def __init__(self, slots: int, slot_seconds: float):
        if slots < 1:
            raise ValueError(f"query_window_slots must be >= 1, "
                             f"got {slots}")
        self.capacity = int(slots)
        self.slot_seconds = float(slot_seconds)
        self.lock = threading.Lock()
        self._slots: deque[WindowSlot] = deque(maxlen=self.capacity)
        self.cuts = 0          # total rotations (evictions = cuts - len)
        self.last_cut = 0.0    # unix ts of the newest completed cut

    def rotate(self, part: dict, now_ts: float) -> None:
        """Push one completed interval's snapshot part as the newest
        slot (called at the flush cut; O(1), no copies)."""
        with self.lock:
            slot = WindowSlot(part,
                              t_start=self.last_cut or now_ts,
                              t_end=now_ts, seq=self.cuts)
            self._slots.append(slot)
            self.cuts += 1
            self.last_cut = now_ts

    def covering(self, window_s: Optional[float] = None,
                 slots: Optional[int] = None,
                 now: Optional[float] = None) -> tuple[list, dict]:
        """The newest-first slot list covering the requested window
        (`slots` = newest-k; else `window_s` of wall time, minimum one
        slot so a sub-slot window still answers from the last cut),
        plus coverage metadata: covered_[from,to]_unix, fused/requested
        counts, `partial` (the ring could not cover the whole request)
        and `fresh` (the newest completed cut is included — the
        staleness contract's discrete form)."""
        import time as _time
        now = _time.time() if now is None else now
        with self.lock:
            snap = list(self._slots)
            cuts, last_cut = self.cuts, self.last_cut
        snap.reverse()   # newest first
        if slots is not None:
            want = max(1, int(slots))
            take = snap[:want]
            partial = len(take) < want
        else:
            horizon = now - float(window_s or self.slot_seconds)
            take = [s for s in snap if s.t_end > horizon]
            if not take and snap:
                take = snap[:1]
            # partial = the request reaches earlier than the fused
            # coverage AND earlier cuts actually existed (seq > 0);
            # before the first cut ever, "everything we have" is not
            # partial — it is simply all the data there is
            partial = (not take
                       or (take[-1].t_start > horizon
                           and take[-1].seq > 0))
        info = {
            "slots_fused": len(take),
            "slots_requested": (want if slots is not None else None),
            "window_s": (float(window_s) if window_s is not None
                         else None),
            "covered_from_unix": take[-1].t_start if take else None,
            "covered_to_unix": take[0].t_end if take else None,
            "partial": bool(partial),
            "fresh": bool(take) and take[0].t_end == last_cut,
        }
        return take, info

    def slots_between(self, t0: float, t1: float) -> list:
        """Snapshot of the slots overlapping [t0, t1), newest first —
        the range-query planner's view of the ring (the finest
        retention source)."""
        with self.lock:
            snap = [s for s in self._slots
                    if s.t_end > t0 and s.t_start < t1]
        snap.reverse()
        return snap

    def stats(self) -> dict:
        with self.lock:
            return {
                "slots": len(self._slots),
                "capacity": self.capacity,
                "cuts": self.cuts,
                "evicted": self.cuts - len(self._slots),
                "last_cut_unix": self.last_cut,
                "points_held": sum(s.n_points for s in self._slots),
            }
