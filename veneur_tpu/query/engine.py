"""The query engine: window fusion + quantile evaluation on read.

One `QueryEngine` per server answers `GET /query` from the arenas'
window rings (query/rings.py):

  digest family   fuse = concatenate the covered slots' staged weighted
                  point clouds for the key (raw samples, imported
                  centroids and hot-row pre-reduction centroids alike),
                  then evaluate with the numpy mirror of the serving
                  flush's evaluation core (sketches/tdigest.py
                  weighted_eval: stable sort, cumulative-weight midpoint
                  interpolation, clamp to the exact [min, max]).

  moments family  fuse = elementwise vector add (sketches/moments.py
                  merge_vectors rebases and adds the power-sum blocks),
                  then ONE maxent solve (ops/moments_eval.py
                  quantiles_from_vectors) — the arXiv 1803.01969 window
                  story: fusion cost independent of the window's sample
                  count.

Every answer carries a self-describing mergeable PAYLOAD (a centroid
list for digests — the forwarding wire shape — or the moments vector),
so an upper tier (the proxy's scatter-gather) can merge answers through
the same family codecs it already speaks, and `merge_responses` below
is that merge.

Telemetry per request: query.served_total / query.errors_total /
query.latency_ms (tier-tagged), /debug/vars -> query, and a `query`
span on the flight recorder.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

_PCT_MIN, _PCT_MAX = 0.0, 1.0
# answers whose fused digest point cloud exceeds this compress down to
# the wire centroid shape (bounded payload; the reference's
# MergingDigest.Data form) before serialization
PAYLOAD_POINT_CAP = 2048
# recent per-request latencies kept for stats()/bench percentiles
_LATENCY_RING = 512


class QueryError(ValueError):
    """A request error with its HTTP status (400 bad params, 404
    disabled/unknown, 503 upstream)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = int(code)


def weighted_quantiles_np(vals: np.ndarray, wts: np.ndarray,
                          d_min: float, d_max: float,
                          qs) -> Optional[np.ndarray]:
    """Numpy mirror of the flush evaluation core
    (sketches/tdigest.py weighted_eval, single row): stable sort by
    value, cumulative-weight midpoint interpolation, clamp to the
    authoritative [min, max].  Returns None for an empty cloud."""
    wts = np.asarray(wts, np.float64)
    occ = wts > 0
    v = np.asarray(vals, np.float64)[occ]
    w = wts[occ]
    if len(v) == 0:
        return None
    order = np.argsort(v, kind="stable")
    v, w = v[order], w[order]
    cum = np.cumsum(w)
    total = cum[-1]
    qs = np.asarray(qs, np.float64)
    if len(v) == 1:
        out = np.full(len(qs), v[0])
        return np.clip(out, d_min, d_max)
    cmid = cum - 0.5 * w
    tq = qs * total
    # rank = count of cmid strictly below the target (the twin's fused
    # comparison-count), then clamp into [1, n-1] for interpolation
    idx = np.searchsorted(cmid, tq, side="left")
    ii = np.clip(idx, 1, len(v) - 1)
    m_lo, m_hi = v[ii - 1], v[ii]
    c_lo, c_hi = cmid[ii - 1], cmid[ii]
    t = np.where(c_hi > c_lo,
                 (tq - c_lo) / np.maximum(c_hi - c_lo, 1e-30), 0.0)
    out = m_lo + (m_hi - m_lo) * np.clip(t, 0.0, 1.0)
    return np.clip(out, d_min, d_max)


def _compress_payload(vals: np.ndarray, wts: np.ndarray,
                      compression: float) -> tuple[np.ndarray,
                                                   np.ndarray]:
    """Bound a fused point cloud to wire-centroid size via the serving
    compress kernel (sketches/tdigest.py compress, eager on a [1, M]
    row padded to a power of two)."""
    import jax.numpy as jnp

    from veneur_tpu.sketches import tdigest as td
    m = 1 << (len(vals) - 1).bit_length()
    dv = np.zeros((1, m), np.float32)
    dw = np.zeros((1, m), np.float32)
    dv[0, :len(vals)] = vals
    dw[0, :len(wts)] = wts
    ccap = td.centroid_capacity(compression)
    cm, cw = td.compress(jnp.asarray(dv), jnp.asarray(dw),
                         compression, ccap)
    cm = np.asarray(cm[0], np.float64)
    cw = np.asarray(cw[0], np.float64)
    occ = cw > 0
    return cm[occ], cw[occ]


# -- parameter parsing (shared by server and proxy HTTP handlers) --------

def parse_query_params(q: dict) -> dict:
    """urllib parse_qs dict -> validated query spec.  Raises
    QueryError(400) on anything malformed."""
    name = (q.get("name") or [""])[0]
    if not name:
        raise QueryError(400, "missing name=")
    try:
        qs = [float(x) for x in
              (q.get("q") or ["0.5"])[0].split(",") if x]
    except ValueError:
        raise QueryError(400, "bad q= (comma-separated floats)")
    if not qs or any(not (_PCT_MIN < p < _PCT_MAX) for p in qs):
        raise QueryError(400, "q= values must be in (0, 1)")
    window_s = None
    slots = None
    if "slots" in q:
        try:
            slots = int(q["slots"][0])
        except ValueError:
            raise QueryError(400, "bad slots=")
        if slots < 1:
            raise QueryError(400, "slots= must be >= 1")
    elif "window_s" in q:
        try:
            window_s = float(q["window_s"][0])
        except ValueError:
            raise QueryError(400, "bad window_s=")
        if not window_s > 0:
            raise QueryError(400, "window_s= must be > 0")
    tags = [t for t in (q.get("tags") or [""])[0].split(",") if t]
    kind = (q.get("type") or [None])[0]
    if kind is not None and kind not in ("histogram", "timer"):
        raise QueryError(400, "type= must be histogram or timer")
    return {"name": name, "qs": qs, "window_s": window_s,
            "slots": slots, "tags": tags, "kind": kind}


class QueryEngine:
    """Per-server windowed-quantile read path over the aggregator's
    window rings.  Thread-safe; holds no aggregator or arena lock —
    reads touch only immutable flush snapshots."""

    def __init__(self, aggregator, recorder=None, statsd_fn=None,
                 tier: str = "local", hostname: str = ""):
        self.agg = aggregator
        self.recorder = recorder
        self._statsd_fn = statsd_fn or (lambda: None)
        self.tier = tier
        self.hostname = hostname
        self.served = 0
        self.errors = 0
        self._lock = threading.Lock()
        self._latencies_ms: list[float] = []

    @property
    def enabled(self) -> bool:
        return self.agg.query_rings is not None

    def stats(self) -> dict:
        with self._lock:
            lat = list(self._latencies_ms)
        out = {"enabled": self.enabled, "served": self.served,
               "errors": self.errors}
        if lat:
            out["latency_p50_ms"] = float(np.percentile(lat, 50))
            out["latency_p99_ms"] = float(np.percentile(lat, 99))
        if self.enabled:
            rings = self.agg.query_rings
            out["rings"] = {fam: r.stats() for fam, r in rings.items()}
        return out

    # -- HTTP entry (telemetry + span wrapper) ---------------------------

    def serve(self, q: dict) -> tuple[int, dict]:
        """parse_qs dict -> (http status, JSON-able body), with the
        per-request telemetry contract: query.served_total /
        query.errors_total / query.latency_ms (tier-tagged) and one
        `query` span on the flight recorder."""
        from veneur_tpu import scopedstatsd
        statsd = scopedstatsd.ensure(self._statsd_fn())
        t0 = time.perf_counter()
        name = (q.get("name") or [""])[0]
        code = 200
        try:
            spec = parse_query_params(q)
            body = self.query(**spec)
        except QueryError as e:
            code, body = e.code, {"error": str(e)}
        except Exception as e:  # noqa: BLE001 - surfaced as HTTP 500
            code, body = 500, {"error": f"{type(e).__name__}: {e}"}
        dt_ms = (time.perf_counter() - t0) * 1e3
        ttags = [f"tier:{self.tier}"]
        with self._lock:
            if code == 200:
                self.served += 1
            else:
                self.errors += 1
            self._latencies_ms.append(dt_ms)
            if len(self._latencies_ms) > _LATENCY_RING:
                del self._latencies_ms[:-_LATENCY_RING]
        if code == 200:
            statsd.count("query.served_total", 1, tags=ttags)
        else:
            statsd.count("query.errors_total", 1,
                         tags=ttags + [f"code:{code}"])
        statsd.timing("query.latency_ms", dt_ms, tags=ttags)
        if self.recorder is not None:
            from veneur_tpu import trace as trace_mod
            span = trace_mod.Span("query", service="veneur_tpu",
                                  tags={"tier": self.tier,
                                        "name": name,
                                        "code": str(code)})
            span.start_ns = time.time_ns() - int(dt_ms * 1e6)
            span.error = code >= 400
            span.client = None        # ring fast path, like segments
            span.finish()
            self.recorder.record_span(span)
        return code, body

    # -- the windowed read -----------------------------------------------

    def query(self, name: str, tags: Optional[list] = None,
              qs=(0.5,), window_s: Optional[float] = None,
              slots: Optional[int] = None,
              kind: Optional[str] = None,
              payload: bool = True) -> dict:
        """Fuse the ring slots covering the window and evaluate the
        requested quantiles for one key.  A key absent from every
        covered slot answers count=0 (not an error: absence of samples
        is a legitimate windowed answer)."""
        rings = self.agg.query_rings
        if rings is None:
            raise QueryError(
                404, "query plane disabled (query_window_slots: 0)")
        jtags = ",".join(sorted(tags)) if tags else ""
        now = time.time()
        td_slots, td_info = rings["tdigest"].covering(
            window_s=window_s, slots=slots, now=now)
        mo_slots, mo_info = rings["moments"].covering(
            window_s=window_s, slots=slots, now=now)
        # the two family rings rotate back to back (not atomically);
        # a read landing between the appends would see one ring a cut
        # ahead of the other.  Coverage metadata merges CONSERVATIVELY
        # over both so the answer never claims coverage one fused
        # family lacks: fresh/partial only hold when both hold, and
        # the covered window is the intersection's bounds
        info = dict(td_info)
        info["fresh"] = bool(td_info["fresh"] and mo_info["fresh"])
        info["partial"] = bool(td_info["partial"]
                               or mo_info["partial"])
        info["slots_fused"] = min(td_info["slots_fused"],
                                  mo_info["slots_fused"])
        # intersection bounds: [max(from), min(to)] — min(from) would
        # claim coverage one of the fused families lacks
        for k, pick in (("covered_from_unix", max),
                        ("covered_to_unix", min)):
            vals = [v for v in (td_info[k], mo_info[k])
                    if v is not None]
            info[k] = pick(vals) if vals else None

        td = self._fuse_tdigest(td_slots, name, jtags, kind)
        mo = self._fuse_moments(mo_slots, name, jtags, kind)

        qarr = np.asarray(list(qs), np.float64)
        out = {
            "name": name, "tags": sorted(tags) if tags else [],
            "tier": self.tier, "host": self.hostname,
            "staleness_ms": (
                round((now - info["covered_to_unix"]) * 1e3, 3)
                if info["covered_to_unix"] else None),
            "quantiles": {}, "count": 0.0, "sum": 0.0,
            "min": None, "max": None, "family": "none",
            "mixed_families": bool(td["count"] > 0 and mo["count"] > 0),
            "payload": None,
        }
        out.update(info)
        # a key can legitimately live in BOTH families across a window
        # (a cross-tier sketch_family_rules mismatch is the documented
        # degradation); the families cannot merge exactly, so the
        # answer follows the family holding more mass and flags it
        fam = td if td["count"] >= mo["count"] else mo
        if fam["count"] > 0:
            out["family"] = fam["family"]
            out["count"] = fam["count"]
            out["sum"] = fam["sum"]
            out["min"] = fam["min"]
            out["max"] = fam["max"]
            quants = fam["eval"](qarr)
            if quants is not None:
                out["quantiles"] = {
                    repr(float(p)): float(v)
                    for p, v in zip(qarr, quants)}
            if payload:
                out["payload"] = fam["payload"]()
        return out

    def _fuse_tdigest(self, slots_list, name, jtags, kind) -> dict:
        vparts: list[np.ndarray] = []
        wparts: list[np.ndarray] = []
        mn, mx = np.inf, -np.inf
        cnt = sm = rs = 0.0
        for slot in slots_list:
            pos = slot.positions(name, jtags, kind)
            if not pos:
                continue
            prt = slot.part
            if len(pos) == 1:
                # the common case: one position per key per slot —
                # scalar item reads beat five fancy-index+reduce
                # numpy round-trips (~8 us each) on the query path
                i = pos[0]
                mn = min(mn, float(prt["d_min"][i]))
                mx = max(mx, float(prt["d_max"][i]))
                cnt += float(prt["d_weight"][i])
                sm += float(prt["d_sum"][i])
                rs += float(prt["d_rsum"][i])
                rows_sel = prt["rows"][i:i + 1]
            else:
                parr = np.asarray(pos, np.int64)
                mn = min(mn, float(prt["d_min"][parr].min()))
                mx = max(mx, float(prt["d_max"][parr].max()))
                cnt += float(prt["d_weight"][parr].sum())
                sm += float(prt["d_sum"][parr].sum())
                rs += float(prt["d_rsum"][parr].sum())
                rows_sel = prt["rows"][parr]
            v, w = slot.points_for(rows_sel)
            if len(v):
                vparts.append(v)
                wparts.append(w)

        def _eval(qarr):
            if not vparts:
                return None
            return weighted_quantiles_np(
                np.concatenate(vparts), np.concatenate(wparts),
                mn, mx, qarr)

        def _payload():
            if not vparts:
                return None
            v = np.concatenate(vparts)
            w = np.concatenate(wparts)
            if len(v) > PAYLOAD_POINT_CAP:
                v, w = _compress_payload(
                    v, w, self.agg.digests.compression)
            return {"family": "tdigest",
                    "means": [float(x) for x in v],
                    "weights": [float(x) for x in w],
                    "min": float(mn), "max": float(mx),
                    "count": cnt, "sum": sm, "rsum": rs}

        return {"family": "tdigest", "count": cnt, "sum": sm,
                "min": (float(mn) if cnt > 0 else None),
                "max": (float(mx) if cnt > 0 else None),
                "eval": _eval, "payload": _payload}

    def _fuse_moments(self, slots_list, name, jtags, kind) -> dict:
        from veneur_tpu.sketches import moments as mo
        marena = self.agg.moments
        vec = None
        for slot in slots_list:
            pos = slot.positions(name, jtags, kind)
            if not pos:
                continue

            def _compute(slot=slot, pos=pos):
                # REDUCED staged view: assemble_vectors' per-point
                # mask walks only the key's own points, and the
                # result memoizes per slot, so repeat queries are a
                # dict hit + vector add
                parr = np.asarray(pos, np.int64)
                sub = slot.staged_rows_for(slot.part["rows"][parr])
                vecs = marena.assemble_vectors(slot.part, sub, parr)
                out = vecs[0].copy()
                for row in vecs[1:]:
                    out = mo.merge_vectors(out[None, :],
                                           row[None, :])[0]
                return out
            svec = slot.vector_memo((name, jtags, kind), _compute)
            vec = (svec.copy() if vec is None
                   else mo.merge_vectors(vec[None, :],
                                         svec[None, :])[0])
        cnt = float(vec[mo.IDX_COUNT]) if vec is not None else 0.0

        def _eval(qarr):
            if vec is None or cnt <= 0:
                return None
            from veneur_tpu.ops import moments_eval as me
            return me.quantiles_from_vectors(vec[None, :], qarr)[0]

        def _payload():
            if vec is None:
                return None
            return {"family": "moments", "k": marena.k,
                    "vector": [float(x) for x in vec]}

        return {"family": "moments", "count": cnt,
                "sum": (float(vec[mo.IDX_SUM]) if vec is not None
                        else 0.0),
                "min": (float(vec[mo.IDX_MIN]) if cnt > 0 else None),
                "max": (float(vec[mo.IDX_MAX]) if cnt > 0 else None),
                "eval": _eval, "payload": _payload}


# -- cross-tier merge (the proxy's scatter-gather codec) -----------------

def merge_responses(responses: list[dict], qs,
                    compression: float = 100.0) -> dict:
    """Merge tier /query answers through their self-describing
    payloads: digest payloads concatenate as weighted point clouds and
    re-evaluate through the same twin; moments payloads vector-add and
    re-solve.  Families that cannot merge exactly follow the
    larger-mass family with `mixed_families` flagged (the same
    degradation contract as a cross-tier sketch_family_rules
    mismatch).  Coverage metadata merges conservatively: staleness is
    the WORST upstream's, `partial`/`fresh` only hold if they hold
    everywhere."""
    from veneur_tpu.sketches import moments as mo
    qarr = np.asarray(list(qs), np.float64)
    td_v: list[np.ndarray] = []
    td_w: list[np.ndarray] = []
    td = {"count": 0.0, "sum": 0.0, "rsum": 0.0,
          "min": np.inf, "max": -np.inf}
    mo_vec = None
    mixed = False
    for r in responses:
        mixed = mixed or bool(r.get("mixed_families"))
        p = r.get("payload")
        if not p:
            continue
        if p["family"] == "tdigest":
            td_v.append(np.asarray(p["means"], np.float64))
            td_w.append(np.asarray(p["weights"], np.float64))
            td["count"] += float(p["count"])
            td["sum"] += float(p["sum"])
            td["rsum"] += float(p.get("rsum", 0.0))
            td["min"] = min(td["min"], float(p["min"]))
            td["max"] = max(td["max"], float(p["max"]))
        elif p["family"] == "moments":
            vec = np.asarray(p["vector"], np.float64)
            mo_vec = (vec if mo_vec is None
                      else mo.merge_vectors(mo_vec[None, :],
                                            vec[None, :])[0])
    mo_count = float(mo_vec[mo.IDX_COUNT]) if mo_vec is not None else 0.0
    out = {
        "name": responses[0]["name"] if responses else "",
        "tags": responses[0].get("tags", []) if responses else [],
        "quantiles": {}, "count": 0.0, "sum": 0.0,
        "min": None, "max": None, "family": "none",
        "mixed_families": mixed or (td["count"] > 0 and mo_count > 0),
        "slots_fused": sum(r.get("slots_fused") or 0
                           for r in responses),
        "partial": any(r.get("partial") for r in responses),
        "fresh": bool(responses) and all(r.get("fresh")
                                         for r in responses),
        "staleness_ms": max(
            (r["staleness_ms"] for r in responses
             if r.get("staleness_ms") is not None), default=None),
        "payload": None,
    }
    if td["count"] >= mo_count and td["count"] > 0:
        v = np.concatenate(td_v)
        w = np.concatenate(td_w)
        quants = weighted_quantiles_np(v, w, td["min"], td["max"],
                                       qarr)
        out.update(family="tdigest", count=td["count"], sum=td["sum"],
                   min=float(td["min"]), max=float(td["max"]))
        if quants is not None:
            out["quantiles"] = {repr(float(p)): float(x)
                                for p, x in zip(qarr, quants)}
        if len(v) > PAYLOAD_POINT_CAP:
            v, w = _compress_payload(v, w, compression)
        out["payload"] = {"family": "tdigest",
                          "means": [float(x) for x in v],
                          "weights": [float(x) for x in w],
                          "min": float(td["min"]),
                          "max": float(td["max"]),
                          "count": td["count"], "sum": td["sum"],
                          "rsum": td["rsum"]}
    elif mo_count > 0:
        from veneur_tpu.ops import moments_eval as me
        quants = me.quantiles_from_vectors(mo_vec[None, :], qarr)[0]
        out.update(family="moments", count=mo_count,
                   sum=float(mo_vec[mo.IDX_SUM]),
                   min=float(mo_vec[mo.IDX_MIN]),
                   max=float(mo_vec[mo.IDX_MAX]))
        out["quantiles"] = {repr(float(p)): float(x)
                            for p, x in zip(qarr, quants)}
        out["payload"] = {"family": "moments",
                          "k": mo.k_from_len(len(mo_vec)),
                          "vector": [float(x) for x in mo_vec]}
    return out
