"""The query engine: window fusion + quantile evaluation on read.

One `QueryEngine` per server answers `GET /query` from the arenas'
window rings (query/rings.py):

  digest family   fuse = concatenate the covered slots' staged weighted
                  point clouds for the key (raw samples, imported
                  centroids and hot-row pre-reduction centroids alike),
                  then evaluate with the numpy mirror of the serving
                  flush's evaluation core (sketches/tdigest.py
                  weighted_eval: stable sort, cumulative-weight midpoint
                  interpolation, clamp to the exact [min, max]).

  moments family  fuse = elementwise vector add (sketches/moments.py
                  merge_vectors rebases and adds the power-sum blocks),
                  then ONE maxent solve (ops/moments_eval.py
                  quantiles_from_vectors) — the arXiv 1803.01969 window
                  story: fusion cost independent of the window's sample
                  count.

  compactor family  fuse = level-wise concat-then-compact
                  (sketches/compactor.py merge_vectors; order-free
                  bit-for-bit), then the rank/quantile read-off
                  (quantiles_from_vectors) — the relative-error
                  guarantee survives the window fusion because the
                  merge IS the sketch's own merge.

Every answer carries a self-describing mergeable PAYLOAD (a centroid
list for digests — the forwarding wire shape — or the moments /
compactor vector), so an upper tier (the proxy's scatter-gather) can
merge answers through the same family codecs it already speaks, and
`merge_responses` below is that merge.

Telemetry per request: query.served_total / query.errors_total /
query.latency_ms (tier-tagged), /debug/vars -> query, and a `query`
span on the flight recorder.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

import numpy as np

_PCT_MIN, _PCT_MAX = 0.0, 1.0
# answers whose fused digest point cloud exceeds this compress down to
# the wire centroid shape (bounded payload; the reference's
# MergingDigest.Data form) before serialization
PAYLOAD_POINT_CAP = 2048
# recent per-request latencies kept for stats()/bench percentiles
_LATENCY_RING = 512
# bound on ?since=&step= range answers: a request asking more bins is
# a 400, not an unbounded fuse-and-solve loop
MAX_RANGE_BINS = 2048


class QueryError(ValueError):
    """A request error with its HTTP status (400 bad params, 404
    disabled/unknown, 503 upstream)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = int(code)


def weighted_quantiles_np(vals: np.ndarray, wts: np.ndarray,
                          d_min: float, d_max: float,
                          qs) -> Optional[np.ndarray]:
    """Numpy mirror of the flush evaluation core
    (sketches/tdigest.py weighted_eval, single row): stable sort by
    value, cumulative-weight midpoint interpolation, clamp to the
    authoritative [min, max].  Returns None for an empty cloud."""
    wts = np.asarray(wts, np.float64)
    occ = wts > 0
    v = np.asarray(vals, np.float64)[occ]
    w = wts[occ]
    if len(v) == 0:
        return None
    order = np.argsort(v, kind="stable")
    v, w = v[order], w[order]
    cum = np.cumsum(w)
    total = cum[-1]
    qs = np.asarray(qs, np.float64)
    if len(v) == 1:
        out = np.full(len(qs), v[0])
        return np.clip(out, d_min, d_max)
    cmid = cum - 0.5 * w
    tq = qs * total
    # rank = count of cmid strictly below the target (the twin's fused
    # comparison-count), then clamp into [1, n-1] for interpolation
    idx = np.searchsorted(cmid, tq, side="left")
    ii = np.clip(idx, 1, len(v) - 1)
    m_lo, m_hi = v[ii - 1], v[ii]
    c_lo, c_hi = cmid[ii - 1], cmid[ii]
    t = np.where(c_hi > c_lo,
                 (tq - c_lo) / np.maximum(c_hi - c_lo, 1e-30), 0.0)
    out = m_lo + (m_hi - m_lo) * np.clip(t, 0.0, 1.0)
    return np.clip(out, d_min, d_max)


def weighted_quantiles_np_batch(vals_list, wts_list, mins, maxs,
                                qs) -> list:
    """Batched ``weighted_quantiles_np`` over many point clouds: ONE
    global stable lexsort + reduceat ranking instead of a python loop
    of per-cloud sort pipelines — the group-by cube read's hot path
    (hundreds of groups per query; the per-group numpy call overhead
    dominates at that width).  Returns one array per cloud (None for
    an empty cloud), matching the per-group twin to float rounding
    (the cumulative weights rebase off a global cumsum, so the
    addition order differs — ranks at an exact boundary may shift one
    interpolation step, which moves the answer continuously)."""
    qs = np.asarray(qs, np.float64)
    n_g = len(vals_list)
    out: list = [None] * n_g
    vs, ws = [], []
    sizes = np.zeros(n_g, np.int64)
    for g in range(n_g):
        w = np.asarray(wts_list[g], np.float64)
        occ = w > 0
        v = np.asarray(vals_list[g], np.float64)[occ]
        vs.append(v)
        ws.append(w[occ])
        sizes[g] = len(v)
    if not sizes.sum():
        return out
    v = np.concatenate(vs)
    w = np.concatenate(ws)
    seg = np.repeat(np.arange(n_g), sizes)
    order = np.lexsort((v, seg))    # stable: by group, then value
    v, w, seg = v[order], w[order], seg[order]
    starts = np.zeros(n_g, np.int64)
    starts[1:] = np.cumsum(sizes)[:-1]
    cumg = np.cumsum(w)
    base = np.where(starts > 0, cumg[starts - 1], 0.0)
    base[sizes == 0] = 0.0
    ends = starts + sizes
    tot = np.where(sizes > 0, cumg[np.maximum(ends - 1, 0)] - base,
                   0.0)
    cmid = (cumg - base[seg]) - 0.5 * w

    nz = np.flatnonzero(sizes > 0)
    tq = tot[nz, None] * qs[None, :]            # [Gnz, Q]
    # rank = per-group count of cmid strictly below the target
    # (the searchsorted(side="left") twin), via one reduceat over the
    # nonzero segments' starts
    cmp = cmid[:, None] < tq[np.searchsorted(nz, seg), :]
    idx = np.add.reduceat(cmp, starts[nz], axis=0)
    ii = np.clip(idx, 1, np.maximum(sizes[nz, None] - 1, 1))
    # single-point clouds land on ii=1 past their only point; clamp
    # into the buffer (their answer is overwritten just below)
    gi = np.minimum(starts[nz, None] + ii, len(v) - 1)
    m_lo, m_hi = v[gi - 1], v[gi]
    c_lo, c_hi = cmid[gi - 1], cmid[gi]
    t = np.where(c_hi > c_lo,
                 (tq - c_lo) / np.maximum(c_hi - c_lo, 1e-30), 0.0)
    ans = m_lo + (m_hi - m_lo) * np.clip(t, 0.0, 1.0)
    # single-point clouds answer their one value (the twin's
    # special case); then clamp to each cloud's authoritative domain
    one = sizes[nz] == 1
    if one.any():
        ans[one] = v[starts[nz][one], None]
    mins = np.asarray(mins, np.float64)[nz, None]
    maxs = np.asarray(maxs, np.float64)[nz, None]
    ans = np.clip(ans, mins, maxs)
    for j, g in enumerate(nz):
        out[int(g)] = ans[j]
    return out


def _compress_payload(vals: np.ndarray, wts: np.ndarray,
                      compression: float) -> tuple[np.ndarray,
                                                   np.ndarray]:
    """Bound a fused point cloud to wire-centroid size via the serving
    compress kernel (sketches/tdigest.py compress, eager on a [1, M]
    row padded to a power of two)."""
    import jax.numpy as jnp

    from veneur_tpu.sketches import tdigest as td
    m = 1 << (len(vals) - 1).bit_length()
    dv = np.zeros((1, m), np.float32)
    dw = np.zeros((1, m), np.float32)
    dv[0, :len(vals)] = vals
    dw[0, :len(wts)] = wts
    ccap = td.centroid_capacity(compression)
    cm, cw = td.compress(jnp.asarray(dv), jnp.asarray(dw),
                         compression, ccap)
    cm = np.asarray(cm[0], np.float64)
    cw = np.asarray(cw[0], np.float64)
    occ = cw > 0
    return cm[occ], cw[occ]


# -- parameter parsing (shared by server and proxy HTTP handlers) --------

def parse_query_params(q: dict) -> dict:
    """urllib parse_qs dict -> validated query spec.  Raises
    QueryError(400) on anything malformed."""
    name = (q.get("name") or [""])[0]
    if not name:
        raise QueryError(400, "missing name=")
    try:
        qs = [float(x) for x in
              (q.get("q") or ["0.5"])[0].split(",") if x]
    except ValueError:
        raise QueryError(400, "bad q= (comma-separated floats)")
    if not qs or any(not (_PCT_MIN < p < _PCT_MAX) for p in qs):
        raise QueryError(400, "q= values must be in (0, 1)")
    window_s = None
    slots = None
    if "slots" in q:
        try:
            slots = int(q["slots"][0])
        except ValueError:
            raise QueryError(400, "bad slots=")
        if slots < 1:
            raise QueryError(400, "slots= must be >= 1")
    elif "window_s" in q:
        try:
            window_s = float(q["window_s"][0])
        except ValueError:
            raise QueryError(400, "bad window_s=")
        # `not (x > 0)` also rejects nan; isfinite rejects +inf (a
        # window reaching past every ring is a malformed request, not
        # an everything-window)
        if not (window_s > 0 and math.isfinite(window_s)):
            raise QueryError(400, "window_s= must be a positive "
                                  "finite number of seconds")
    tags = [t for t in (q.get("tags") or [""])[0].split(",") if t]
    kind = (q.get("type") or [None])[0]
    if kind is not None and kind not in ("histogram", "timer"):
        raise QueryError(400, "type= must be histogram or timer")
    # group-by cube queries: ?group_by=tag1,tag2[&top=K&by=q99]
    group_by = [t for t in (q.get("group_by") or [""])[0].split(",")
                if t]
    for t in group_by:
        if ":" in t:
            raise QueryError(400, f"group_by= takes tag NAMES, got "
                             f"{t!r} (a tag:value filter belongs in "
                             "tags=)")
    top = None
    if "top" in q:
        if not group_by:
            raise QueryError(400, "top= requires group_by=")
        try:
            top = int(q["top"][0])
        except ValueError:
            raise QueryError(400, "bad top=")
        if top < 1:
            raise QueryError(400, "top= must be >= 1")
    by = (q.get("by") or [None])[0]
    if by is not None and not group_by:
        raise QueryError(400, "by= requires group_by=")
    parse_rank_by(by)   # validate eagerly (raises QueryError(400))
    # payload=0 answers quantiles/counts only — the dashboard read.
    # Mergeable family payloads are the proxy's scatter-gather
    # currency, not something every client wants on the wire (a
    # group-by answer carries one payload PER GROUP)
    pay = (q.get("payload") or ["1"])[0]
    if pay not in ("0", "1", "true", "false"):
        raise QueryError(400, "payload= must be 0 or 1")
    # range form: ?since=<unix>&step=<seconds>[&until=<unix>] asks a
    # bucketed timeline instead of one point answer (the retention
    # tiers' read surface).  Validation is strict-400, never a silent
    # clamp: a future since=, step<=0, or a bin count past
    # MAX_RANGE_BINS are caller bugs the server must say out loud.
    since = until = step = None
    if "since" in q or "until" in q or "step" in q:
        if "since" not in q or "step" not in q:
            raise QueryError(400, "range form needs both since= and "
                                  "step=")
        try:
            since = float(q["since"][0])
            step = float(q["step"][0])
            until = float(q["until"][0]) if "until" in q else None
        except ValueError:
            raise QueryError(400, "bad since=/until=/step= "
                                  "(unix seconds)")
        if not (math.isfinite(since) and math.isfinite(step)
                and (until is None or math.isfinite(until))):
            raise QueryError(400, "since=/until=/step= must be "
                                  "finite")
        if step <= 0:
            raise QueryError(400, "step= must be > 0")
        now = time.time()
        if since > now:
            raise QueryError(400, "since= is in the future")
        if until is not None and until <= since:
            raise QueryError(400, "until= must be > since=")
        if slots is not None or window_s is not None:
            raise QueryError(400, "range form (since=/step=) "
                                  "excludes slots= and window_s=")
        if group_by:
            raise QueryError(400, "range form does not take "
                                  "group_by=")
        if ((until if until is not None else now) - since) / step \
                > MAX_RANGE_BINS:
            raise QueryError(400, f"range asks more than "
                                  f"{MAX_RANGE_BINS} bins — raise "
                                  "step= or narrow the range")
    return {"name": name, "qs": qs, "window_s": window_s,
            "slots": slots, "tags": tags, "kind": kind,
            "group_by": group_by or None, "top": top, "by": by,
            "payload": pay in ("1", "true"),
            "since": since, "until": until, "step": step}


def parse_rank_by(by: Optional[str]) -> tuple:
    """``by=`` ranking mode -> ("count", None) or ("quantile", p).
    ``q99`` / ``q99.9`` are percent forms; ``q0.99`` the fraction
    form."""
    if by in (None, "", "count"):
        return "count", None
    if isinstance(by, str) and by.startswith("q"):
        try:
            p = float(by[1:])
        except ValueError:
            raise QueryError(400, f"bad by={by!r} (count | q<pct>)")
        if p >= 1.0:
            p = p / 100.0
        if not (_PCT_MIN < p < _PCT_MAX):
            raise QueryError(400, f"by={by!r} quantile out of (0, 1)")
        return "quantile", p
    raise QueryError(400, f"bad by={by!r} (count | q<pct>)")


def rank_groups(entries: list, mode: str, p: Optional[float],
                seed: int, top: Optional[int]) -> list:
    """Order group entries for the top-k answer: descending by the
    ranking stat (count, or the ``by=`` quantile read from the entry's
    evaluated quantiles), with the DETERMINISTIC seeded fnv1a rank of
    the canonical group key as the tie-break — the same
    identity-hash ordering the cube budget machinery uses, so equal
    groups order identically on every tier."""
    from veneur_tpu.samplers.metric_key import fnv1a_64
    qkey = repr(float(p)) if mode == "quantile" else None

    def stat(e):
        if mode == "count":
            return float(e.get("count") or 0.0)
        v = (e.get("quantiles") or {}).get(qkey)
        return float(v) if v is not None else float("-inf")

    entries.sort(key=lambda e: (-stat(e),
                                fnv1a_64(e["key"], seed), e["key"]))
    return entries[:top] if top else entries


class QueryEngine:
    """Per-server windowed-quantile read path over the aggregator's
    window rings.  Thread-safe; holds no aggregator or arena lock —
    reads touch only immutable flush snapshots."""

    def __init__(self, aggregator, recorder=None, statsd_fn=None,
                 tier: str = "local", hostname: str = ""):
        self.agg = aggregator
        self.recorder = recorder
        self._statsd_fn = statsd_fn or (lambda: None)
        self.tier = tier
        self.hostname = hostname
        self.served = 0
        self.errors = 0
        self._lock = threading.Lock()
        self._latencies_ms: list[float] = []

    @property
    def enabled(self) -> bool:
        return self.agg.query_rings is not None

    def stats(self) -> dict:
        with self._lock:
            lat = list(self._latencies_ms)
        out = {"enabled": self.enabled, "served": self.served,
               "errors": self.errors}
        if lat:
            out["latency_p50_ms"] = float(np.percentile(lat, 50))
            out["latency_p99_ms"] = float(np.percentile(lat, 99))
        if self.enabled:
            rings = self.agg.query_rings
            out["rings"] = {fam: r.stats() for fam, r in rings.items()}
        return out

    # -- HTTP entry (telemetry + span wrapper) ---------------------------

    def serve(self, q: dict) -> tuple[int, dict]:
        """parse_qs dict -> (http status, JSON-able body), with the
        per-request telemetry contract: query.served_total /
        query.errors_total / query.latency_ms (tier-tagged) and one
        `query` span on the flight recorder."""
        from veneur_tpu import scopedstatsd
        statsd = scopedstatsd.ensure(self._statsd_fn())
        t0 = time.perf_counter()
        name = (q.get("name") or [""])[0]
        code = 200
        try:
            spec = parse_query_params(q)
            body = self.query(**spec)
        except QueryError as e:
            code, body = e.code, {"error": str(e)}
        except Exception as e:  # noqa: BLE001 - surfaced as HTTP 500
            code, body = 500, {"error": f"{type(e).__name__}: {e}"}
        dt_ms = (time.perf_counter() - t0) * 1e3
        ttags = [f"tier:{self.tier}"]
        with self._lock:
            if code == 200:
                self.served += 1
            else:
                self.errors += 1
            self._latencies_ms.append(dt_ms)
            if len(self._latencies_ms) > _LATENCY_RING:
                del self._latencies_ms[:-_LATENCY_RING]
        if code == 200:
            statsd.count("query.served_total", 1, tags=ttags)
        else:
            statsd.count("query.errors_total", 1,
                         tags=ttags + [f"code:{code}"])
        statsd.timing("query.latency_ms", dt_ms, tags=ttags)
        if self.recorder is not None:
            from veneur_tpu import trace as trace_mod
            span = trace_mod.Span("query", service="veneur_tpu",
                                  tags={"tier": self.tier,
                                        "name": name,
                                        "code": str(code)})
            span.start_ns = time.time_ns() - int(dt_ms * 1e6)
            span.error = code >= 400
            span.client = None        # ring fast path, like segments
            span.finish()
            self.recorder.record_span(span)
        return code, body

    # -- the windowed read -----------------------------------------------

    def _covering(self, window_s, slots, now) -> tuple:
        """Every family ring's covering slots + CONSERVATIVELY merged
        coverage metadata.  The family rings rotate back to back (not
        atomically); a read landing between the appends would see one
        ring a cut ahead of another, so the answer never claims
        coverage one fused family lacks: fresh/partial only hold when
        all hold, and the covered window is the intersection's
        bounds."""
        rings = self.agg.query_rings
        td_slots, td_info = rings["tdigest"].covering(
            window_s=window_s, slots=slots, now=now)
        mo_slots, mo_info = rings["moments"].covering(
            window_s=window_s, slots=slots, now=now)
        cc_slots, cc_info = rings["compactor"].covering(
            window_s=window_s, slots=slots, now=now)
        infos = (td_info, mo_info, cc_info)
        info = dict(td_info)
        info["fresh"] = all(i["fresh"] for i in infos)
        info["partial"] = any(i["partial"] for i in infos)
        info["slots_fused"] = min(i["slots_fused"] for i in infos)
        # intersection bounds: [max(from), min(to)] — min(from) would
        # claim coverage one of the fused families lacks
        for k, pick in (("covered_from_unix", max),
                        ("covered_to_unix", min)):
            vals = [i[k] for i in infos if i[k] is not None]
            info[k] = pick(vals) if vals else None
        return td_slots, mo_slots, cc_slots, info

    def query(self, name: str, tags: Optional[list] = None,
              qs=(0.5,), window_s: Optional[float] = None,
              slots: Optional[int] = None,
              kind: Optional[str] = None,
              payload: bool = True,
              group_by: Optional[list] = None,
              top: Optional[int] = None,
              by: Optional[str] = None,
              since: Optional[float] = None,
              until: Optional[float] = None,
              step: Optional[float] = None) -> dict:
        """Fuse the ring slots covering the window and evaluate the
        requested quantiles for one key.  A key absent from every
        covered slot answers count=0 (not an error: absence of samples
        is a legitimate windowed answer).  With ``group_by`` the read
        answers per cube group instead (query_groups); with ``since``
        it answers the bucketed range form instead (query_range)."""
        rings = self.agg.query_rings
        if rings is None:
            raise QueryError(
                404, "query plane disabled (query_window_slots: 0)")
        if since is not None:
            return self.query_range(
                name, tags=tags, qs=qs, since=since, until=until,
                step=step, kind=kind, payload=payload)
        if group_by:
            return self.query_groups(
                name, group_by, qs=qs, window_s=window_s, slots=slots,
                kind=kind, top=top, by=by, payload=payload)
        jtags = ",".join(sorted(tags)) if tags else ""
        now = time.time()
        td_slots, mo_slots, cc_slots, info = self._covering(
            window_s, slots, now)

        td = self._fuse_tdigest(td_slots, name, jtags, kind)
        mo = self._fuse_moments(mo_slots, name, jtags, kind)
        cc = self._fuse_compactor(cc_slots, name, jtags, kind)

        qarr = np.asarray(list(qs), np.float64)
        out = {
            "name": name, "tags": sorted(tags) if tags else [],
            "tier": self.tier, "host": self.hostname,
            "staleness_ms": (
                round((now - info["covered_to_unix"]) * 1e3, 3)
                if info["covered_to_unix"] else None),
            "quantiles": {}, "count": 0.0, "sum": 0.0,
            "min": None, "max": None, "family": "none",
            "mixed_families": sum(
                f["count"] > 0 for f in (td, mo, cc)) > 1,
            "payload": None,
        }
        out.update(info)
        # a key can legitimately live in SEVERAL families across a
        # window (a cross-tier sketch_family_rules mismatch is the
        # documented degradation); the families cannot merge exactly,
        # so the answer follows the family holding most mass, flagged
        fam = max((td, mo, cc), key=lambda f: f["count"])
        if fam["count"] > 0:
            out["family"] = fam["family"]
            out["count"] = fam["count"]
            out["sum"] = fam["sum"]
            out["min"] = fam["min"]
            out["max"] = fam["max"]
            quants = fam["eval"](qarr)
            if quants is not None:
                out["quantiles"] = {
                    repr(float(p)): float(v)
                    for p, v in zip(qarr, quants)}
            if payload:
                out["payload"] = fam["payload"]()
        return out

    def _fuse_tdigest(self, slots_list, name, jtags, kind) -> dict:
        vparts: list[np.ndarray] = []
        wparts: list[np.ndarray] = []
        mn, mx = np.inf, -np.inf
        cnt = sm = rs = 0.0
        for slot in slots_list:
            pos = slot.positions(name, jtags, kind)
            if not pos:
                continue
            prt = slot.part
            if len(pos) == 1:
                # the common case: one position per key per slot —
                # scalar item reads beat five fancy-index+reduce
                # numpy round-trips (~8 us each) on the query path
                i = pos[0]
                mn = min(mn, float(prt["d_min"][i]))
                mx = max(mx, float(prt["d_max"][i]))
                cnt += float(prt["d_weight"][i])
                sm += float(prt["d_sum"][i])
                rs += float(prt["d_rsum"][i])
                rows_sel = prt["rows"][i:i + 1]
            else:
                parr = np.asarray(pos, np.int64)
                mn = min(mn, float(prt["d_min"][parr].min()))
                mx = max(mx, float(prt["d_max"][parr].max()))
                cnt += float(prt["d_weight"][parr].sum())
                sm += float(prt["d_sum"][parr].sum())
                rs += float(prt["d_rsum"][parr].sum())
                rows_sel = prt["rows"][parr]
            v, w = slot.points_for(rows_sel)
            if len(v):
                vparts.append(v)
                wparts.append(w)

        def _eval(qarr):
            if not vparts:
                return None
            return weighted_quantiles_np(
                np.concatenate(vparts), np.concatenate(wparts),
                mn, mx, qarr)

        def _payload():
            if not vparts:
                return None
            v = np.concatenate(vparts)
            w = np.concatenate(wparts)
            if len(v) > PAYLOAD_POINT_CAP:
                v, w = _compress_payload(
                    v, w, self.agg.digests.compression)
            return {"family": "tdigest",
                    "means": [float(x) for x in v],
                    "weights": [float(x) for x in w],
                    "min": float(mn), "max": float(mx),
                    "count": cnt, "sum": sm, "rsum": rs}

        def _cloud():
            if not vparts:
                return np.zeros(0, np.float64), np.zeros(0, np.float64)
            return np.concatenate(vparts), np.concatenate(wparts)

        return {"family": "tdigest", "count": cnt, "sum": sm,
                "min": (float(mn) if cnt > 0 else None),
                "max": (float(mx) if cnt > 0 else None),
                "rsum": rs,
                "eval": _eval, "payload": _payload, "cloud": _cloud}

    def _fuse_moments(self, slots_list, name, jtags, kind) -> dict:
        from veneur_tpu.sketches import moments as mo
        marena = self.agg.moments
        vec = None
        for slot in slots_list:
            pos = slot.positions(name, jtags, kind)
            if not pos:
                continue

            def _compute(slot=slot, pos=pos):
                # REDUCED staged view: assemble_vectors' per-point
                # mask walks only the key's own points, and the
                # result memoizes per slot, so repeat queries are a
                # dict hit + vector add
                parr = np.asarray(pos, np.int64)
                sub = slot.staged_rows_for(slot.part["rows"][parr])
                vecs = marena.assemble_vectors(slot.part, sub, parr)
                out = vecs[0].copy()
                for row in vecs[1:]:
                    out = mo.merge_vectors(out[None, :],
                                           row[None, :])[0]
                return out
            svec = slot.vector_memo((name, jtags, kind), _compute)
            vec = (svec.copy() if vec is None
                   else mo.merge_vectors(vec[None, :],
                                         svec[None, :])[0])
        cnt = float(vec[mo.IDX_COUNT]) if vec is not None else 0.0

        def _eval(qarr):
            if vec is None or cnt <= 0:
                return None
            from veneur_tpu.ops import moments_eval as me
            return me.quantiles_from_vectors(vec[None, :], qarr)[0]

        def _payload():
            if vec is None:
                return None
            return {"family": "moments", "k": marena.k,
                    "vector": [float(x) for x in vec]}

        return {"family": "moments", "count": cnt,
                "sum": (float(vec[mo.IDX_SUM]) if vec is not None
                        else 0.0),
                "min": (float(vec[mo.IDX_MIN]) if cnt > 0 else None),
                "max": (float(vec[mo.IDX_MAX]) if cnt > 0 else None),
                "eval": _eval, "payload": _payload, "vector": vec}

    def _fuse_compactor(self, slots_list, name, jtags, kind) -> dict:
        from veneur_tpu.sketches import compactor as cs
        carena = self.agg.compactors
        vec = None
        for slot in slots_list:
            pos = slot.positions(name, jtags, kind)
            if not pos:
                continue

            def _compute(slot=slot, pos=pos):
                # same REDUCED staged view + per-slot memo as the
                # moments fusion; compactor merges are concat-then-
                # compact (order-free, the ladder geometry makes them
                # associative) so cross-slot fusion is a fold
                parr = np.asarray(pos, np.int64)
                sub = slot.staged_rows_for(slot.part["rows"][parr])
                vecs = carena.assemble_vectors(slot.part, sub, parr)
                out = vecs[0].copy()
                for row in vecs[1:]:
                    out = cs.merge_vectors(out[None, :],
                                           row[None, :])[0]
                return out
            svec = slot.vector_memo((name, jtags, kind), _compute)
            vec = (svec.copy() if vec is None
                   else cs.merge_vectors(vec[None, :],
                                         svec[None, :])[0])
        cnt = float(vec[cs.IDX_COUNT]) if vec is not None else 0.0

        def _eval(qarr):
            if vec is None or cnt <= 0:
                return None
            return cs.quantiles_from_vectors(vec[None, :], qarr)[0]

        def _payload():
            if vec is None:
                return None
            return {"family": "compactor",
                    "vector": [float(x) for x in vec]}

        return {"family": "compactor", "count": cnt,
                "sum": (float(vec[cs.IDX_SUM]) if vec is not None
                        else 0.0),
                "min": (float(vec[cs.IDX_MIN]) if cnt > 0 else None),
                "max": (float(vec[cs.IDX_MAX]) if cnt > 0 else None),
                "eval": _eval, "payload": _payload, "vector": vec}

    # -- the range read (the retention timeline's query surface) ---------

    @staticmethod
    def _overlap(a0: float, a1: float, b0: float, b1: float) -> float:
        return max(0.0, min(a1, b1) - max(a0, b0))

    @staticmethod
    def _fuse_buckets(buckets: list, keys: list) -> tuple:
        """Fuse one key's payloads across retention buckets: digest
        clouds concat (merge_cloud), moments vectors add, compactor
        ladders merge — the same family merges that built the
        buckets.  Returns (td entry | None, mo vec | None,
        cc vec | None)."""
        from veneur_tpu.retention.timeline import merge_cloud
        from veneur_tpu.sketches import compactor as cs
        from veneur_tpu.sketches import moments as mo
        td_e = mo_v = cc_v = None
        for bk in buckets:
            for k in keys:
                e = bk.td.get(k)
                if e is not None:
                    td_e = e if td_e is None else merge_cloud(td_e, e)
                v = bk.mo.get(k)
                if v is not None:
                    mo_v = v.copy() if mo_v is None else \
                        mo.merge_vectors(mo_v[None, :], v[None, :])[0]
                v = bk.cc.get(k)
                if v is not None:
                    cc_v = v.copy() if cc_v is None else \
                        cs.merge_vectors(cc_v[None, :], v[None, :])[0]
        return td_e, mo_v, cc_v

    def query_range(self, name: str, tags: Optional[list] = None,
                    qs=(0.5,), since: float = 0.0,
                    until: Optional[float] = None,
                    step: Optional[float] = None,
                    kind: Optional[str] = None,
                    payload: bool = True) -> dict:
        """The `?since=&step=` range read: plan which sources cover
        each step bin — the window ring (finest), then the in-memory
        retention tiers finest-first, then the on-disk tier segments —
        fuse the winning source's buckets per bin, and evaluate every
        bin's quantiles in ONE batch per family (a range of moments
        bins costs one maxent solve, not one per bin).  Each bin
        carries its coverage metadata (source, covered span), and each
        bin's payload stays mergeable so the proxy scatter-gathers
        ranges exactly like point queries (merge_range_responses)."""
        from veneur_tpu.sketches import compactor as cs
        from veneur_tpu.sketches import moments as mo
        rings = self.agg.query_rings
        now = time.time()
        t_lo = float(since)
        t_hi = min(float(until), now) if until is not None else now
        step = float(step) if step else max(t_hi - t_lo, 1e-9)
        # Bin edges via multiplication, not accumulation: at unix-timestamp
        # magnitude one float64 ulp is ~2.4e-7 s, so `t += step` drifts off
        # the floor-aligned bucket grid and manufactures spurious overlaps.
        bins: list[tuple[float, float]] = []
        i = 0
        while len(bins) < MAX_RANGE_BINS:
            b0 = t_lo + i * step
            if b0 >= t_hi - 1e-9:
                break
            bins.append((b0, min(t_lo + (i + 1) * step, t_hi)))
            i += 1
        # Overlap slack: edges of bins vs. buckets come from different float
        # computations and can disagree by a few ulp of the absolute time.
        ov_eps = max(1e-9, step * 1e-4)
        jtags = ",".join(sorted(tags)) if tags else ""
        keys = ([(name, jtags, kind)] if kind is not None
                else [(name, jtags, "histogram"),
                      (name, jtags, "timer")])

        # sources, finest first (order breaks coverage ties)
        sources: list = []
        last_cut = 0.0
        if rings is not None:
            td_sl = rings["tdigest"].slots_between(t_lo, t_hi)
            mo_sl = rings["moments"].slots_between(t_lo, t_hi)
            cc_sl = rings["compactor"].slots_between(t_lo, t_hi)
            last_cut = rings["tdigest"].last_cut
            sources.append(("ring", "ring",
                            (td_sl, mo_sl, cc_sl)))
        retention = getattr(self.agg, "retention", None)
        if retention is not None:
            for tname, _bs, buckets in \
                    retention.sources_overlapping(t_lo, t_hi):
                sources.append((tname, "tier", buckets))
        if not sources:
            raise QueryError(
                404, "range form needs the query plane "
                     "(query_window_slots > 0)")

        series: list[dict] = []
        td_pending: list = []
        mo_pending: list = []
        cc_pending: list = []
        used_sources: set = set()
        cov_from = cov_to = None
        for b0, b1 in bins:
            best = None
            best_cov = 0.0
            for label, skind, data in sources:
                if skind == "ring":
                    # conservative across the three family rings (they
                    # rotate back to back, not atomically)
                    cov = min(
                        sum(self._overlap(s.t_start, s.t_end, b0, b1)
                            for s in sl)
                        for sl in data)
                else:
                    cov = sum(self._overlap(
                        bk.t_start, min(bk.filled_to, bk.t_end),
                        b0, b1) for bk in data)
                if cov > best_cov + ov_eps:
                    best, best_cov = (label, skind, data), cov
            ent = {"t_start": b0, "t_end": b1, "source": None,
                   "coverage_s": 0.0, "covered_from_unix": None,
                   "covered_to_unix": None, "family": "none",
                   "count": 0.0, "sum": 0.0, "min": None, "max": None,
                   "mixed_families": False, "quantiles": {},
                   "payload": None}
            series.append(ent)
            if best is None:
                continue
            label, skind, data = best
            used_sources.add(label)
            if skind == "ring":
                sel = [[s for s in sl
                        if self._overlap(s.t_start, s.t_end,
                                         b0, b1) > ov_eps]
                       for sl in data]
                spans = [(s.t_start, s.t_end)
                         for sl in sel for s in sl]
                td = self._fuse_tdigest(sel[0], name, jtags, kind)
                mof = self._fuse_moments(sel[1], name, jtags, kind)
                ccf = self._fuse_compactor(sel[2], name, jtags, kind)
                td_e = None
                if td["count"] > 0:
                    v, w = td["cloud"]()
                    td_e = {"v": v, "w": w, "min": td["min"],
                            "max": td["max"], "count": td["count"],
                            "sum": td["sum"], "rsum": td["rsum"]}
                mo_v, cc_v = mof["vector"], ccf["vector"]
            else:
                sel_b = [bk for bk in data
                         if self._overlap(bk.t_start,
                                          min(bk.filled_to, bk.t_end),
                                          b0, b1) > ov_eps]
                spans = [(bk.t_start, min(bk.filled_to, bk.t_end))
                         for bk in sel_b]
                td_e, mo_v, cc_v = self._fuse_buckets(sel_b, keys)
            ent["source"] = label
            ent["coverage_s"] = round(best_cov, 6)
            if spans:
                ent["covered_from_unix"] = max(
                    min(s[0] for s in spans), b0)
                ent["covered_to_unix"] = min(
                    max(s[1] for s in spans), b1)
                cov_from = ent["covered_from_unix"] if cov_from is \
                    None else min(cov_from, ent["covered_from_unix"])
                cov_to = ent["covered_to_unix"] if cov_to is None \
                    else max(cov_to, ent["covered_to_unix"])
            td_cnt = td_e["count"] if td_e is not None else 0.0
            mo_cnt = float(mo_v[mo.IDX_COUNT]) if mo_v is not None \
                else 0.0
            cc_cnt = float(cc_v[cs.IDX_COUNT]) if cc_v is not None \
                else 0.0
            ent["mixed_families"] = sum(
                c > 0 for c in (td_cnt, mo_cnt, cc_cnt)) > 1
            if td_cnt <= 0 and mo_cnt <= 0 and cc_cnt <= 0:
                continue
            # same larger-mass family pick as the point read
            if td_cnt >= mo_cnt and td_cnt >= cc_cnt:
                ent.update(family="tdigest", count=td_cnt,
                           sum=td_e["sum"], min=float(td_e["min"]),
                           max=float(td_e["max"]))
                td_pending.append((ent, td_e))
                if payload:
                    pv, pw = td_e["v"], td_e["w"]
                    if len(pv) > PAYLOAD_POINT_CAP:
                        pv, pw = _compress_payload(
                            pv, pw, self.agg.digests.compression)
                    ent["payload"] = {
                        "family": "tdigest",
                        "means": [float(x) for x in pv],
                        "weights": [float(x) for x in pw],
                        "min": float(td_e["min"]),
                        "max": float(td_e["max"]),
                        "count": td_cnt, "sum": td_e["sum"],
                        "rsum": td_e["rsum"]}
            elif mo_cnt >= cc_cnt:
                ent.update(family="moments", count=mo_cnt,
                           sum=float(mo_v[mo.IDX_SUM]),
                           min=float(mo_v[mo.IDX_MIN]),
                           max=float(mo_v[mo.IDX_MAX]))
                mo_pending.append((ent, mo_v))
                if payload:
                    ent["payload"] = {
                        "family": "moments",
                        "k": mo.k_from_len(len(mo_v)),
                        "vector": [float(x) for x in mo_v]}
            else:
                ent.update(family="compactor", count=cc_cnt,
                           sum=float(cc_v[cs.IDX_SUM]),
                           min=float(cc_v[cs.IDX_MIN]),
                           max=float(cc_v[cs.IDX_MAX]))
                cc_pending.append((ent, cc_v))
                if payload:
                    ent["payload"] = {
                        "family": "compactor",
                        "vector": [float(x) for x in cc_v]}

        qarr = np.asarray(list(qs), np.float64)
        if td_pending:
            allq = weighted_quantiles_np_batch(
                [e["v"] for _, e in td_pending],
                [e["w"] for _, e in td_pending],
                [e["min"] for _, e in td_pending],
                [e["max"] for _, e in td_pending], qarr)
            for (ent, _), quants in zip(td_pending, allq):
                if quants is not None:
                    ent["quantiles"] = {repr(float(p)): float(x)
                                        for p, x in zip(qarr, quants)}
        if mo_pending:
            # one batched maxent solve for the WHOLE range — the
            # per-bin eager path costs hundreds of ms per solve
            from veneur_tpu.ops import moments_eval as me
            allq = me.quantiles_from_vectors(
                np.stack([v for _, v in mo_pending]), qarr)
            for (ent, _), quants in zip(mo_pending, allq):
                ent["quantiles"] = {repr(float(p)): float(x)
                                    for p, x in zip(qarr, quants)}
        if cc_pending:
            allq = cs.quantiles_from_vectors(
                np.stack([v for _, v in cc_pending]), qarr)
            for (ent, _), quants in zip(cc_pending, allq):
                ent["quantiles"] = {repr(float(p)): float(x)
                                    for p, x in zip(qarr, quants)}

        partial = any(
            e["coverage_s"] + 1e-6 < (e["t_end"] - e["t_start"])
            for e in series)
        return {
            "name": name, "tags": sorted(tags) if tags else [],
            "tier": self.tier, "host": self.hostname,
            "range": True, "since": t_lo, "until": t_hi,
            "step": step, "bins": len(series), "series": series,
            "sources": sorted(used_sources),
            "covered_from_unix": cov_from,
            "covered_to_unix": cov_to,
            "partial": partial,
            "fresh": (cov_to is not None and last_cut > 0
                      and cov_to >= min(t_hi, last_cut) - 1e-6),
            "staleness_ms": (round((now - cov_to) * 1e3, 3)
                             if cov_to is not None else None),
        }

    # -- the group-by cube read ------------------------------------------

    def query_groups(self, name: str, group_by: list, qs=(0.5,),
                     window_s: Optional[float] = None,
                     slots: Optional[int] = None,
                     kind: Optional[str] = None,
                     top: Optional[int] = None,
                     by: Optional[str] = None,
                     payload: bool = True) -> dict:
        """Per-group windowed answer from the cube rows
        (veneur_tpu/cubes/): resolve ``group_by`` against the
        configured dimensions (an exact dimension answers directly; a
        SUPERSET dimension answers via the segmented-reduce
        coarsening), fuse each group's rows across the covered slots,
        and rank for ``top=K&by=``.  The accounted overflow row rides
        along as ``other`` so degraded mass stays visible."""
        from veneur_tpu.cubes import cube as cb
        cubes = getattr(self.agg, "cubes", None)
        gb = sorted(set(group_by))
        md = cb.match_dimension(cubes.dims if cubes else [], gb,
                                name=name)
        if md is None:
            # no configured dimension covers the request (or no cube
            # plane at all — a global tier can hold forwarded cube
            # rows without local dimensions): serve whatever cube
            # rows carry EXACTLY the requested tag names
            dim, exact = cb.CubeDimension(gb), True
        else:
            dim, exact = md
        seed = cubes.seed if cubes is not None else 0
        mode, rank_p = parse_rank_by(by)
        qarr = np.asarray(list(qs), np.float64)
        qeval = list(qarr)
        if mode == "quantile" and rank_p not in qeval:
            qeval.append(rank_p)
        qeval = np.asarray(qeval, np.float64)

        now = time.time()
        td_slots, mo_slots, cc_slots, info = self._covering(
            window_s, slots, now)
        td_groups = self._fuse_group_clouds(td_slots, name, dim, kind)
        mo_groups = self._fuse_group_vectors(mo_slots, name, dim, kind)
        cc_groups = self._fuse_group_ladders(cc_slots, name, dim, kind)
        launch = 0
        if not exact:
            td_groups = self._coarsen_clouds(td_groups, gb)
            mo_groups, launch = self._coarsen_vectors(
                mo_groups, gb, seed)
            cc_groups = self._coarsen_ladders(cc_groups, gb)

        from veneur_tpu.sketches import compactor as cs
        from veneur_tpu.sketches import moments as mo
        entries = []
        td_pending = []        # (entry, v, w, min, max): ONE batch
        mo_pending = []        # (entry, vector): solved in ONE batch
        cc_pending = []        # (entry, vector): read off in ONE batch
        for gkey in set(td_groups) | set(mo_groups) | set(cc_groups):
            td_g = td_groups.get(gkey)
            mo_v = mo_groups.get(gkey)
            cc_v = cc_groups.get(gkey)
            td_cnt = td_g["count"] if td_g else 0.0
            mo_cnt = float(mo_v[mo.IDX_COUNT]) if mo_v is not None \
                else 0.0
            cc_cnt = float(cc_v[cs.IDX_COUNT]) if cc_v is not None \
                else 0.0
            if td_cnt <= 0 and mo_cnt <= 0 and cc_cnt <= 0:
                continue
            e = {"key": gkey,
                 "group": cb.group_of(gkey.split(",")),
                 "mixed_families": sum(
                     c > 0 for c in (td_cnt, mo_cnt, cc_cnt)) > 1,
                 "quantiles": {}, "payload": None}
            # per-group family pick: same larger-mass rule as the
            # single-key read (families cannot merge exactly)
            if td_cnt >= mo_cnt and td_cnt >= cc_cnt:
                v = np.concatenate(td_g["v"]) if td_g["v"] else \
                    np.zeros(0)
                w = np.concatenate(td_g["w"]) if td_g["w"] else \
                    np.zeros(0)
                e.update(family="tdigest", count=td_cnt,
                         sum=td_g["sum"], min=float(td_g["min"]),
                         max=float(td_g["max"]))
                td_pending.append((e, v, w, float(td_g["min"]),
                                   float(td_g["max"])))
                if payload:
                    pv, pw = v, w
                    if len(pv) > PAYLOAD_POINT_CAP:
                        pv, pw = _compress_payload(
                            pv, pw, self.agg.digests.compression)
                    e["payload"] = {
                        "family": "tdigest",
                        "means": [float(x) for x in pv],
                        "weights": [float(x) for x in pw],
                        "min": float(td_g["min"]),
                        "max": float(td_g["max"]),
                        "count": td_cnt, "sum": td_g["sum"],
                        "rsum": td_g["rsum"]}
            elif mo_cnt >= cc_cnt:
                e.update(family="moments", count=mo_cnt,
                         sum=float(mo_v[mo.IDX_SUM]),
                         min=float(mo_v[mo.IDX_MIN]),
                         max=float(mo_v[mo.IDX_MAX]))
                mo_pending.append((e, mo_v))
                if payload:
                    e["payload"] = {"family": "moments",
                                    "k": self.agg.moments.k,
                                    "vector": [float(x) for x in mo_v]}
            else:
                e.update(family="compactor", count=cc_cnt,
                         sum=float(cc_v[cs.IDX_SUM]),
                         min=float(cc_v[cs.IDX_MIN]),
                         max=float(cc_v[cs.IDX_MAX]))
                cc_pending.append((e, cc_v))
                if payload:
                    e["payload"] = {"family": "compactor",
                                    "vector": [float(x) for x in cc_v]}
            entries.append(e)

        if td_pending:
            # one batched rank-and-interpolate for every digest group
            # (one global lexsort instead of G per-cloud sorts)
            allq = weighted_quantiles_np_batch(
                [p[1] for p in td_pending], [p[2] for p in td_pending],
                [p[3] for p in td_pending], [p[4] for p in td_pending],
                qeval)
            for (e, *_), quants in zip(td_pending, allq):
                if quants is not None:
                    e["quantiles"] = {repr(float(p)): float(x)
                                      for p, x in zip(qeval, quants)}
        if mo_pending:
            # one batched maxent solve for every moments group — the
            # per-group eager path costs hundreds of ms per call
            from veneur_tpu.ops import moments_eval as me
            allq = me.quantiles_from_vectors(
                np.stack([v for _, v in mo_pending]), qeval)
            for (e, _), quants in zip(mo_pending, allq):
                e["quantiles"] = {repr(float(p)): float(x)
                                  for p, x in zip(qeval, quants)}
        if cc_pending:
            allq = cs.quantiles_from_vectors(
                np.stack([v for _, v in cc_pending]), qeval)
            for (e, _), quants in zip(cc_pending, allq):
                e["quantiles"] = {repr(float(p)): float(x)
                                  for p, x in zip(qeval, quants)}

        groups_total = len(entries)
        entries = rank_groups(entries, mode, rank_p, seed, top)

        # the dimension's accounted overflow row (budget degradation):
        # fused like any single key, reported out loud next to the
        # exact groups so windowed cube answers reconcile
        ojtags = ",".join(sorted([cb.CUBE_TAG,
                                  cb.DIM_TAG_PREFIX + dim.dim_id]))
        otd = self._fuse_tdigest(td_slots, cb.OTHER_NAME, ojtags, kind)
        omo = self._fuse_moments(mo_slots, cb.OTHER_NAME, ojtags, kind)
        occ = self._fuse_compactor(cc_slots, cb.OTHER_NAME, ojtags,
                                   kind)
        ofam = max((otd, omo, occ), key=lambda f: f["count"])
        other = None
        if ofam["count"] > 0:
            other = {"family": ofam["family"], "count": ofam["count"],
                     "sum": ofam["sum"], "min": ofam["min"],
                     "max": ofam["max"], "quantiles": {},
                     "payload": (ofam["payload"]() if payload
                                 else None)}
            oq = ofam["eval"](qarr)
            if oq is not None:
                other["quantiles"] = {repr(float(p)): float(x)
                                      for p, x in zip(qarr, oq)}

        out = {
            "name": name, "group_by": gb,
            "dimension": list(dim.tags), "coarsened": not exact,
            "tier": self.tier, "host": self.hostname,
            "groups": entries, "groups_total": groups_total,
            "other": other, "top": top, "by": by,
            "cube_groups_per_launch": launch,
            "staleness_ms": (
                round((now - info["covered_to_unix"]) * 1e3, 3)
                if info["covered_to_unix"] else None),
        }
        out.update(info)
        return out

    def _fuse_group_clouds(self, slots_list, name, dim, kind) -> dict:
        """Digest-family cube fusion: canonical group key -> the fused
        accumulators + point-cloud parts across the covered slots."""
        groups: dict = {}
        for slot in slots_list:
            prt = slot.part
            for pos, gkey, _ in slot.cube_positions(
                    name, tuple(dim.tags), kind):
                g = groups.get(gkey)
                if g is None:
                    g = groups[gkey] = {
                        "count": 0.0, "sum": 0.0, "rsum": 0.0,
                        "min": np.inf, "max": -np.inf,
                        "v": [], "w": []}
                g["min"] = min(g["min"], float(prt["d_min"][pos]))
                g["max"] = max(g["max"], float(prt["d_max"][pos]))
                g["count"] += float(prt["d_weight"][pos])
                g["sum"] += float(prt["d_sum"][pos])
                g["rsum"] += float(prt["d_rsum"][pos])
                v, w = slot.points_for(prt["rows"][pos:pos + 1])
                if len(v):
                    g["v"].append(v)
                    g["w"].append(w)
        return groups

    def _fuse_group_vectors(self, slots_list, name, dim, kind) -> dict:
        """Moments-family cube fusion: ONE assemble_vectors walk per
        slot covers every group row (memoized per slot), then groups
        merge across slots by vector add."""
        from veneur_tpu.sketches import moments as mo
        marena = self.agg.moments
        groups: dict = {}
        for slot in slots_list:
            hits = slot.cube_positions(name, tuple(dim.tags), kind)
            if not hits:
                continue

            def _compute(slot=slot, hits=hits):
                parr = np.asarray([p for p, _, _ in hits], np.int64)
                sub = slot.staged_rows_for(slot.part["rows"][parr])
                vecs = marena.assemble_vectors(slot.part, sub, parr)
                return tuple(g for _, g, _ in hits), vecs
            gkeys, vecs = slot.vector_memo(
                ("\x00cube", name, tuple(dim.tags), kind), _compute)
            for gkey, vec in zip(gkeys, vecs):
                cur = groups.get(gkey)
                groups[gkey] = (
                    vec.copy() if cur is None
                    else mo.merge_vectors(cur[None, :],
                                          vec[None, :])[0])
        return groups

    def _fuse_group_ladders(self, slots_list, name, dim, kind) -> dict:
        """Compactor-family cube fusion: ONE assemble_vectors walk per
        slot covers every group row (memoized per slot), then groups
        merge across slots by concat-then-compact."""
        from veneur_tpu.sketches import compactor as cs
        carena = self.agg.compactors
        groups: dict = {}
        for slot in slots_list:
            hits = slot.cube_positions(name, tuple(dim.tags), kind)
            if not hits:
                continue

            def _compute(slot=slot, hits=hits):
                parr = np.asarray([p for p, _, _ in hits], np.int64)
                sub = slot.staged_rows_for(slot.part["rows"][parr])
                vecs = carena.assemble_vectors(slot.part, sub, parr)
                return tuple(g for _, g, _ in hits), vecs
            gkeys, vecs = slot.vector_memo(
                ("\x00cube", name, tuple(dim.tags), kind), _compute)
            for gkey, vec in zip(gkeys, vecs):
                cur = groups.get(gkey)
                groups[gkey] = (
                    vec.copy() if cur is None
                    else cs.merge_vectors(cur[None, :],
                                          vec[None, :])[0])
        return groups

    @staticmethod
    def _coarsen_ladders(groups: dict, keep: list) -> dict:
        """Compactor sub-cube roll-up: fine group ladders merge under
        their projected coarse key on the host (the concat-then-
        compact merge is a per-pair host op — no batched kernel form,
        and cube group counts stay small enough that it doesn't earn
        one)."""
        from veneur_tpu.cubes import cube as cb
        from veneur_tpu.sketches import compactor as cs
        out: dict = {}
        for gkey, vec in groups.items():
            ck = cb.project_group(gkey, keep)
            cur = out.get(ck)
            out[ck] = (vec if cur is None
                       else cs.merge_vectors(cur[None, :],
                                             vec[None, :])[0])
        return out

    @staticmethod
    def _coarsen_clouds(groups: dict, keep: list) -> dict:
        """Digest sub-cube roll-up: concatenate the fine groups' point
        clouds under their projected coarse key (host — clouds are
        already materialized lists)."""
        from veneur_tpu.cubes import cube as cb
        out: dict = {}
        for gkey, g in groups.items():
            ck = cb.project_group(gkey, keep)
            c = out.get(ck)
            if c is None:
                out[ck] = g
                continue
            c["count"] += g["count"]
            c["sum"] += g["sum"]
            c["rsum"] += g["rsum"]
            c["min"] = min(c["min"], g["min"])
            c["max"] = max(c["max"], g["max"])
            c["v"].extend(g["v"])
            c["w"].extend(g["w"])
        return out

    @staticmethod
    def _coarsen_vectors(groups: dict, keep: list, seed: int) -> tuple:
        """Moments sub-cube roll-up on the segmented-reduce kernel:
        the fine group vectors stack to ``[U, M]``, segment ids come
        from the SORTED fnv1a hash column of the projected coarse
        identities, and every coarse group reduces in one launch
        (ops/segmented_reduce.py).  Returns (coarse groups,
        groups_per_launch)."""
        if not groups:
            return {}, 0
        from veneur_tpu.cubes import cube as cb
        from veneur_tpu.ops.segmented_reduce import \
            coarsen_moments_vectors
        from veneur_tpu.samplers.metric_key import fnv1a_64
        keys = sorted(groups)
        cks = [cb.project_group(k, keep) for k in keys]
        hs = np.array([fnv1a_64(c, seed) for c in cks], np.uint64)
        vecs = np.stack([groups[k] for k in keys])
        uniq, gvecs, launch = coarsen_moments_vectors(vecs, hs)
        by_hash = {int(fnv1a_64(c, seed)): c for c in cks}
        return ({by_hash[int(h)]: gvecs[i]
                 for i, h in enumerate(uniq)}, launch)


# -- cross-tier merge (the proxy's scatter-gather codec) -----------------

def merge_responses(responses: list[dict], qs,
                    compression: float = 100.0) -> dict:
    """Merge tier /query answers through their self-describing
    payloads: digest payloads concatenate as weighted point clouds and
    re-evaluate through the same twin; moments payloads vector-add and
    re-solve.  Families that cannot merge exactly follow the
    larger-mass family with `mixed_families` flagged (the same
    degradation contract as a cross-tier sketch_family_rules
    mismatch).  Coverage metadata merges conservatively: staleness is
    the WORST upstream's, `partial`/`fresh` only hold if they hold
    everywhere."""
    from veneur_tpu.sketches import compactor as cs
    from veneur_tpu.sketches import moments as mo
    qarr = np.asarray(list(qs), np.float64)
    td_v: list[np.ndarray] = []
    td_w: list[np.ndarray] = []
    td = {"count": 0.0, "sum": 0.0, "rsum": 0.0,
          "min": np.inf, "max": -np.inf}
    mo_vec = None
    cc_vec = None
    mixed = False
    for r in responses:
        mixed = mixed or bool(r.get("mixed_families"))
        p = r.get("payload")
        if not p:
            continue
        if p["family"] == "tdigest":
            td_v.append(np.asarray(p["means"], np.float64))
            td_w.append(np.asarray(p["weights"], np.float64))
            td["count"] += float(p["count"])
            td["sum"] += float(p["sum"])
            td["rsum"] += float(p.get("rsum", 0.0))
            td["min"] = min(td["min"], float(p["min"]))
            td["max"] = max(td["max"], float(p["max"]))
        elif p["family"] == "moments":
            vec = np.asarray(p["vector"], np.float64)
            mo_vec = (vec if mo_vec is None
                      else mo.merge_vectors(mo_vec[None, :],
                                            vec[None, :])[0])
        elif p["family"] == "compactor":
            vec = np.asarray(p["vector"], np.float64)
            cc_vec = (vec if cc_vec is None
                      else cs.merge_vectors(cc_vec[None, :],
                                            vec[None, :])[0])
    mo_count = float(mo_vec[mo.IDX_COUNT]) if mo_vec is not None else 0.0
    cc_count = float(cc_vec[cs.IDX_COUNT]) if cc_vec is not None else 0.0
    out = {
        "name": responses[0]["name"] if responses else "",
        "tags": responses[0].get("tags", []) if responses else [],
        "quantiles": {}, "count": 0.0, "sum": 0.0,
        "min": None, "max": None, "family": "none",
        "mixed_families": mixed or sum(
            c > 0 for c in (td["count"], mo_count, cc_count)) > 1,
        "slots_fused": sum(r.get("slots_fused") or 0
                           for r in responses),
        "partial": any(r.get("partial") for r in responses),
        "fresh": bool(responses) and all(r.get("fresh")
                                         for r in responses),
        "staleness_ms": max(
            (r["staleness_ms"] for r in responses
             if r.get("staleness_ms") is not None), default=None),
        "payload": None,
    }
    if (td["count"] >= mo_count and td["count"] >= cc_count
            and td["count"] > 0):
        v = np.concatenate(td_v)
        w = np.concatenate(td_w)
        quants = weighted_quantiles_np(v, w, td["min"], td["max"],
                                       qarr)
        out.update(family="tdigest", count=td["count"], sum=td["sum"],
                   min=float(td["min"]), max=float(td["max"]))
        if quants is not None:
            out["quantiles"] = {repr(float(p)): float(x)
                                for p, x in zip(qarr, quants)}
        if len(v) > PAYLOAD_POINT_CAP:
            v, w = _compress_payload(v, w, compression)
        out["payload"] = {"family": "tdigest",
                          "means": [float(x) for x in v],
                          "weights": [float(x) for x in w],
                          "min": float(td["min"]),
                          "max": float(td["max"]),
                          "count": td["count"], "sum": td["sum"],
                          "rsum": td["rsum"]}
    elif mo_count >= cc_count and mo_count > 0:
        from veneur_tpu.ops import moments_eval as me
        quants = me.quantiles_from_vectors(mo_vec[None, :], qarr)[0]
        out.update(family="moments", count=mo_count,
                   sum=float(mo_vec[mo.IDX_SUM]),
                   min=float(mo_vec[mo.IDX_MIN]),
                   max=float(mo_vec[mo.IDX_MAX]))
        out["quantiles"] = {repr(float(p)): float(x)
                            for p, x in zip(qarr, quants)}
        out["payload"] = {"family": "moments",
                          "k": mo.k_from_len(len(mo_vec)),
                          "vector": [float(x) for x in mo_vec]}
    elif cc_count > 0:
        quants = cs.quantiles_from_vectors(cc_vec[None, :], qarr)[0]
        out.update(family="compactor", count=cc_count,
                   sum=float(cc_vec[cs.IDX_SUM]),
                   min=float(cc_vec[cs.IDX_MIN]),
                   max=float(cc_vec[cs.IDX_MAX]))
        out["quantiles"] = {repr(float(p)): float(x)
                            for p, x in zip(qarr, quants)}
        out["payload"] = {"family": "compactor",
                          "vector": [float(x) for x in cc_vec]}
    return out


def merge_range_responses(responses: list[dict], qs,
                          compression: float = 100.0) -> dict:
    """Merge tier range answers bin by bin: upstream bins align on
    their [t_start, t_end) bounds (every upstream answered the same
    validated spec, so the bin grid is shared), and each aligned
    bucket of bins runs through the same self-describing payload codec
    as the point merge (merge_responses per bin).  Coverage stays
    conservative: a bin's covered span is the union the upstreams
    report, `partial`/`fresh`/staleness merge exactly like the point
    form."""
    by_bin: dict = {}
    for r in responses:
        for b in r.get("series") or ():
            kb = (round(float(b["t_start"]), 6),
                  round(float(b["t_end"]), 6))
            by_bin.setdefault(kb, []).append(b)
    series = []
    for kb in sorted(by_bin):
        bl = by_bin[kb]
        pseudo = [{"name": "", "payload": b.get("payload"),
                   "mixed_families": b.get("mixed_families"),
                   "slots_fused": None, "partial": False,
                   "fresh": True, "staleness_ms": None} for b in bl]
        m = merge_responses(pseudo, qs, compression)
        froms = [b["covered_from_unix"] for b in bl
                 if b.get("covered_from_unix") is not None]
        tos = [b["covered_to_unix"] for b in bl
               if b.get("covered_to_unix") is not None]
        series.append({
            "t_start": kb[0], "t_end": kb[1],
            "source": "merged",
            "coverage_s": max((b.get("coverage_s") or 0.0
                               for b in bl), default=0.0),
            "covered_from_unix": min(froms) if froms else None,
            "covered_to_unix": max(tos) if tos else None,
            "family": m["family"], "count": m["count"],
            "sum": m["sum"], "min": m["min"], "max": m["max"],
            "mixed_families": m["mixed_families"],
            "quantiles": m["quantiles"], "payload": m["payload"]})
    first = responses[0] if responses else {}
    tos = [b["covered_to_unix"] for b in series
           if b["covered_to_unix"] is not None]
    return {
        "name": first.get("name", ""),
        "tags": first.get("tags", []),
        "range": True,
        "since": first.get("since"), "until": first.get("until"),
        "step": first.get("step"), "bins": len(series),
        "series": series,
        "sources": sorted({s for r in responses
                           for s in (r.get("sources") or ())}),
        "covered_from_unix": min(
            (b["covered_from_unix"] for b in series
             if b["covered_from_unix"] is not None), default=None),
        "covered_to_unix": max(tos) if tos else None,
        "partial": any(r.get("partial") for r in responses),
        "fresh": bool(responses) and all(r.get("fresh")
                                         for r in responses),
        "staleness_ms": max(
            (r["staleness_ms"] for r in responses
             if r.get("staleness_ms") is not None), default=None),
    }


def merge_group_responses(responses: list[dict], qs,
                          compression: float = 100.0,
                          top: Optional[int] = None,
                          by: Optional[str] = None) -> dict:
    """Merge tier group-by /query answers: bucket every upstream's
    group entries by canonical group key, run each bucket through the
    same self-describing payload codec as the single-key merge
    (merge_responses per group), then re-rank for ``top=K&by=`` over
    the MERGED stats — top-k must apply after the merge, since a group
    inside one tier's top-k can fall out of (or into) the global top-k
    once the other tiers' mass lands.  The accounted ``other`` rows
    merge the same way, and coverage metadata stays conservative."""
    from veneur_tpu.cubes import cube as cb
    mode, rank_p = parse_rank_by(by)
    qeval = [float(x) for x in qs]
    if mode == "quantile" and rank_p not in qeval:
        qeval.append(rank_p)

    def _pseudo(r, g):
        return {"name": r.get("name", ""),
                "payload": g.get("payload"),
                "mixed_families": g.get("mixed_families"),
                "slots_fused": r.get("slots_fused"),
                "partial": r.get("partial"),
                "fresh": r.get("fresh"),
                "staleness_ms": r.get("staleness_ms")}

    buckets: dict = {}
    others: list[dict] = []
    groups_total = 0
    launch = 0
    for r in responses:
        groups_total += int(r.get("groups_total") or 0)
        launch = max(launch, int(r.get("cube_groups_per_launch") or 0))
        for g in r.get("groups") or ():
            buckets.setdefault(g["key"], []).append(_pseudo(r, g))
        if r.get("other"):
            others.append(_pseudo(r, r["other"]))

    entries = []
    for gkey, pseudo in buckets.items():
        m = merge_responses(pseudo, qeval, compression)
        if m["count"] <= 0:
            continue
        entries.append({
            "key": gkey, "group": cb.group_of(gkey.split(",")),
            "family": m["family"], "count": m["count"],
            "sum": m["sum"], "min": m["min"], "max": m["max"],
            "quantiles": m["quantiles"], "payload": m["payload"],
            "mixed_families": m["mixed_families"]})
    # proxies rank with seed 0: the scatter-gather answer must not
    # depend on which member's cube seed the proxy happens to know
    entries = rank_groups(entries, mode, rank_p, 0, top)

    other = None
    if others:
        m = merge_responses(others, qeval, compression)
        if m["count"] > 0:
            other = {"family": m["family"], "count": m["count"],
                     "sum": m["sum"], "min": m["min"], "max": m["max"],
                     "quantiles": m["quantiles"],
                     "payload": m["payload"]}

    first = responses[0] if responses else {}
    return {
        "name": first.get("name", ""),
        "group_by": first.get("group_by") or [],
        "coarsened": any(r.get("coarsened") for r in responses),
        "groups": entries, "groups_total": groups_total,
        "other": other, "top": top, "by": by,
        "cube_groups_per_launch": launch,
        "slots_fused": sum(r.get("slots_fused") or 0
                           for r in responses),
        "partial": any(r.get("partial") for r in responses),
        "fresh": bool(responses) and all(r.get("fresh")
                                         for r in responses),
        "staleness_ms": max(
            (r["staleness_ms"] for r in responses
             if r.get("staleness_ms") is not None), default=None),
    }
