"""gRPC forwarding client: local instance -> global tier.

Mirrors `Server.forward`/`forwardGrpc` (flusher.go:516-591): a persistent
channel dialed once at start (optionally mTLS, server.go:810-828), and per
flush one `SendMetricsV2` client stream carrying each metric
(forwardrpc/forward.proto:12).  The service methods are invoked through
explicit method paths + serializers, which is wire-identical to generated
stubs.
"""

from __future__ import annotations

import concurrent.futures
import logging
from typing import Optional

import grpc
from google.protobuf import empty_pb2

from veneur_tpu.forward import convert
from veneur_tpu.protocol import forward_pb2, metric_pb2
from veneur_tpu.samplers import samplers as sm

logger = logging.getLogger("veneur_tpu.forward")

SEND_METRICS = "/forwardrpc.Forward/SendMetrics"
SEND_METRICS_V2 = "/forwardrpc.Forward/SendMetricsV2"


# A python-grpc client stream tops out at ~20k msgs/s (each message is a
# cond-var handoff to the channel thread).  Against this framework's own
# globals, flushes go as batched V1 MetricList RPCs (thousands of
# metrics per call); a reference global answers the first V1 attempt
# UNIMPLEMENTED (sources/proxy/server.go:138-142) and the client falls
# back permanently to the reference's V2 stream protocol, fanned out
# over parallel streams for big flushes (metrics are independent —
# merges commute — so interleaving is safe).
STREAM_CHUNK = 2048
BATCH_MAX = 2000


class _V1Unsupported(Exception):
    """The first V1 batch answered UNIMPLEMENTED before anything was
    imported: safe to fall back to V2 for the same metrics."""


class ForwardClient:
    def __init__(self, address: str,
                 credentials: Optional[grpc.ChannelCredentials] = None,
                 timeout_s: float = 10.0, max_streams: int = 8):
        self.address = address
        self.timeout_s = timeout_s
        self.max_streams = max(1, max_streams)
        if credentials is not None:
            self.channel = grpc.secure_channel(address, credentials)
        else:
            self.channel = grpc.insecure_channel(address)
        self._v2 = self.channel.stream_unary(
            SEND_METRICS_V2,
            request_serializer=metric_pb2.Metric.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        self._v1 = self.channel.unary_unary(
            SEND_METRICS,
            request_serializer=forward_pb2.MetricList.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.max_streams,
            thread_name_prefix=f"fwd-{address}")
        self._use_v1: Optional[bool] = None   # None = not yet probed

    def __call__(self, metrics: list[sm.ForwardMetric]) -> None:
        self.send(metrics)

    def send(self, metrics: list[sm.ForwardMetric]) -> None:
        """One flush's forward: batched V1 against this framework's
        globals, the reference's V2 stream protocol otherwise
        (flusher.go:578-591 semantics — every metric is Sent exactly
        once per flush)."""
        if not metrics:
            return
        pbs = [convert.to_pb(fm) for fm in metrics]
        if self._use_v1 is not False:
            try:
                self._send_v1_batches(pbs)
                # a later-chunk UNIMPLEMENTED inside the batch sender
                # flips _use_v1 off; don't override that verdict
                if self._use_v1 is not False:
                    self._use_v1 = True
                return
            except _V1Unsupported:
                # the FIRST batch (sent alone, nothing imported) got
                # UNIMPLEMENTED — either the initial probe or the global
                # failing over to a reference veneur on the same address
                # mid-life: fall back, this flush double-sends nothing
                logger.info("global %s has no V1 batch import; "
                            "using V2 streams", self.address)
                self._use_v1 = False
        self._send_v2_fanout(pbs)

    def _send_v2_fanout(self, pbs: list) -> None:
        """V2 streams, fanned out in parallel for big payloads — one
        python-grpc client stream tops out around ~20k msgs/s, so large
        flushes split round-robin across max_streams."""
        n_streams = min(self.max_streams,
                        max(1, len(pbs) // STREAM_CHUNK))
        if n_streams == 1:
            self._v2(iter(pbs), timeout=self.timeout_s)
        else:
            futs = [self._pool.submit(self._v2, iter(pbs[i::n_streams]),
                                      timeout=self.timeout_s)
                    for i in range(n_streams)]
            errs = []
            for f in futs:
                try:
                    f.result()
                except Exception as e:   # noqa: BLE001 - re-raised below
                    errs.append(e)
            if errs:
                raise errs[0]
        logger.debug("forwarded %d metrics to %s over %d streams",
                     len(pbs), self.address, n_streams)

    def _send_v1_batches(self, pbs: list) -> None:
        """BATCH_MAX-sized MetricList RPCs, in parallel for big
        flushes.  The first chunk is sent ALONE: if it answers
        UNIMPLEMENTED nothing has been imported yet, so the V2 fallback
        never double-sends.  UNIMPLEMENTED on a LATER chunk (a mixed-
        version load balancer routing chunks to a reference backend)
        re-sends exactly those chunks over V2 — chunk boundaries are
        known, so nothing double-sends — and flips _use_v1 off so the
        next flush avoids the mixed path entirely."""
        chunks = [pbs[i:i + BATCH_MAX]
                  for i in range(0, len(pbs), BATCH_MAX)]
        try:
            self._v1(forward_pb2.MetricList(metrics=chunks[0]),
                     timeout=self.timeout_s)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                raise _V1Unsupported() from e
            raise
        if len(chunks) == 1:
            return
        futs = [(c, self._pool.submit(
            self._v1, forward_pb2.MetricList(metrics=c),
            timeout=self.timeout_s)) for c in chunks[1:]]
        errs = []
        v2_retry: list = []
        n_failed_chunks = 0
        for c, f in futs:
            try:
                f.result()
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                    v2_retry.extend(c)
                    n_failed_chunks += 1
                else:
                    errs.append(e)
            except Exception as e:       # noqa: BLE001 - re-raised below
                errs.append(e)
        if v2_retry:
            logger.info(
                "global %s answered UNIMPLEMENTED on %d later V1 "
                "chunk(s); re-sending those over V2 and disabling V1",
                self.address, n_failed_chunks)
            self._use_v1 = False
            try:
                self._send_v2_fanout(v2_retry)
            except Exception as e:       # noqa: BLE001 - merged below
                # surface the V1 errors too before this propagates: the
                # operator needs both to diagnose a mixed-backend flush
                for prior in errs:
                    logger.warning(
                        "V1 chunk to %s also failed (masked by V2 "
                        "retry error): %s", self.address, prior)
                raise e
        if errs:
            raise errs[0]

    def send_v1(self, metrics: list[sm.ForwardMetric]) -> None:
        """Batch API; the reference global leaves this unimplemented
        server-side (sources/proxy/server.go:138-142) but the client
        exists for proxy compatibility."""
        req = forward_pb2.MetricList(
            metrics=[convert.to_pb(fm) for fm in metrics])
        self._v1(req, timeout=self.timeout_s)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        self.channel.close()
