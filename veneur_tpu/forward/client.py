"""gRPC forwarding client: local instance -> global tier.

Mirrors `Server.forward`/`forwardGrpc` (flusher.go:516-591): a persistent
channel dialed once at start (optionally mTLS, server.go:810-828), and per
flush one `SendMetricsV2` client stream carrying each metric
(forwardrpc/forward.proto:12).  The service methods are invoked through
explicit method paths + serializers, which is wire-identical to generated
stubs.

Retry policy: the reference's loss model is UDP-heritage — a failed
forward drops the interval.  Here each flush's send runs under a bounded
RetryPolicy (exponential backoff + seeded jitter) that retries only what
is provably undelivered: V1 batches are chunked unary RPCs, so failed
chunks are known exactly and only they are re-sent; a V2 stream retries
only when grpc pulled ZERO messages from its request iterator before
the failure (nothing can have reached the peer) — any later break may
have partially imported and is dropped rather than risk double-counting
counters.  Exhausted retries are accounted in
`dropped` (surfaced at /debug/vars and as forward.dropped_total), never
silently logged.
"""

from __future__ import annotations

import concurrent.futures
import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

import grpc
from google.protobuf import empty_pb2

from veneur_tpu import failpoints
from veneur_tpu.forward import convert
from veneur_tpu.protocol import forward_pb2, metric_pb2
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.trace import recorder as trace_rec

logger = logging.getLogger("veneur_tpu.forward")

SEND_METRICS = "/forwardrpc.Forward/SendMetrics"
SEND_METRICS_V2 = "/forwardrpc.Forward/SendMetricsV2"

# gRPC metadata key carrying one V1 chunk's (source, interval_epoch,
# chunk_id) identity — the exactly-once handle the global tier's dedup
# ledger (sources/proxy.py) keys on.  The identity is minted ONCE when
# the chunk is formed and reused verbatim by every retry and every
# spool replay, so an ambiguous failure (a timeout on a chunk the peer
# actually imported) re-delivers under the SAME identity and merges
# exactly once.
CHUNK_ID_KEY = "veneur-chunk-id"

# minimum spacing between fresh-channel re-dials after exhausted
# transport failures (see ForwardClient._maybe_redial); an extended
# outage re-dials once per failed flush at most, not once per chunk
REDIAL_MIN_INTERVAL_S = 1.0
# how long a replaced channel lingers before close(): concurrent
# forwards (up to FORWARD_MAX_IN_FLIGHT flush threads) may still hold
# in-flight RPCs on it, and closing under them turns recoverable
# failures into closed-channel drops
REDIAL_OLD_CHANNEL_LINGER_X = 2.0


def chunk_id_value(ident: tuple) -> str:
    source, epoch, idx = ident
    return f"{source}:{epoch:x}:{idx:x}"


def parse_chunk_id(value: str) -> Optional[tuple]:
    """Inverse of chunk_id_value; None on malformed input (a foreign
    sender must never fault the import path with a bad header)."""
    try:
        source, epoch_s, idx_s = str(value).rsplit(":", 2)
        if not source:
            return None
        return source, int(epoch_s, 16), int(idx_s, 16)
    except (ValueError, AttributeError):
        return None


# A python-grpc client stream tops out at ~20k msgs/s (each message is a
# cond-var handoff to the channel thread).  Against this framework's own
# globals, flushes go as batched V1 MetricList RPCs (thousands of
# metrics per call); a reference global answers the first V1 attempt
# UNIMPLEMENTED (sources/proxy/server.go:138-142) and the client falls
# back permanently to the reference's V2 stream protocol, fanned out
# over parallel streams for big flushes (metrics are independent —
# merges commute — so interleaving is safe).
STREAM_CHUNK = 2048
BATCH_MAX = 2000

# Status codes where gRPC guarantees (UNAVAILABLE: the RPC never left
# the client / the connection refused) or strongly implies
# (RESOURCE_EXHAUSTED, ABORTED: the peer rejected before applying) that
# nothing was imported — safe to re-send without double-counting.
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
    grpc.StatusCode.ABORTED,
})

# DEADLINE_EXCEEDED is AMBIGUOUS: a frozen (SIGSTOP'd, GC-paused,
# overloaded) peer neither refuses nor resets — it just hangs, and may
# import the chunk after the client gives up.  Re-sending is safe ONLY
# against a ledger-bearing global of this framework, where the chunk's
# stable identity makes re-delivery idempotent
# (config.forward_deadline_retry_safe).
DEADLINE_CODES = frozenset({grpc.StatusCode.DEADLINE_EXCEEDED})


@dataclass
class RetryPolicy:
    """Bounded exponential backoff with seeded jitter.

    attempts counts TOTAL tries (1 = no retry).  Sleep before retry k
    (k=1..) is min(backoff_max_s, backoff_base_s * 2**(k-1)) * (1 +
    jitter * U[0,1)) with U drawn from a Random(seed) stream, so a
    seeded chaos run replays the same schedule."""
    attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.5
    seed: Optional[int] = None

    def delay_s(self, retry_idx: int, rng: random.Random) -> float:
        base = min(self.backoff_max_s,
                   self.backoff_base_s * (2 ** retry_idx))
        return base * (1.0 + self.jitter * rng.random())


class _V1Unsupported(Exception):
    """The first V1 batch answered UNIMPLEMENTED before anything was
    imported: safe to fall back to V2 for the same metrics."""


@dataclass
class _Chunk:
    """One V1 MetricList chunk with its stable identity.  `ident` is
    None for payloads that lost chunk atomicity (the V2 fallback path
    against reference globals) — those are never spooled."""
    pbs: list
    ident: Optional[tuple] = None


class _SendFailure(Exception):
    """An attempt failed with `undelivered` chunks known (or
    pessimistically assumed) not to have been imported.  `retry_safe`
    means re-sending them cannot double-count (identified chunks are
    additionally idempotent via the global's dedup ledger)."""

    def __init__(self, undelivered: list, cause: BaseException,
                 retry_safe: bool):
        super().__init__(str(cause))
        self.undelivered = undelivered      # list[_Chunk]
        self.cause = cause
        self.retry_safe = retry_safe


def _code_of(exc: BaseException):
    """The grpc status code, or None (code() can fail on odd
    errors)."""
    if not isinstance(exc, grpc.RpcError):
        return None
    try:
        return exc.code()
    except Exception:   # noqa: BLE001 - code() can fail on odd errors
        return None


def _retry_safe(exc: BaseException,
                deadline_safe: bool = False) -> bool:
    if isinstance(exc, failpoints.FailpointDrop):
        return True
    code = _code_of(exc)
    return code is not None and (
        code in RETRYABLE_CODES or (
            deadline_safe and code in DEADLINE_CODES))


class ForwardClient:
    def __init__(self, address: str,
                 credentials: Optional[grpc.ChannelCredentials] = None,
                 timeout_s: float = 10.0, max_streams: int = 8,
                 retry: Optional[RetryPolicy] = None,
                 spool=None, source: str = "",
                 trace_recorder=None,
                 deadline_retry_safe: bool = False):
        """`spool` (a forward.spool.ForwardSpool) makes exhausted
        retries crash-durable: identified V1 chunks spill to disk and a
        background replayer re-delivers them oldest-first once the
        destination recovers.  `source` names this sender in chunk
        identities; a per-boot nonce is appended so a restart without a
        spool can never collide with a previous boot's epochs at the
        global's dedup ledger (spooled records keep their RECORDED
        identity — that is the exactly-once handle).  `trace_recorder`
        (a FlightRecorder) receives the forward.replay spans."""
        self.address = address
        self.timeout_s = timeout_s
        self.max_streams = max(1, max_streams)
        # DEADLINE_EXCEEDED joins the retry-safe codes only when the
        # deployment says the peer is a ledger-bearing global
        # (config.forward_deadline_retry_safe; see DEADLINE_CODES)
        self.deadline_retry_safe = bool(deadline_retry_safe)
        self.retry = retry or RetryPolicy()
        self._retry_rng = random.Random(self.retry.seed)
        self._credentials = credentials
        self._dial()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.max_streams,
            thread_name_prefix=f"fwd-{address}")
        self._use_v1: Optional[bool] = None   # None = not yet probed
        self.spool = spool
        self.trace_recorder = trace_recorder
        self.source = (f"{source or 'veneur'}"
                       f"#{time.time_ns() & 0xFFFFFFFF:08x}")
        self._epoch_seq = 0
        # diagnostics counters (surfaced at /debug/vars -> "forward" and
        # as forward.retries_total / forward.dropped_total self-metrics)
        self._stats_lock = threading.Lock()
        self.sent = 0        # metrics delivered (per-chunk accounting)
        self.retries = 0     # retry attempts taken
        self.dropped = 0     # metrics given up on after exhausted retries
        self.spilled = 0     # metrics spilled to the durable spool
        self.redials = 0     # fresh channels dialed after exhaustion
        self._last_redial = 0.0
        if self.spool is not None:
            self.spool.start_replayer(self._replay_send)

    def _dial(self) -> None:
        """(Re)build the channel and its method stubs.  Stubs are
        looked up as attributes at every call site, so an in-flight
        send on the OLD channel keeps its stubs while new sends pick
        up the fresh ones."""
        if self._credentials is not None:
            self.channel = grpc.secure_channel(self.address,
                                               self._credentials)
        else:
            self.channel = grpc.insecure_channel(self.address)
        self._v2 = self.channel.stream_unary(
            SEND_METRICS_V2,
            request_serializer=metric_pb2.Metric.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        self._v1 = self.channel.unary_unary(
            SEND_METRICS,
            request_serializer=forward_pb2.MetricList.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        # raw-bytes V1 sender: spool replay re-delivers the serialized
        # MetricList exactly as recorded (no re-parse, same identity)
        self._v1_raw = self.channel.unary_unary(
            SEND_METRICS,
            request_serializer=lambda b: b,
            response_deserializer=empty_pb2.Empty.FromString)

    def _maybe_redial(self, cause: BaseException) -> None:
        """Exhausted retries on a REAL transport failure: swap in a
        fresh channel so later flushes (and spool replay ticks) never
        inherit this channel's subchannel state.

        This is the wedged-subchannel-after-peer-death audit fix
        (ROADMAP #5e): a peer that died under a live channel leaves
        its subchannel in TRANSIENT_FAILURE with growing backoff, and
        fail-fast RPCs can keep failing UNAVAILABLE long after the
        peer revived on the same port — the mode that bit spool
        replay.  The proxy tier is immune by construction (a failed
        Destination is destroyed with its channel and the half-open
        probe dials fresh); this gives the forward client the same
        re-dial-fresh story WITHOUT changing RPC semantics — live
        sends stay fail-fast, so a dead peer still fails UNAVAILABLE
        (provably undelivered -> spool-able), never an ambiguous
        wait-for-ready DEADLINE.  Injected failpoint faults never
        re-dial (chaos must not churn channels), and re-dials are
        rate-limited.  The old channel lingers before close():
        concurrent forwards may hold in-flight RPCs on it."""
        if (not isinstance(cause, grpc.RpcError)
                or getattr(cause, "failpoint", None)):
            return
        now = time.monotonic()
        with self._stats_lock:
            if now - self._last_redial < REDIAL_MIN_INTERVAL_S:
                return
            self._last_redial = now
            self.redials += 1
            old = self.channel
        logger.info("forward to %s: re-dialing a fresh channel after "
                    "exhausted retries (%s)", self.address, cause)
        self._dial()
        timer = threading.Timer(
            REDIAL_OLD_CHANNEL_LINGER_X * self.timeout_s, old.close)
        timer.daemon = True
        timer.start()

    # the server's flush path may hand a trace parent span down
    # (core/server.py _forward_safely); custom forwarder callables that
    # lack this attribute are called with metrics alone
    accepts_trace = True
    # ...and the flush interval as the chunk-identity epoch
    accepts_epoch = True

    def __call__(self, metrics: list[sm.ForwardMetric],
                 trace_parent=None, epoch: Optional[int] = None) -> None:
        self.send(metrics, trace_parent=trace_parent, epoch=epoch)

    def stats(self) -> dict[str, int]:
        with self._stats_lock:
            return {"sent": self.sent, "retries": self.retries,
                    "dropped": self.dropped, "spilled": self.spilled,
                    "redials": self.redials}

    def spool_stats(self) -> Optional[dict]:
        return None if self.spool is None else self.spool.stats()

    def _count(self, field: str, n: int) -> None:
        with self._stats_lock:
            setattr(self, field, getattr(self, field) + n)

    def _rsafe(self, exc: BaseException) -> bool:
        """This client's retry-safety verdict for one failure (the
        module-level table plus the deadline opt-in)."""
        return _retry_safe(exc, self.deadline_retry_safe)

    def send(self, metrics: list[sm.ForwardMetric],
             trace_parent=None, epoch: Optional[int] = None) -> None:
        """One flush's forward: batched V1 against this framework's
        globals, the reference's V2 stream protocol otherwise
        (flusher.go:578-591 semantics — every metric is Sent exactly
        once per flush), under the bounded RetryPolicy."""
        if not metrics:
            return
        self.send_pbs([convert.to_pb(fm) for fm in metrics],
                      trace_parent=trace_parent, epoch=epoch)

    def _mint_epoch(self) -> int:
        with self._stats_lock:
            self._epoch_seq += 1
            return self._epoch_seq

    def send_pbs(self, pbs: list, trace_parent=None,
                 epoch: Optional[int] = None) -> None:
        """With `trace_parent` (a trace.Span), every attempt becomes one
        child span — tagged with its attempt index, outcome, and the
        injected failpoint name when chaos fired — and the attempt's
        trace context rides the RPC metadata, so the receiving proxy /
        global parents its own span to exactly the attempt that
        delivered the metrics (duplicate attempts stay leaf spans with
        error=true; only the delivered edge continues the trace).

        The payload is chunked ONCE up front and every chunk's identity
        (source, epoch, chunk_id) is minted here — retries, the durable
        spool and its replays all reuse the same identity, which is
        what lets the global's dedup ledger make re-delivery
        idempotent.  `epoch` is the caller's interval number (the
        server passes its flush count, which survives a checkpoint
        restore); None mints a client-local epoch."""
        epoch = self._mint_epoch() if epoch is None else int(epoch)
        remaining = [
            _Chunk(pbs[i:i + BATCH_MAX],
                   ident=(self.source, epoch, i // BATCH_MAX))
            for i in range(0, len(pbs), BATCH_MAX)]
        retry_idx = 0
        while True:
            aspan = (trace_parent.child(
                "forward.attempt",
                tags={"attempt": str(retry_idx + 1),
                      "metrics": str(sum(len(c.pbs)
                                         for c in remaining))})
                if trace_parent is not None else None)
            try:
                self._send_attempt(
                    remaining,
                    metadata=(None if aspan is None else
                              trace_rec.ctx_metadata(aspan.trace_id,
                                                     aspan.span_id)))
                return
            except _SendFailure as f:
                if aspan is not None:
                    aspan.error = True
                    aspan.tags["cause"] = type(f.cause).__name__
                    fp = getattr(f.cause, "failpoint", None)
                    if fp:
                        aspan.tags["failpoint"] = str(fp)
                    # stamp the failure now — the finally also finishes
                    # (idempotently) but only after the backoff sleep
                    aspan.finish()
                remaining = f.undelivered
                if (not f.retry_safe
                        or retry_idx >= self.retry.attempts - 1):
                    self._spill_or_drop(remaining, f, retry_idx + 1,
                                        trace_parent)
                    return
                self._count("retries", 1)
                delay = self.retry.delay_s(retry_idx, self._retry_rng)
                logger.info(
                    "forward to %s: attempt %d failed (%s); retrying %d "
                    "chunks in %.0f ms", self.address, retry_idx + 1,
                    f.cause, len(remaining), delay * 1e3)
                time.sleep(delay)
                retry_idx += 1
            finally:
                if aspan is not None:
                    aspan.finish()

    def _spill_or_drop(self, chunks: list, f: _SendFailure,
                       attempts: int, trace_parent=None) -> None:
        """Exhausted remainder: PROVABLY-undelivered identified chunks
        spill to the durable spool; everything else — ambiguous
        failures (the peer may be a proxy, which re-shards without a
        dedup ledger, so re-delivery could double-count), anonymous V2
        remainders, spool off, disk errors — drops with accounting and
        re-raises the cause.  The chunk identity still guards the
        REPLAY path's own crash window against a ledger-bearing
        global."""
        # exhausted transport failures re-dial a fresh channel so the
        # NEXT flush / replay tick cannot inherit a wedged subchannel
        self._maybe_redial(f.cause)
        spilled = dropped = 0
        tid = sid = 0
        if trace_parent is not None:
            tid, sid = trace_parent.trace_id, trace_parent.span_id
        for c in chunks:
            if (self.spool is not None and c.ident is not None
                    and f.retry_safe):
                body = forward_pb2.MetricList(
                    metrics=c.pbs).SerializeToString()
                if self.spool.append(c.ident, body, len(c.pbs),
                                     trace_id=tid, span_id=sid):
                    spilled += len(c.pbs)
                    continue
            dropped += len(c.pbs)
        if spilled:
            self._count("spilled", spilled)
            logger.info(
                "forward to %s: spilled %d metrics to the spool after "
                "%d attempt(s) (%s); background replay will re-deliver",
                self.address, spilled, attempts, f.cause)
        if dropped:
            self._count("dropped", dropped)
            logger.warning(
                "forward to %s: dropping %d metrics after %d "
                "attempt(s) (%s%s)", self.address, dropped, attempts,
                f.cause, "" if f.retry_safe else "; not retry-safe")
            raise f.cause

    def _replay_send(self, rec, body: bytes) -> None:
        """Spool replay delivery: the recorded MetricList bytes go out
        as one raw V1 RPC under the RECORDED chunk identity, with a
        forward.replay span continuing the original interval's trace
        context so the cross-tier assembler sees one trace across the
        crash.  Retry-safe failures re-raise as RetryableReplayError
        (the spool keeps the record for the next tick).

        The RPC runs wait_for_ready: a fail-fast RPC on a channel
        whose peer DIED (real SIGKILL, not a refused dial) leaves the
        subchannel wedged in TRANSIENT_FAILURE — grpc never re-dials
        for it, so every replay tick fails UNAVAILABLE forever even
        after the peer revives on the same port, and the record ages
        out.  A queued (wait-for-ready) pick keeps the channel
        dialing; the deadline still bounds each attempt.  Whether an
        expired deadline KEEPS the record follows the same
        forward_deadline_retry_safe gate as live sends: against a
        ledger-bearing peer the next tick's re-delivery under the
        same chunk identity merges exactly once, but through a PROXY
        (which re-shards per-metric and does not propagate chunk
        identity) an ambiguous deadline re-delivery would double-
        count — there the record is dropped with accounting, same as
        a live send."""
        from veneur_tpu.forward import spool as spool_mod
        span = None
        if rec.trace_id and rec.span_id:
            span = trace_rec.continue_span(
                "forward.replay", rec.trace_id, rec.span_id,
                tags={"chunk": chunk_id_value(rec.ident),
                      "metrics": str(rec.n_metrics)})
            span.client = None
        metadata = ((CHUNK_ID_KEY, chunk_id_value(rec.ident)),)
        if span is not None:
            metadata += trace_rec.ctx_metadata(span.trace_id,
                                               span.span_id)
        try:
            self._v1_raw(body, timeout=self.timeout_s,
                         metadata=metadata, wait_for_ready=True)
        except grpc.RpcError as e:
            if span is not None:
                span.error = True
            if self._rsafe(e):
                raise spool_mod.RetryableReplayError(str(e)) from e
            raise
        finally:
            if span is not None:
                span.finish()
                if self.trace_recorder is not None:
                    self.trace_recorder.record_span(span)
        self._count("sent", rec.n_metrics)

    def _send_attempt(self, chunks: list, metadata=None) -> None:
        """One try at delivering `chunks`; raises _SendFailure carrying
        exactly what is still undelivered."""
        try:
            failpoints.inject("forward.send")
        except (failpoints.FailpointDrop, grpc.RpcError) as e:
            raise _SendFailure(chunks, e, self._rsafe(e)) from e
        if self._use_v1 is not False:
            try:
                self._send_v1_batches(chunks, metadata=metadata)
                # a later-chunk UNIMPLEMENTED inside the batch sender
                # flips _use_v1 off; don't override that verdict
                if self._use_v1 is not False:
                    self._use_v1 = True
                return
            # vnlint: disable=silent-loss (protocol FALLBACK, not loss:
            #   the first batch was sent alone so nothing imported, and
            #   the whole payload re-sends over V2 streams below)
            except _V1Unsupported:
                # the FIRST batch (sent alone, nothing imported) got
                # UNIMPLEMENTED — either the initial probe or the global
                # failing over to a reference veneur on the same address
                # mid-life: fall back, this flush double-sends nothing
                logger.info("global %s has no V1 batch import; "
                            "using V2 streams", self.address)
                self._use_v1 = False
        pbs = [pb for c in chunks for pb in c.pbs]
        try:
            self._send_v2_fanout(pbs, metadata=metadata)
        except _SendFailure as f:
            # V2 loses chunk atomicity: the undelivered remainder is one
            # anonymous chunk (never spooled — a reference global has no
            # dedup ledger to make re-delivery idempotent)
            raise _SendFailure([_Chunk(f.undelivered)], f.cause,
                               f.retry_safe) from f.cause

    def _send_v2_fanout(self, pbs: list, metadata=None) -> None:
        """V2 streams, fanned out in parallel for big payloads — one
        python-grpc client stream tops out around ~20k msgs/s, so large
        flushes split round-robin across max_streams.

        Retry safety is PESSIMISTIC here: the import server applies V2
        messages incrementally as the stream flows, so a break after the
        first message may have partially imported the slice — blind
        re-send would double-count counters.  Each stream's request
        iterator therefore tracks how many messages grpc has PULLED;
        only a failure with zero pulled (connection never got a message
        to carry — e.g. refused at dial, or an injected pre-send fault)
        is retry-safe.  Anything later is dropped and ACCOUNTED instead
        (the V1 batch path, which is chunk-atomic, carries the
        fleet-internal retry story)."""
        n_streams = min(self.max_streams,
                        max(1, len(pbs) // STREAM_CHUNK))

        class _Stream:
            __slots__ = ("pulled",)

            def __init__(self):
                self.pulled = 0

            def run(self, client: "ForwardClient",
                    slice_pbs: list) -> None:
                failpoints.inject("forward.v2_stream")

                def it():
                    for pb in slice_pbs:
                        self.pulled += 1
                        yield pb
                client._v2(it(), timeout=client.timeout_s,
                           metadata=metadata)

        def stream_safe(st: _Stream, e: BaseException) -> bool:
            return st.pulled == 0 and self._rsafe(e)

        if n_streams == 1:
            st = _Stream()
            try:
                st.run(self, pbs)
            except (grpc.RpcError, failpoints.FailpointDrop) as e:
                raise _SendFailure(pbs, e, stream_safe(st, e)) from e
            self._count("sent", len(pbs))
        else:
            slices = [pbs[i::n_streams] for i in range(n_streams)]
            streams = [_Stream() for _ in slices]
            futs = [self._pool.submit(st.run, self, s)
                    for st, s in zip(streams, slices)]
            undelivered: list = []
            errs = []
            safe = True
            for st, s, f in zip(streams, slices, futs):
                try:
                    f.result()
                    self._count("sent", len(s))
                except Exception as e:   # noqa: BLE001 - re-raised below
                    undelivered.extend(s)
                    errs.append(e)
                    safe = safe and stream_safe(st, e)
            if errs:
                raise _SendFailure(undelivered, errs[0],
                                   safe) from errs[0]
        logger.debug("forwarded %d metrics to %s over %d streams",
                     len(pbs), self.address, n_streams)

    @staticmethod
    def _chunk_metadata(metadata, chunk: _Chunk):
        """The per-RPC metadata: the attempt's trace context plus this
        chunk's stable identity header."""
        if chunk.ident is None:
            return metadata
        entry = ((CHUNK_ID_KEY, chunk_id_value(chunk.ident)),)
        return entry if metadata is None else tuple(metadata) + entry

    def _send_v1_batches(self, chunks: list, metadata=None) -> None:
        """One MetricList RPC per chunk, in parallel for big flushes,
        each carrying its chunk-identity metadata.  The first chunk is
        sent ALONE: if it answers UNIMPLEMENTED nothing has been
        imported yet, so the V2 fallback never double-sends.
        UNIMPLEMENTED on a LATER chunk (a mixed-version load balancer
        routing chunks to a reference backend) re-sends exactly those
        chunks over V2 — chunk boundaries are known, so nothing
        double-sends — and flips _use_v1 off so the next flush avoids
        the mixed path entirely.  Any other chunk failure surfaces as
        _SendFailure carrying exactly the failed chunks, so the retry
        loop re-sends only those (under their original identities)."""
        try:
            self._v1(forward_pb2.MetricList(metrics=chunks[0].pbs),
                     timeout=self.timeout_s,
                     metadata=self._chunk_metadata(metadata, chunks[0]))
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                raise _V1Unsupported() from e
            # nothing delivered yet: every chunk is undelivered
            raise _SendFailure(list(chunks), e, self._rsafe(e)) from e
        self._count("sent", len(chunks[0].pbs))
        if len(chunks) == 1:
            return
        futs = [(c, self._pool.submit(self._send_v1_chunk, c, metadata))
                for c in chunks[1:]]
        errs = []
        undelivered: list = []
        v2_retry: list = []
        for c, f in futs:
            try:
                f.result()
                self._count("sent", len(c.pbs))
            # vnlint: disable=silent-loss (errors COLLECT, then
            #   re-raise: an UNIMPLEMENTED chunk re-sends over V2 below,
            #   and errs/undelivered raise _SendFailure at the end of
            #   this function — the bounded retry loop owns accounting)
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                    v2_retry.extend(c.pbs)
                else:
                    errs.append(e)
                    undelivered.append(c)
            # vnlint: disable=silent-loss (same collect-then-re-raise
            #   contract as the RpcError arm above)
            except Exception as e:       # noqa: BLE001 - re-raised below
                errs.append(e)
                undelivered.append(c)
        if v2_retry:
            logger.info(
                "global %s answered UNIMPLEMENTED on later V1 "
                "chunk(s); re-sending %d metrics over V2 and disabling "
                "V1", self.address, len(v2_retry))
            self._use_v1 = False
            try:
                self._send_v2_fanout(v2_retry, metadata=metadata)
            except _SendFailure as f:
                # fold the V2-undelivered remainder into this attempt's
                # failure so the OUTER bounded retry loop re-sends it —
                # the old behavior was a single unbounded shot that
                # logged the V1 errors and gave up
                for prior in errs:
                    logger.warning(
                        "V1 chunk to %s also failed (alongside the V2 "
                        "retry failure): %s", self.address, prior)
                undelivered.append(_Chunk(f.undelivered))
                raise _SendFailure(
                    undelivered, f.cause,
                    f.retry_safe and all(self._rsafe(e) for e in errs)
                ) from f.cause
        if errs:
            raise _SendFailure(
                undelivered, errs[0],
                all(self._rsafe(e) for e in errs)) from errs[0]

    def _send_v1_chunk(self, chunk: _Chunk, metadata=None) -> None:
        self._v1(forward_pb2.MetricList(metrics=chunk.pbs),
                 timeout=self.timeout_s,
                 metadata=self._chunk_metadata(metadata, chunk))

    def send_v1(self, metrics: list[sm.ForwardMetric]) -> None:
        """Batch API; the reference global leaves this unimplemented
        server-side (sources/proxy/server.go:138-142) but the client
        exists for proxy compatibility."""
        req = forward_pb2.MetricList(
            metrics=[convert.to_pb(fm) for fm in metrics])
        self._v1(req, timeout=self.timeout_s)

    def close(self, drain_spool: bool = True) -> None:
        if self.spool is not None:
            # graceful close fsyncs the spool tail; a simulated crash
            # (Server.crash) passes drain_spool=False and relies on
            # the per-append flush + recovery scan
            self.spool.close(drain=drain_spool)
        self._pool.shutdown(wait=False)
        self.channel.close()
