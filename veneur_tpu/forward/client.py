"""gRPC forwarding client: local instance -> global tier.

Mirrors `Server.forward`/`forwardGrpc` (flusher.go:516-591): a persistent
channel dialed once at start (optionally mTLS, server.go:810-828), and per
flush one `SendMetricsV2` client stream carrying each metric
(forwardrpc/forward.proto:12).  The service methods are invoked through
explicit method paths + serializers, which is wire-identical to generated
stubs.
"""

from __future__ import annotations

import logging
from typing import Optional

import grpc
from google.protobuf import empty_pb2

from veneur_tpu.forward import convert
from veneur_tpu.protocol import forward_pb2, metric_pb2
from veneur_tpu.samplers import samplers as sm

logger = logging.getLogger("veneur_tpu.forward")

SEND_METRICS = "/forwardrpc.Forward/SendMetrics"
SEND_METRICS_V2 = "/forwardrpc.Forward/SendMetricsV2"


class ForwardClient:
    def __init__(self, address: str,
                 credentials: Optional[grpc.ChannelCredentials] = None,
                 timeout_s: float = 10.0):
        self.address = address
        self.timeout_s = timeout_s
        if credentials is not None:
            self.channel = grpc.secure_channel(address, credentials)
        else:
            self.channel = grpc.insecure_channel(address)
        self._v2 = self.channel.stream_unary(
            SEND_METRICS_V2,
            request_serializer=metric_pb2.Metric.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        self._v1 = self.channel.unary_unary(
            SEND_METRICS,
            request_serializer=forward_pb2.MetricList.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)

    def __call__(self, metrics: list[sm.ForwardMetric]) -> None:
        self.send(metrics)

    def send(self, metrics: list[sm.ForwardMetric]) -> None:
        """One stream per flush, one Send per metric
        (flusher.go:578-591)."""
        if not metrics:
            return
        pbs = [convert.to_pb(fm) for fm in metrics]
        self._v2(iter(pbs), timeout=self.timeout_s)
        logger.debug("forwarded %d metrics to %s", len(pbs), self.address)

    def send_v1(self, metrics: list[sm.ForwardMetric]) -> None:
        """Batch API; the reference global leaves this unimplemented
        server-side (sources/proxy/server.go:138-142) but the client
        exists for proxy compatibility."""
        req = forward_pb2.MetricList(
            metrics=[convert.to_pb(fm) for fm in metrics])
        self._v1(req, timeout=self.timeout_s)

    def close(self) -> None:
        self.channel.close()
