"""gRPC forwarding client: local instance -> global tier.

Mirrors `Server.forward`/`forwardGrpc` (flusher.go:516-591): a persistent
channel dialed once at start (optionally mTLS, server.go:810-828), and per
flush one `SendMetricsV2` client stream carrying each metric
(forwardrpc/forward.proto:12).  The service methods are invoked through
explicit method paths + serializers, which is wire-identical to generated
stubs.

Retry policy: the reference's loss model is UDP-heritage — a failed
forward drops the interval.  Here each flush's send runs under a bounded
RetryPolicy (exponential backoff + seeded jitter) that retries only what
is provably undelivered: V1 batches are chunked unary RPCs, so failed
chunks are known exactly and only they are re-sent; a V2 stream retries
only when grpc pulled ZERO messages from its request iterator before
the failure (nothing can have reached the peer) — any later break may
have partially imported and is dropped rather than risk double-counting
counters.  Exhausted retries are accounted in
`dropped` (surfaced at /debug/vars and as forward.dropped_total), never
silently logged.
"""

from __future__ import annotations

import concurrent.futures
import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

import grpc
from google.protobuf import empty_pb2

from veneur_tpu import failpoints
from veneur_tpu.forward import convert
from veneur_tpu.protocol import forward_pb2, metric_pb2
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.trace import recorder as trace_rec

logger = logging.getLogger("veneur_tpu.forward")

SEND_METRICS = "/forwardrpc.Forward/SendMetrics"
SEND_METRICS_V2 = "/forwardrpc.Forward/SendMetricsV2"


# A python-grpc client stream tops out at ~20k msgs/s (each message is a
# cond-var handoff to the channel thread).  Against this framework's own
# globals, flushes go as batched V1 MetricList RPCs (thousands of
# metrics per call); a reference global answers the first V1 attempt
# UNIMPLEMENTED (sources/proxy/server.go:138-142) and the client falls
# back permanently to the reference's V2 stream protocol, fanned out
# over parallel streams for big flushes (metrics are independent —
# merges commute — so interleaving is safe).
STREAM_CHUNK = 2048
BATCH_MAX = 2000

# Status codes where gRPC guarantees (UNAVAILABLE: the RPC never left
# the client / the connection refused) or strongly implies
# (RESOURCE_EXHAUSTED, ABORTED: the peer rejected before applying) that
# nothing was imported — safe to re-send without double-counting.
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
    grpc.StatusCode.ABORTED,
})


@dataclass
class RetryPolicy:
    """Bounded exponential backoff with seeded jitter.

    attempts counts TOTAL tries (1 = no retry).  Sleep before retry k
    (k=1..) is min(backoff_max_s, backoff_base_s * 2**(k-1)) * (1 +
    jitter * U[0,1)) with U drawn from a Random(seed) stream, so a
    seeded chaos run replays the same schedule."""
    attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.5
    seed: Optional[int] = None

    def delay_s(self, retry_idx: int, rng: random.Random) -> float:
        base = min(self.backoff_max_s,
                   self.backoff_base_s * (2 ** retry_idx))
        return base * (1.0 + self.jitter * rng.random())


class _V1Unsupported(Exception):
    """The first V1 batch answered UNIMPLEMENTED before anything was
    imported: safe to fall back to V2 for the same metrics."""


class _SendFailure(Exception):
    """An attempt failed with `undelivered` protobuf metrics known (or
    pessimistically assumed) not to have been imported.  `retry_safe`
    means re-sending them cannot double-count."""

    def __init__(self, undelivered: list, cause: BaseException,
                 retry_safe: bool):
        super().__init__(str(cause))
        self.undelivered = undelivered
        self.cause = cause
        self.retry_safe = retry_safe


def _retry_safe(exc: BaseException) -> bool:
    if isinstance(exc, failpoints.FailpointDrop):
        return True
    if isinstance(exc, grpc.RpcError):
        try:
            return exc.code() in RETRYABLE_CODES
        except Exception:   # noqa: BLE001 - code() can fail on odd errors
            return False
    return False


class ForwardClient:
    def __init__(self, address: str,
                 credentials: Optional[grpc.ChannelCredentials] = None,
                 timeout_s: float = 10.0, max_streams: int = 8,
                 retry: Optional[RetryPolicy] = None):
        self.address = address
        self.timeout_s = timeout_s
        self.max_streams = max(1, max_streams)
        self.retry = retry or RetryPolicy()
        self._retry_rng = random.Random(self.retry.seed)
        if credentials is not None:
            self.channel = grpc.secure_channel(address, credentials)
        else:
            self.channel = grpc.insecure_channel(address)
        self._v2 = self.channel.stream_unary(
            SEND_METRICS_V2,
            request_serializer=metric_pb2.Metric.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        self._v1 = self.channel.unary_unary(
            SEND_METRICS,
            request_serializer=forward_pb2.MetricList.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.max_streams,
            thread_name_prefix=f"fwd-{address}")
        self._use_v1: Optional[bool] = None   # None = not yet probed
        # diagnostics counters (surfaced at /debug/vars -> "forward" and
        # as forward.retries_total / forward.dropped_total self-metrics)
        self._stats_lock = threading.Lock()
        self.sent = 0        # metrics delivered (per-chunk accounting)
        self.retries = 0     # retry attempts taken
        self.dropped = 0     # metrics given up on after exhausted retries

    # the server's flush path may hand a trace parent span down
    # (core/server.py _forward_safely); custom forwarder callables that
    # lack this attribute are called with metrics alone
    accepts_trace = True

    def __call__(self, metrics: list[sm.ForwardMetric],
                 trace_parent=None) -> None:
        self.send(metrics, trace_parent=trace_parent)

    def stats(self) -> dict[str, int]:
        with self._stats_lock:
            return {"sent": self.sent, "retries": self.retries,
                    "dropped": self.dropped}

    def _count(self, field: str, n: int) -> None:
        with self._stats_lock:
            setattr(self, field, getattr(self, field) + n)

    def send(self, metrics: list[sm.ForwardMetric],
             trace_parent=None) -> None:
        """One flush's forward: batched V1 against this framework's
        globals, the reference's V2 stream protocol otherwise
        (flusher.go:578-591 semantics — every metric is Sent exactly
        once per flush), under the bounded RetryPolicy."""
        if not metrics:
            return
        self.send_pbs([convert.to_pb(fm) for fm in metrics],
                      trace_parent=trace_parent)

    def send_pbs(self, pbs: list, trace_parent=None) -> None:
        """With `trace_parent` (a trace.Span), every attempt becomes one
        child span — tagged with its attempt index, outcome, and the
        injected failpoint name when chaos fired — and the attempt's
        trace context rides the RPC metadata, so the receiving proxy /
        global parents its own span to exactly the attempt that
        delivered the metrics (duplicate attempts stay leaf spans with
        error=true; only the delivered edge continues the trace)."""
        remaining = pbs
        retry_idx = 0
        while True:
            aspan = (trace_parent.child(
                "forward.attempt",
                tags={"attempt": str(retry_idx + 1),
                      "metrics": str(len(remaining))})
                if trace_parent is not None else None)
            try:
                self._send_attempt(
                    remaining,
                    metadata=(None if aspan is None else
                              trace_rec.ctx_metadata(aspan.trace_id,
                                                     aspan.span_id)))
                return
            except _SendFailure as f:
                if aspan is not None:
                    aspan.error = True
                    aspan.tags["cause"] = type(f.cause).__name__
                    fp = getattr(f.cause, "failpoint", None)
                    if fp:
                        aspan.tags["failpoint"] = str(fp)
                    # stamp the failure now — the finally also finishes
                    # (idempotently) but only after the backoff sleep
                    aspan.finish()
                remaining = f.undelivered
                if (not f.retry_safe
                        or retry_idx >= self.retry.attempts - 1):
                    self._count("dropped", len(remaining))
                    logger.warning(
                        "forward to %s: dropping %d metrics after %d "
                        "attempt(s) (%s%s)", self.address, len(remaining),
                        retry_idx + 1, f.cause,
                        "" if f.retry_safe else "; not retry-safe")
                    raise f.cause
                self._count("retries", 1)
                delay = self.retry.delay_s(retry_idx, self._retry_rng)
                logger.info(
                    "forward to %s: attempt %d failed (%s); retrying %d "
                    "metrics in %.0f ms", self.address, retry_idx + 1,
                    f.cause, len(remaining), delay * 1e3)
                time.sleep(delay)
                retry_idx += 1
            finally:
                if aspan is not None:
                    aspan.finish()

    def _send_attempt(self, pbs: list, metadata=None) -> None:
        """One try at delivering `pbs`; raises _SendFailure carrying
        exactly what is still undelivered."""
        try:
            failpoints.inject("forward.send")
        except (failpoints.FailpointDrop, grpc.RpcError) as e:
            raise _SendFailure(pbs, e, _retry_safe(e)) from e
        if self._use_v1 is not False:
            try:
                self._send_v1_batches(pbs, metadata=metadata)
                # a later-chunk UNIMPLEMENTED inside the batch sender
                # flips _use_v1 off; don't override that verdict
                if self._use_v1 is not False:
                    self._use_v1 = True
                return
            except _V1Unsupported:
                # the FIRST batch (sent alone, nothing imported) got
                # UNIMPLEMENTED — either the initial probe or the global
                # failing over to a reference veneur on the same address
                # mid-life: fall back, this flush double-sends nothing
                logger.info("global %s has no V1 batch import; "
                            "using V2 streams", self.address)
                self._use_v1 = False
        self._send_v2_fanout(pbs, metadata=metadata)

    def _send_v2_fanout(self, pbs: list, metadata=None) -> None:
        """V2 streams, fanned out in parallel for big payloads — one
        python-grpc client stream tops out around ~20k msgs/s, so large
        flushes split round-robin across max_streams.

        Retry safety is PESSIMISTIC here: the import server applies V2
        messages incrementally as the stream flows, so a break after the
        first message may have partially imported the slice — blind
        re-send would double-count counters.  Each stream's request
        iterator therefore tracks how many messages grpc has PULLED;
        only a failure with zero pulled (connection never got a message
        to carry — e.g. refused at dial, or an injected pre-send fault)
        is retry-safe.  Anything later is dropped and ACCOUNTED instead
        (the V1 batch path, which is chunk-atomic, carries the
        fleet-internal retry story)."""
        n_streams = min(self.max_streams,
                        max(1, len(pbs) // STREAM_CHUNK))

        class _Stream:
            __slots__ = ("pulled",)

            def __init__(self):
                self.pulled = 0

            def run(self, client: "ForwardClient",
                    slice_pbs: list) -> None:
                failpoints.inject("forward.v2_stream")

                def it():
                    for pb in slice_pbs:
                        self.pulled += 1
                        yield pb
                client._v2(it(), timeout=client.timeout_s,
                           metadata=metadata)

        def stream_safe(st: _Stream, e: BaseException) -> bool:
            return st.pulled == 0 and _retry_safe(e)

        if n_streams == 1:
            st = _Stream()
            try:
                st.run(self, pbs)
            except (grpc.RpcError, failpoints.FailpointDrop) as e:
                raise _SendFailure(pbs, e, stream_safe(st, e)) from e
            self._count("sent", len(pbs))
        else:
            slices = [pbs[i::n_streams] for i in range(n_streams)]
            streams = [_Stream() for _ in slices]
            futs = [self._pool.submit(st.run, self, s)
                    for st, s in zip(streams, slices)]
            undelivered: list = []
            errs = []
            safe = True
            for st, s, f in zip(streams, slices, futs):
                try:
                    f.result()
                    self._count("sent", len(s))
                except Exception as e:   # noqa: BLE001 - re-raised below
                    undelivered.extend(s)
                    errs.append(e)
                    safe = safe and stream_safe(st, e)
            if errs:
                raise _SendFailure(undelivered, errs[0],
                                   safe) from errs[0]
        logger.debug("forwarded %d metrics to %s over %d streams",
                     len(pbs), self.address, n_streams)

    def _send_v1_batches(self, pbs: list, metadata=None) -> None:
        """BATCH_MAX-sized MetricList RPCs, in parallel for big
        flushes.  The first chunk is sent ALONE: if it answers
        UNIMPLEMENTED nothing has been imported yet, so the V2 fallback
        never double-sends.  UNIMPLEMENTED on a LATER chunk (a mixed-
        version load balancer routing chunks to a reference backend)
        re-sends exactly those chunks over V2 — chunk boundaries are
        known, so nothing double-sends — and flips _use_v1 off so the
        next flush avoids the mixed path entirely.  Any other chunk
        failure surfaces as _SendFailure carrying exactly the failed
        chunks' metrics, so the retry loop re-sends only those."""
        chunks = [pbs[i:i + BATCH_MAX]
                  for i in range(0, len(pbs), BATCH_MAX)]
        try:
            self._v1(forward_pb2.MetricList(metrics=chunks[0]),
                     timeout=self.timeout_s, metadata=metadata)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                raise _V1Unsupported() from e
            # nothing delivered yet: every chunk is undelivered
            raise _SendFailure(pbs, e, _retry_safe(e)) from e
        self._count("sent", len(chunks[0]))
        if len(chunks) == 1:
            return
        futs = [(c, self._pool.submit(self._send_v1_chunk, c, metadata))
                for c in chunks[1:]]
        errs = []
        undelivered: list = []
        v2_retry: list = []
        n_unimpl_chunks = 0
        for c, f in futs:
            try:
                f.result()
                self._count("sent", len(c))
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                    v2_retry.extend(c)
                    n_unimpl_chunks += 1
                else:
                    errs.append(e)
                    undelivered.extend(c)
            except Exception as e:       # noqa: BLE001 - re-raised below
                errs.append(e)
                undelivered.extend(c)
        if v2_retry:
            logger.info(
                "global %s answered UNIMPLEMENTED on %d later V1 "
                "chunk(s); re-sending those over V2 and disabling V1",
                self.address, n_unimpl_chunks)
            self._use_v1 = False
            try:
                self._send_v2_fanout(v2_retry, metadata=metadata)
            except _SendFailure as f:
                # fold the V2-undelivered remainder into this attempt's
                # failure so the OUTER bounded retry loop re-sends it —
                # the old behavior was a single unbounded shot that
                # logged the V1 errors and gave up
                for prior in errs:
                    logger.warning(
                        "V1 chunk to %s also failed (alongside the V2 "
                        "retry failure): %s", self.address, prior)
                undelivered.extend(f.undelivered)
                raise _SendFailure(
                    undelivered, f.cause,
                    f.retry_safe and all(_retry_safe(e) for e in errs)
                ) from f.cause
        if errs:
            raise _SendFailure(
                undelivered, errs[0],
                all(_retry_safe(e) for e in errs)) from errs[0]

    def _send_v1_chunk(self, chunk: list, metadata=None) -> None:
        self._v1(forward_pb2.MetricList(metrics=chunk),
                 timeout=self.timeout_s, metadata=metadata)

    def send_v1(self, metrics: list[sm.ForwardMetric]) -> None:
        """Batch API; the reference global leaves this unimplemented
        server-side (sources/proxy/server.go:138-142) but the client
        exists for proxy compatibility."""
        req = forward_pb2.MetricList(
            metrics=[convert.to_pb(fm) for fm in metrics])
        self._v1(req, timeout=self.timeout_s)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        self.channel.close()
