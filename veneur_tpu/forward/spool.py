"""Durable forward spool: crash-safe buffering of undelivered chunks.

When the bounded RetryPolicy (forward/client.py) exhausts against a
down destination, the provably-chunked V1 payloads are not dropped —
they are serialized into an on-disk segment spool and replayed
oldest-first once the destination recovers.  Combined with the chunk
identity each payload carries on gRPC metadata and the global tier's
dedup ledger (sources/proxy.py), delivery becomes exactly-once across
crashes on EITHER side of the edge:

  * sender crash: spool segments survive on disk; the revived client
    replays them with their RECORDED identities, so a chunk that was
    actually delivered before the crash (an ambiguous timeout) merges
    once at the global.
  * receiver crash: the global's ledger rides its checkpoint
    (core/checkpoint.py), so a chunk imported pre-crash and replayed
    post-restore is recognized and skipped.

Only PROVABLY-undelivered chunks enter the spool (retry-safe failure
codes, forward/client.py): a proxy peer re-shards batches without a
dedup ledger, so re-delivering an *ambiguous* failure through it could
double-count — those keep the pre-spool drop-with-accounting
behavior.  The identity header therefore guards the replay path's own
ambiguity (a replay timeout keeps the record; the re-replay under the
same identity dedups at a ledger-bearing global).

Disk format (one segment file = `spool-<seq>.seg`, records appended):

    u32 payload_len | u32 crc32(payload) | payload
    payload: u16 version | u64 ts_ms | u64 epoch | u32 chunk_idx
             | u32 n_metrics | u64 trace_id | u64 span_id
             | u16 src_len | src | body (serialized MetricList)

CRC + length framing make torn writes detectable: a reopen scan skips
a truncated final record (counted, then the file is truncated back to
the last good boundary so later appends cannot interleave garbage) and
rejects CRC-damaged records individually.  Bodies are NOT held in
memory — replay reads them back from disk, so the spool's RAM cost is
one small index entry per pending record regardless of spool_max_bytes.

Bounds are visible-loss, never silent: a record older than
`max_age_s` or evicted to keep the spool under `max_bytes` lands in
the `expired` counters (records AND metric points), and every
counter surfaces at /debug/vars -> spool and as forward.spool.*
self-metrics.  Disk errors (the `spool.io` failpoint's edge) degrade
to drop-with-accounting instead of wedging the forward thread.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from veneur_tpu import failpoints

logger = logging.getLogger("veneur_tpu.forward.spool")

SEGMENT_PREFIX = "spool-"
SEGMENT_SUFFIX = ".seg"
_FRAME = struct.Struct("<II")                  # payload_len, crc32
_HEADER = struct.Struct("<HQQIIQQH")           # version..src_len
_VERSION = 1

# fsync policies: every append / on segment rotation+close / never
FSYNC_POLICIES = ("always", "rotate", "never")

# bound on waiting out the replayer thread at close (it sleeps in
# replay_interval_s ticks, so one tick plus slack always suffices)
REPLAYER_JOIN_TIMEOUT_S = 2.0


def open_segment(path: str):
    """Open (create) a spool segment for appending — paired with
    close_segment on every path (vnlint resource-pairing)."""
    return open(path, "ab")


def close_segment(f, fsync: bool = False) -> None:
    """Flush (optionally fsync) and close a spool segment handle."""
    try:
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    finally:
        f.close()


@dataclass
class SpoolRecord:
    """One spooled chunk's index entry; the body stays on disk."""
    ident: tuple            # (source, epoch, chunk_idx)
    ts_ms: int
    n_metrics: int
    trace_id: int
    span_id: int
    seg_seq: int
    offset: int             # body offset within the segment file
    body_len: int
    disk_bytes: int         # full framed record size


def encode_record(ident: tuple, body: bytes, n_metrics: int,
                  trace_id: int = 0, span_id: int = 0,
                  ts_ms: Optional[int] = None) -> bytes:
    source, epoch, chunk_idx = ident
    src = source.encode()
    ts = int(ts_ms if ts_ms is not None else time.time() * 1e3)
    payload = _HEADER.pack(_VERSION, ts, int(epoch), int(chunk_idx),
                           int(n_metrics), int(trace_id), int(span_id),
                           len(src)) + src + body
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


class ForwardSpool:
    def __init__(self, directory: str, max_bytes: int = 64 << 20,
                 max_age_s: float = 600.0,
                 fsync: str = "rotate",
                 segment_max_bytes: int = 4 << 20,
                 replay_interval_s: float = 0.5):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"unknown spool fsync policy {fsync!r} "
                             f"(want one of {FSYNC_POLICIES})")
        self.dir = directory
        self.max_bytes = int(max_bytes)
        self.max_age_s = float(max_age_s)
        self.fsync = fsync
        self.segment_max_bytes = int(segment_max_bytes)
        self.replay_interval_s = float(replay_interval_s)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._records: deque[SpoolRecord] = deque()
        # seg_seq -> records still pending in that segment (a segment
        # file is deleted only once every record it holds is settled)
        self._seg_pending: dict[int, int] = {}
        self._active = None          # (seq, file handle, bytes written)
        self._next_seq = 0
        self.pending_bytes = 0
        self.pending_points = 0      # metric points in pending records
        # ledger counters: spilled + recovered == replayed + expired +
        # dropped + pending at all times — the accounting closure the
        # crash chaos arms assert.  `recovered` counts records a reopen
        # re-indexed from disk: they were spilled by a PREVIOUS
        # process, so this instance's spilled counters never saw them.
        self.spilled_records = 0
        self.spilled_points = 0
        self.recovered_records = 0
        self.recovered_points = 0
        self.replayed_records = 0
        self.replayed_points = 0
        self.expired_records = 0
        self.expired_points = 0
        self.dropped_records = 0
        self.dropped_points = 0
        self.torn_records = 0
        self.crc_rejected = 0
        self.io_errors = 0
        self.replay_attempts = 0
        self._replayer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._recover()

    # -- recovery (reopen after a crash) -----------------------------------

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{SEGMENT_PREFIX}{seq}{SEGMENT_SUFFIX}")

    def _recover(self) -> None:
        """Rebuild the pending index from on-disk segments: every valid
        record re-enters the replay queue (its recorded identity makes
        re-delivery of an already-imported chunk idempotent at the
        global), a truncated final record is skipped with a counter and
        truncated away, CRC-damaged records are rejected individually."""
        seqs = []
        for name in os.listdir(self.dir):
            if name.startswith(SEGMENT_PREFIX) and \
                    name.endswith(SEGMENT_SUFFIX):
                try:
                    seqs.append(int(
                        name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]))
                except ValueError:
                    continue
        for seq in sorted(seqs):
            path = self._segment_path(seq)
            try:
                good_end = self._scan_segment(seq, path)
            except OSError as e:
                self.io_errors += 1
                logger.error("spool: cannot recover segment %s: %s",
                             path, e)
                continue
            if good_end is not None:
                # torn tail: drop the partial record so appends to a
                # recovered active segment cannot interleave with it
                try:
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                except OSError:
                    self.io_errors += 1
            if self._seg_pending.get(seq, 0) == 0:
                self._unlink_segment(seq)
        self._next_seq = max(seqs, default=-1) + 1

    def _scan_segment(self, seq: int, path: str) -> Optional[int]:
        """Index one segment's records; returns the truncation offset
        when a torn tail was found, else None."""
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            if off + _FRAME.size > len(data):
                self.torn_records += 1
                return off
            plen, crc = _FRAME.unpack_from(data, off)
            start = off + _FRAME.size
            if start + plen > len(data):
                self.torn_records += 1
                return off
            payload = data[start:start + plen]
            next_off = start + plen
            if zlib.crc32(payload) != crc:
                self.crc_rejected += 1
                off = next_off
                continue
            try:
                (ver, ts_ms, epoch, chunk_idx, n_metrics, tid, sid,
                 src_len) = _HEADER.unpack_from(payload, 0)
                src = payload[_HEADER.size:_HEADER.size + src_len]
                body_off = _HEADER.size + src_len
                rec = SpoolRecord(
                    ident=(src.decode(), epoch, chunk_idx),
                    ts_ms=ts_ms, n_metrics=n_metrics,
                    trace_id=tid, span_id=sid, seg_seq=seq,
                    offset=start + body_off,
                    body_len=plen - body_off,
                    disk_bytes=_FRAME.size + plen)
            except (struct.error, UnicodeDecodeError):
                self.crc_rejected += 1
                off = next_off
                continue
            if ver != _VERSION:
                self.crc_rejected += 1
                off = next_off
                continue
            self._records.append(rec)
            self._seg_pending[seq] = self._seg_pending.get(seq, 0) + 1
            self.pending_bytes += rec.disk_bytes
            self.pending_points += rec.n_metrics
            self.recovered_records += 1
            self.recovered_points += rec.n_metrics
            off = next_off
        return None

    # -- append (the forward client's spill path) --------------------------

    def append(self, ident: tuple, body: bytes, n_metrics: int,
               trace_id: int = 0, span_id: int = 0) -> bool:
        """Spill one undelivered chunk.  Returns False (after counting
        the loss in dropped_*) when disk I/O fails — the caller's
        contract is drop-with-accounting, never a wedged forward
        thread."""
        ts_ms = int(time.time() * 1e3)
        frame = encode_record(ident, body, n_metrics, trace_id, span_id,
                              ts_ms)
        with self._lock:
            try:
                # vnlint: disable=blocking-propagation (deliberate
                #   failpoint edge: spool.io exists to fault the spill
                #   I/O itself; disarmed cost is one bool read, and
                #   only the spilling forward thread holds this lock)
                failpoints.inject("spool.io")
                seq, f = self._active_segment_locked(len(frame))
                off = f.tell()
                f.write(frame)
                f.flush()
                if self.fsync == "always":
                    os.fsync(f.fileno())
            except Exception as e:
                # the CALLER accounts the drop (forward.dropped) — the
                # spool only records the I/O failure, so the loss is
                # counted exactly once
                self.io_errors += 1
                logger.error("spool: append failed, caller drops %d "
                             "metrics with accounting: %s", n_metrics, e)
                return False
            body_off = (off + _FRAME.size + _HEADER.size
                        + len(ident[0].encode()))
            rec = SpoolRecord(ident=ident, ts_ms=ts_ms,
                              n_metrics=n_metrics, trace_id=trace_id,
                              span_id=span_id, seg_seq=seq,
                              offset=body_off, body_len=len(body),
                              disk_bytes=len(frame))
            self._records.append(rec)
            self._seg_pending[seq] = self._seg_pending.get(seq, 0) + 1
            self.pending_bytes += rec.disk_bytes
            self.pending_points += n_metrics
            self.spilled_records += 1
            self.spilled_points += n_metrics
            self._enforce_bytes_locked()
        self._wake.set()
        return True

    def _close_active_locked(self, fsync: bool = False) -> None:
        if self._active is None:
            return
        _, f, _ = self._active
        self._active = None
        try:
            close_segment(f, fsync=fsync)
        except OSError:
            self.io_errors += 1

    def _active_segment_locked(self, need: int):
        if self._active is not None:
            seq, f, written = self._active
            if written + need <= self.segment_max_bytes:
                self._active = (seq, f, written + need)
                return seq, f
            self._close_active_locked(fsync=self.fsync != "never")
        seq = self._next_seq
        self._next_seq += 1
        f = open_segment(self._segment_path(seq))
        self._active = (seq, f, need)
        self._seg_pending.setdefault(seq, 0)
        return seq, f

    def _enforce_bytes_locked(self) -> None:
        """Evict oldest records while over the byte budget — bounded
        spool, visibly-accounted loss."""
        while self.pending_bytes > self.max_bytes and self._records:
            self._settle_locked(self._records.popleft(), "expired")

    def _settle_locked(self, rec: SpoolRecord, outcome: str) -> None:
        self.pending_bytes -= rec.disk_bytes
        self.pending_points -= rec.n_metrics
        if outcome == "replayed":
            self.replayed_records += 1
            self.replayed_points += rec.n_metrics
        elif outcome == "expired":
            self.expired_records += 1
            self.expired_points += rec.n_metrics
        else:
            self.dropped_records += 1
            self.dropped_points += rec.n_metrics
        left = self._seg_pending.get(rec.seg_seq, 0) - 1
        if left > 0:
            self._seg_pending[rec.seg_seq] = left
            return
        self._seg_pending.pop(rec.seg_seq, None)
        if self._active is not None and self._active[0] == rec.seg_seq:
            # fully-settled ACTIVE segment: rotate it out now, or a
            # restart would re-index (and re-replay) its records —
            # harmless under the dedup ledger, but pending accounting
            # must mean pending
            self._close_active_locked()
        self._unlink_segment(rec.seg_seq)

    def _unlink_segment(self, seq: int) -> None:
        try:
            os.unlink(self._segment_path(seq))
        except OSError:
            pass
        self._seg_pending.pop(seq, None)

    # -- replay ------------------------------------------------------------

    def read_body(self, rec: SpoolRecord) -> bytes:
        """Read one record's chunk bytes back from disk (the replay
        path; `spool.io` injects here too)."""
        failpoints.inject("spool.io")
        # the record may live in the still-open active segment: flushed
        # on append, so a plain read-only open sees it
        with open(self._segment_path(rec.seg_seq), "rb") as f:
            f.seek(rec.offset)
            body = f.read(rec.body_len)
        if len(body) != rec.body_len:
            raise OSError(f"short read ({len(body)}/{rec.body_len}) "
                          f"from spool segment {rec.seg_seq}")
        return body

    def peek(self, n: int = 1) -> list[SpoolRecord]:
        """Oldest n pending records (the crash arms capture one to
        prove duplicate delivery merges once)."""
        with self._lock:
            return list(self._records)[:n]

    def pending_records(self) -> int:
        with self._lock:
            return len(self._records)

    def expire_now(self) -> int:
        """Expire every record older than max_age_s; returns records
        expired.  Runs on each replay tick and is callable directly."""
        cutoff_ms = (time.time() - self.max_age_s) * 1e3
        n = 0
        with self._lock:
            while self._records and self._records[0].ts_ms < cutoff_ms:
                self._settle_locked(self._records.popleft(), "expired")
                n += 1
        return n

    def start_replayer(self, send_fn: Callable[[SpoolRecord, bytes],
                                               None]) -> None:
        """Background oldest-first drain.  `send_fn(rec, body)` raises
        RetryableReplayError to keep the record for the next tick (the
        destination is still down); any other exception drops the
        record with accounting (an UNIMPLEMENTED peer, a poisoned
        chunk)."""
        if self._replayer is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                self._wake.wait(self.replay_interval_s)
                self._wake.clear()
                if self._stop.is_set():
                    return
                try:
                    self.replay_once(send_fn)
                except Exception:
                    logger.exception("spool replay tick failed")

        self._replayer = threading.Thread(target=loop, daemon=True,
                                          name="spool-replay")
        self._replayer.start()

    def replay_once(self, send_fn) -> int:
        """One drain pass: expire, then deliver oldest-first until the
        spool is empty or the destination fails retry-safely.  Returns
        records delivered."""
        self.expire_now()
        delivered = 0
        while not self._stop.is_set():
            with self._lock:
                if not self._records:
                    return delivered
                rec = self._records[0]
            self.replay_attempts += 1
            try:
                body = self.read_body(rec)
            except Exception as e:
                # unreadable record (disk fault, injected spool.io):
                # drop with accounting rather than wedge the queue head
                self.io_errors += 1
                logger.error("spool: replay read failed for %s: %s",
                             rec.ident, e)
                with self._lock:
                    if self._records and self._records[0] is rec:
                        self._settle_locked(self._records.popleft(),
                                            "dropped")
                continue
            try:
                send_fn(rec, body)
            except RetryableReplayError:
                return delivered      # destination still down; next tick
            except Exception as e:
                logger.error("spool: replay of %s failed terminally, "
                             "dropping with accounting: %s", rec.ident, e)
                with self._lock:
                    if self._records and self._records[0] is rec:
                        self._settle_locked(self._records.popleft(),
                                            "dropped")
                continue
            delivered += 1
            with self._lock:
                if self._records and self._records[0] is rec:
                    self._settle_locked(self._records.popleft(),
                                        "replayed")
        return delivered

    def close(self, drain: bool = False) -> None:
        """Stop the replayer and close the active segment.  `drain`
        fsyncs the tail out (graceful shutdown); a simulated crash
        passes False and relies on the per-append flush."""
        self._stop.set()
        self._wake.set()
        t = self._replayer
        if t is not None:
            t.join(timeout=REPLAYER_JOIN_TIMEOUT_S)
            self._replayer = None
        with self._lock:
            self._close_active_locked(
                fsync=drain and self.fsync != "never")

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "pending_records": len(self._records),
                "pending_bytes": self.pending_bytes,
                "pending_points": self.pending_points,
                "spilled": self.spilled_records,
                "spilled_points": self.spilled_points,
                "recovered": self.recovered_records,
                "recovered_points": self.recovered_points,
                "replayed": self.replayed_records,
                "replayed_points": self.replayed_points,
                "expired": self.expired_records,
                "expired_points": self.expired_points,
                "dropped": self.dropped_records,
                "dropped_points": self.dropped_points,
                "torn_records": self.torn_records,
                "crc_rejected": self.crc_rejected,
                "io_errors": self.io_errors,
                "replay_attempts": self.replay_attempts,
            }


class RetryableReplayError(Exception):
    """The replay destination is still down (retry-safe failure): keep
    the record at the queue head for the next tick."""
