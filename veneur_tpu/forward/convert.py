"""ForwardMetric <-> metricpb.Metric conversion.

The neutral in-memory ForwardMetric (veneur_tpu/samplers/samplers.py) maps
onto the reference's wire schema (samplers/metricpb/metric.proto): digests
as MergingDigestData centroid lists (`Histo.Metric()`,
samplers/samplers.go:524-535), sets as encoded HLL bytes
(`Set.Metric()`, samplers.go:279-295), counters/gauges as raw values.
"""

from __future__ import annotations

from veneur_tpu.protocol import metric_pb2, tdigest_pb2
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.samplers.metric_key import MetricScope

_KIND_TO_PB = {
    sm.TYPE_COUNTER: metric_pb2.Counter,
    sm.TYPE_GAUGE: metric_pb2.Gauge,
    sm.TYPE_HISTOGRAM: metric_pb2.Histogram,
    sm.TYPE_SET: metric_pb2.Set,
    sm.TYPE_TIMER: metric_pb2.Timer,
}
_PB_TO_KIND = {v: k for k, v in _KIND_TO_PB.items()}

_SCOPE_TO_PB = {
    MetricScope.MIXED: metric_pb2.Mixed,
    MetricScope.LOCAL_ONLY: metric_pb2.Local,
    MetricScope.GLOBAL_ONLY: metric_pb2.Global,
}
_PB_TO_SCOPE = {v: k for k, v in _SCOPE_TO_PB.items()}


def to_pb(fm: sm.ForwardMetric) -> metric_pb2.Metric:
    m = metric_pb2.Metric(
        name=fm.name, tags=list(fm.tags),
        type=_KIND_TO_PB[fm.kind],
        scope=_SCOPE_TO_PB[MetricScope(fm.scope)])
    if fm.kind == sm.TYPE_COUNTER:
        m.counter.value = int(fm.counter_value)
    elif fm.kind == sm.TYPE_GAUGE:
        m.gauge.value = float(fm.gauge_value)
    elif fm.kind == sm.TYPE_SET:
        m.set.hyper_log_log = fm.hll
    elif fm.compactor is not None:  # histogram/timer, compactor family
        # the ladder vector rides the histogram oneof with compression
        # <= -1024 as the family marker (-1024 - cap; the moments
        # marker is -k, far above): centroid means are wire doubles,
        # so the f64 vector — self-describing header (cap/levels/seed/
        # counters) + level items — transports exactly.  min/max/
        # reciprocalSum mirror the header scalars for wire debuggers.
        from veneur_tpu.sketches import compactor as comp
        vec = [float(x) for x in fm.compactor]
        cap, _, _ = comp.params_from_vector(vec)
        td = tdigest_pb2.MergingDigestData(
            compression=-1024.0 - float(cap),
            min=vec[comp.IDX_MIN], max=vec[comp.IDX_MAX],
            reciprocalSum=vec[comp.IDX_RSUM])
        for x in vec:
            td.main_centroids.add(mean=x, weight=1.0)
        m.histogram.t_digest.CopyFrom(td)
    elif fm.moments is not None:  # histogram / timer, moments family
        # the moments vector rides the histogram oneof with a NEGATIVE
        # compression as the family marker (-k, the power-sum order):
        # centroid means are wire doubles, so the f64 vector transports
        # exactly and the payload stays self-describing — an importer
        # never needs this tier's dispatch rules to route it.  min/max/
        # reciprocalSum mirror the vector's scalars for wire debuggers.
        from veneur_tpu.sketches import moments as mo
        vec = [float(x) for x in fm.moments]
        k = mo.k_from_len(len(vec))
        td = tdigest_pb2.MergingDigestData(
            compression=-float(k),
            min=vec[mo.IDX_MIN], max=vec[mo.IDX_MAX],
            reciprocalSum=vec[mo.IDX_RSUM])
        for x in vec:
            td.main_centroids.add(mean=x, weight=1.0)
        m.histogram.t_digest.CopyFrom(td)
    else:  # histogram / timer, t-digest family
        td = tdigest_pb2.MergingDigestData(
            compression=fm.digest_compression,
            min=fm.digest_min, max=fm.digest_max,
            reciprocalSum=fm.digest_rsum)
        for mean, weight in zip(fm.digest_means or [],
                                fm.digest_weights or []):
            td.main_centroids.add(mean=float(mean), weight=float(weight))
        m.histogram.t_digest.CopyFrom(td)
    return m


def from_pb(m: metric_pb2.Metric) -> sm.ForwardMetric:
    kind = _PB_TO_KIND[m.type]
    fm = sm.ForwardMetric(
        name=m.name, tags=list(m.tags), kind=kind,
        scope=int(_PB_TO_SCOPE[m.scope]))
    which = m.WhichOneof("value")
    if which == "counter":
        fm.counter_value = m.counter.value
    elif which == "gauge":
        fm.gauge_value = m.gauge.value
    elif which == "set":
        fm.hll = m.set.hyper_log_log
    elif which == "histogram":
        td = m.histogram.t_digest
        if td.compression <= -1024:
            # compactor-family marker (see to_pb): means ARE the vector
            fm.compactor = [c.mean for c in td.main_centroids]
        elif td.compression < 0:
            # moments-family marker (see to_pb): means ARE the vector
            fm.moments = [c.mean for c in td.main_centroids]
        else:
            fm.digest_means = [c.mean for c in td.main_centroids]
            fm.digest_weights = [c.weight for c in td.main_centroids]
            fm.digest_compression = td.compression or 100.0
            fm.digest_min = td.min
            fm.digest_max = td.max
            fm.digest_rsum = td.reciprocalSum
    elif which is None:
        raise ValueError("can't import a metric with a nil value")
    return fm
