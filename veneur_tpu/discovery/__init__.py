"""Service discovery for the global tier's membership.

Mirrors `discovery/`: the Discoverer contract
(`discovery/discoverer.go:4-7`) with Consul healthy-instance queries
(`discovery/consul/consul.go:30-47`), Kubernetes pod-label queries
(`discovery/kubernetes/kubernetes.go:93-108`), plus a static list for
fixed fleets and tests.  Implementations use plain HTTP (urllib) and are
exercised against local fake endpoints in tests; real clusters are
reachable with the same code paths.
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Protocol


class Discoverer(Protocol):
    def get_destinations_for_service(self, service: str) -> list[str]: ...


class StaticDiscoverer:
    """A fixed destination list (config-driven fleets, tests)."""

    def __init__(self, destinations: list[str]):
        self.destinations = list(destinations)

    def get_destinations_for_service(self, service: str) -> list[str]:
        return list(self.destinations)


class ConsulDiscoverer:
    """Healthy instances of a service from Consul's health API
    (consul.go:30-47: GET /v1/health/service/{service}?passing)."""

    def __init__(self, base_url: str = "http://127.0.0.1:8500",
                 timeout_s: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def get_destinations_for_service(self, service: str) -> list[str]:
        url = f"{self.base_url}/v1/health/service/{service}?passing"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            entries = json.loads(resp.read())
        out = []
        for entry in entries:
            svc = entry.get("Service", {})
            node = entry.get("Node", {})
            host = svc.get("Address") or node.get("Address")
            port = svc.get("Port")
            if host and port:
                out.append(f"{host}:{port}")
        return out


class KubernetesDiscoverer:
    """Pods labeled app={service} with a port named grpc (falling back to
    http), via the API server (kubernetes.go:93-108).  In-cluster auth
    uses the mounted service-account token."""

    TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

    def __init__(self, api_url: str = "", namespace: str = "default",
                 timeout_s: float = 5.0, insecure_skip_verify: bool = False):
        if not api_url:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "127.0.0.1")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            api_url = f"https://{host}:{port}"
        self.api_url = api_url.rstrip("/")
        self.namespace = namespace
        self.timeout_s = timeout_s
        self.insecure_skip_verify = insecure_skip_verify

    def _request(self, url: str):
        import ssl
        req = urllib.request.Request(url)
        if os.path.exists(self.TOKEN_PATH):
            with open(self.TOKEN_PATH) as f:
                req.add_header("Authorization", f"Bearer {f.read().strip()}")
        ctx = None
        if url.startswith("https"):
            if os.path.exists(self.CA_PATH):
                ctx = ssl.create_default_context(cafile=self.CA_PATH)
            elif self.insecure_skip_verify:
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            else:
                # never silently skip verification: a MITM could capture
                # the bearer token and forge the destination list
                ctx = ssl.create_default_context()
        return urllib.request.urlopen(req, timeout=self.timeout_s,
                                      context=ctx)

    def get_destinations_for_service(self, service: str) -> list[str]:
        url = (f"{self.api_url}/api/v1/namespaces/{self.namespace}/pods"
               f"?labelSelector=app%3D{service}")
        with self._request(url) as resp:
            pods = json.loads(resp.read())
        out = []
        for pod in pods.get("items", []):
            status = pod.get("status", {})
            if status.get("phase") != "Running":
                continue
            ip = status.get("podIP")
            if not ip:
                continue
            port = None
            fallback = None
            for c in pod.get("spec", {}).get("containers", []):
                for p in c.get("ports", []):
                    if p.get("name") == "grpc":
                        port = p.get("containerPort")
                    elif p.get("name") == "http":
                        fallback = p.get("containerPort")
            port = port or fallback
            if port:
                out.append(f"{ip}:{port}")
        return out
