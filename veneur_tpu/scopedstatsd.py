"""Scoped statsd self-metrics client.

Capability twin of `scopedstatsd/client.go:13-58`: a DogStatsD client
wrapper that appends the magic scope tags (`veneurlocalonly` /
`veneurglobalonly`) per metric-type scope so the server's own telemetry
aggregates at the right tier, plus a nil-safe `ensure` (a no-op client
when none is configured).
"""

from __future__ import annotations

import socket
from typing import Optional

from veneur_tpu.samplers import parser as parser_mod

GLOBAL_ONLY = "global"
LOCAL_ONLY = "local"
DEFAULT_SCOPE = ""


class MetricScopes:
    """Per-metric-type scope overrides (veneur_metrics_scopes config)."""

    def __init__(self, counter: str = DEFAULT_SCOPE,
                 gauge: str = DEFAULT_SCOPE, histogram: str = DEFAULT_SCOPE,
                 set_: str = DEFAULT_SCOPE, timing: str = DEFAULT_SCOPE):
        self.counter = counter
        self.gauge = gauge
        self.histogram = histogram
        self.set = set_
        self.timing = timing


def scope_tag(scope: str) -> Optional[str]:
    if scope == GLOBAL_ONLY:
        return parser_mod.GLOBAL_ONLY_TAG
    if scope == LOCAL_ONLY:
        return parser_mod.LOCAL_ONLY_TAG
    return None


class ScopedClient:
    """UDP DogStatsD emitter with scope tags and implicit tags."""

    def __init__(self, address: str = "127.0.0.1:8125",
                 scopes: Optional[MetricScopes] = None,
                 tags: Optional[list[str]] = None,
                 namespace: str = "veneur."):
        from veneur_tpu.util import netaddr
        self._dest = netaddr.split_hostport(address, default_port=8125)
        self._sock = socket.socket(netaddr.family(self._dest[0]),
                                   socket.SOCK_DGRAM)
        self.scopes = scopes or MetricScopes()
        self.tags = list(tags or [])
        # the reference namespaces ALL self-metrics
        # (statsd.WithNamespace("veneur."), cmd/veneur/main.go:92) —
        # dashboards built against a reference fleet key on the prefix
        self.namespace = namespace

    def _emit(self, name: str, value, mtype: str, tags: Optional[list[str]],
              scope: str, rate: float = 1.0) -> None:
        all_tags = self.tags + list(tags or [])
        st = scope_tag(scope)
        if st:
            all_tags.append(st)
        line = f"{self.namespace}{name}:{value}|{mtype}"
        if rate != 1.0:
            line += f"|@{rate}"
        if all_tags:
            line += "|#" + ",".join(all_tags)
        try:
            self._sock.sendto(line.encode(), self._dest)
        except OSError:
            pass

    def count(self, name: str, value: int,
              tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, value, "c", tags, self.scopes.counter, rate)

    def incr(self, name: str, tags: Optional[list[str]] = None,
             rate: float = 1.0) -> None:
        self.count(name, 1, tags, rate)

    def gauge(self, name: str, value: float,
              tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, value, "g", tags, self.scopes.gauge, rate)

    def histogram(self, name: str, value: float,
                  tags: Optional[list[str]] = None,
                  rate: float = 1.0) -> None:
        self._emit(name, value, "h", tags, self.scopes.histogram, rate)

    def timing(self, name: str, ms: float,
               tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, ms, "ms", tags, self.scopes.timing, rate)

    def set(self, name: str, member: str,
            tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, member, "s", tags, self.scopes.set, rate)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class NoopClient:
    """The nil-safe fallback (scopedstatsd.Ensure, client.go:24-30)."""

    def count(self, *a, **kw): ...
    def incr(self, *a, **kw): ...
    def gauge(self, *a, **kw): ...
    def histogram(self, *a, **kw): ...
    def timing(self, *a, **kw): ...
    def set(self, *a, **kw): ...
    def close(self): ...


def ensure(client) -> object:
    """Return a usable client: the given one, or a no-op."""
    return client if client is not None else NoopClient()
