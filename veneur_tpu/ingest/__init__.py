"""Native ingest data plane: ctypes binding + arena drain adapter.

The hot edge path (UDP read -> DogStatsD parse -> staging) runs in C++
(`native/ingest_engine.cpp`), replacing the per-packet pure-Python chain the
reference implements with SO_REUSEPORT reader goroutines + a zero-alloc
parser (`networking.go:54-107`, `samplers/parser.go:349-503`,
`worker.go:34-50`).  The engine interns each metric identity to a dense u32
id and stages columnar batches; `NativeIngest.drain_into()` applies a drain
to the arenas with a handful of vectorized numpy calls under one brief lock
acquisition — per-metric Python and per-metric locking are gone from the
packet path (the round-1 verdict's #2 item).

Events and service checks punt to the Python slow path for exact reference
semantics; malformed metric lines are counted and dropped, matching the
reference's log-and-drop (`server.go:956-993` logs the parse error and moves
on — nothing malformed ever reaches aggregation on either path).
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from veneur_tpu.samplers.metric_key import (MetricKey, MetricScope,
                                            metric_digest)

logger = logging.getLogger("veneur.ingest")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "ingest_engine.cpp")
_SO = os.path.join(_REPO_ROOT, "native", ".build", "libvningest.so")

_TYPE_NAMES = ("counter", "gauge", "histogram", "timer", "set")

# vn_engine_opt enum mirrors (ingest_engine.cpp VnSimd / VnBackend)
SIMD_MODES = {"auto": 0, "scalar": 1, "sse2": 2, "avx2": 3}
SIMD_NAMES = {v: k for k, v in SIMD_MODES.items()}
BACKEND_MODES = {"auto": 0, "recvmmsg": 1, "io_uring": 2}
BACKEND_NAMES = {0: "none", 1: "recvmmsg", 2: "io_uring"}

# Data-plane stage names in pipeline order; the first four are
# per-reader-thread, drain is engine-level (the Python drainer thread).
# veneur_tpu.profiling owns the canonical tuple + unit map (tests pin
# them); re-exported here for callers working at the engine level.
from veneur_tpu.profiling import STAGE_UNITS  # noqa: E402
from veneur_tpu.profiling import STAGES as STAGE_NAMES  # noqa: E402

_build_lock = threading.Lock()
_lib = None


def _compile() -> None:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    tmp = _SO + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-Wall", "-Wextra"]
    if os.environ.get("VENEUR_TPU_TEST"):
        # the test build path promotes warnings to errors so a warning
        # introduced by a change fails the suite, not just stderr
        cmd.append("-Werror")
    cmd += ["-o", tmp, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _SO)


def load_library():
    """Build (if stale) and load the native engine; raises on failure."""
    global _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _compile()
        lib = ctypes.CDLL(_SO)
        lib.vn_engine_new.restype = ctypes.c_void_p
        lib.vn_engine_new.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.vn_engine_free.argtypes = [ctypes.c_void_p]
        lib.vn_thread_new.restype = ctypes.c_int
        lib.vn_thread_new.argtypes = [ctypes.c_void_p]
        lib.vn_ingest.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_char_p, ctypes.c_long]
        lib.vn_add_udp_reader.restype = ctypes.c_int
        lib.vn_add_udp_reader.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.vn_add_udp_reader_pinned.restype = ctypes.c_int
        lib.vn_add_udp_reader_pinned.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.vn_engine_opt.restype = ctypes.c_int
        lib.vn_engine_opt.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
        lib.vn_reader_backend.restype = ctypes.c_int
        lib.vn_reader_backend.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.vn_simd_mode.restype = ctypes.c_int
        lib.vn_simd_mode.argtypes = [ctypes.c_void_p]
        lib.vn_simd_supported.restype = ctypes.c_int
        lib.vn_simd_supported.argtypes = [ctypes.c_int]
        lib.vn_key_hash.restype = ctypes.c_ulonglong
        lib.vn_key_hash.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_int]
        lib.vn_scan_tokens.restype = ctypes.c_longlong
        lib.vn_scan_tokens.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_longlong]
        lib.vn_stop.argtypes = [ctypes.c_void_p]
        lib.vn_drain.restype = ctypes.c_void_p
        lib.vn_drain.argtypes = [ctypes.c_void_p]
        lib.vn_drain_clear.restype = ctypes.c_void_p
        lib.vn_drain_clear.argtypes = [ctypes.c_void_p]
        lib.vn_drain_section.restype = ctypes.c_longlong
        lib.vn_drain_section.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p)]
        lib.vn_drain_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong)]
        lib.vn_drain_free.argtypes = [ctypes.c_void_p]
        lib.vn_totals.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong)]
        lib.vn_intern_count.restype = ctypes.c_ulonglong
        lib.vn_intern_count.argtypes = [ctypes.c_void_p]
        lib.vn_stage_thread_count.restype = ctypes.c_longlong
        lib.vn_stage_thread_count.argtypes = [ctypes.c_void_p]
        lib.vn_stage_stats.restype = ctypes.c_longlong
        lib.vn_stage_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.c_longlong]
        lib.vn_stage_drain.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong)]
        lib.vn_metro64.restype = ctypes.c_ulonglong
        lib.vn_metro64.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.vn_blast_udp.restype = ctypes.c_longlong
        lib.vn_blast_udp.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_longlong,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
        lib.vn_fill_dense.restype = ctypes.c_longlong
        lib.vn_fill_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_longlong, ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int]
        lib.vn_route.restype = ctypes.c_void_p
        lib.vn_route.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_int]
        lib.vn_route_dest.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.vn_route_chunks.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.vn_route_free.argtypes = [ctypes.c_void_p]
        lib.vn_import_scan.restype = ctypes.c_void_p
        lib.vn_import_scan.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
        lib.vn_import_scan_n.restype = ctypes.c_longlong
        lib.vn_import_scan_n.argtypes = [ctypes.c_void_p]
        lib.vn_import_scan_arrays.argtypes = [
            ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_void_p)] * 8
        lib.vn_import_scan_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def import_scan(payload: bytes):
    """Columnar scan of a serialized MetricList (vn_import_scan):
    returns dict of numpy arrays {h_lo, h_hi (u64 identity hashes),
    which (u8: 1 counter, 2 gauge, 3 set, 4 histogram), mtype, scope
    (u8), value (f64), rec_off, rec_len (i64 Metric submessage
    ranges)} — copies, safe after free — or None if the payload failed
    the wire scan (caller falls back to protobuf parsing)."""
    import numpy as np

    lib = load_library()
    handle = lib.vn_import_scan(payload, len(payload))
    if not handle:
        return None
    try:
        n = lib.vn_import_scan_n(handle)
        ptrs = [ctypes.c_void_p() for _ in range(8)]
        lib.vn_import_scan_arrays(handle, *map(ctypes.byref, ptrs))
        if n == 0:
            return {"n": 0}

        def arr(ptr, dtype, count=n):
            size = np.dtype(dtype).itemsize * count
            return np.frombuffer(
                ctypes.string_at(ptr.value, size), dtype).copy()

        return {
            "n": int(n),
            "h_lo": arr(ptrs[0], np.uint64),
            "h_hi": arr(ptrs[1], np.uint64),
            "which": arr(ptrs[2], np.uint8),
            "mtype": arr(ptrs[3], np.uint8),
            "scope": arr(ptrs[4], np.uint8),
            "value": arr(ptrs[5], np.float64),
            "rec_off": arr(ptrs[6], np.int64),
            "rec_len": arr(ptrs[7], np.int64),
        }
    finally:
        lib.vn_import_scan_free(handle)


def route_metric_list(payload: bytes, ring_hashes, ring_dests,
                      n_dests: int, chunk_max: int = 2000):
    """Parse-free consistent-hash routing of a serialized MetricList
    (vn_route): returns a list with one entry per destination index,
    each a tuple (chunks, chunk_counts, count) where chunks is a list
    of bytes — each a VALID MetricList body of <= chunk_max metrics,
    with chunk_counts its parallel per-chunk metric counts — or None if
    the native router rejected the payload (caller falls back to the
    protobuf path).  ring_hashes: uint32 sorted ndarray; ring_dests:
    int32 ndarray of destination indices."""
    lib = load_library()
    handle = lib.vn_route(
        payload, len(payload),
        ring_hashes.ctypes.data_as(ctypes.c_void_p),
        ring_dests.ctypes.data_as(ctypes.c_void_p),
        len(ring_hashes), n_dests, chunk_max)
    if not handle:
        return None
    try:
        out = []
        for d in range(n_dests):
            ptr = ctypes.c_void_p()
            nbytes = ctypes.c_longlong()
            count = ctypes.c_longlong()
            lib.vn_route_dest(handle, d, ctypes.byref(ptr),
                              ctypes.byref(nbytes), ctypes.byref(count))
            offs_ptr = ctypes.c_void_p()
            n_bounds = ctypes.c_longlong()
            lib.vn_route_chunks(handle, d, ctypes.byref(offs_ptr),
                                ctypes.byref(n_bounds))
            chunks = []
            chunk_counts = []
            if count.value:
                region = ctypes.string_at(ptr.value, nbytes.value)
                offs = ctypes.cast(
                    offs_ptr,
                    ctypes.POINTER(ctypes.c_longlong * n_bounds.value)
                ).contents
                remaining = count.value
                for i in range(n_bounds.value - 1):
                    chunks.append(region[offs[i]:offs[i + 1]])
                    n = min(chunk_max, remaining)
                    chunk_counts.append(n)
                    remaining -= n
            out.append((chunks, chunk_counts, count.value))
        return out
    finally:
        lib.vn_route_free(handle)


def fill_dense(rows, vals, wts, dense_id, dv, dw, depths,
               n_threads: int = 4) -> int:
    """Native COO->dense fill (see vn_fill_dense in ingest_engine.cpp).
    Arrays must be C-contiguous with dtypes int64/float64/float64/
    int64/float32/float32/int16.  Row ids outside [0, len(dense_id))
    are corrupt and count as dropped — both here (cheap vectorized
    pre-check, so a poisoned batch never reaches native code) and in
    the C++ fill itself (defense in depth: NumPy-style negative indices
    would otherwise wrap into an out-of-bounds read).  Returns
    dropped-element count (caller falls back to the numpy builder when
    nonzero)."""
    import numpy as np

    lib = load_library()

    def ptr(a):
        return a.ctypes.data_as(ctypes.c_void_p) if a is not None else None

    assert rows.dtype == np.int64 and vals.dtype == np.float64
    assert dv.dtype == np.float32 and dense_id.dtype == np.int64
    capacity = len(dense_id)
    if len(rows) and (int(rows.min()) < 0
                      or int(rows.max()) >= capacity):
        return int(((rows < 0) | (rows >= capacity)).sum())
    u_pad, d_pad = dv.shape
    return int(lib.vn_fill_dense(
        ptr(rows), ptr(vals), ptr(wts), len(rows), ptr(dense_id),
        capacity, ptr(dv), ptr(dw), ptr(depths), u_pad, d_pad,
        n_threads))


def metro64(data: bytes) -> int:
    """Native MetroHash64 (seed 1337) — test hook for hash parity with
    veneur_tpu.sketches.hll.hash64."""
    return int(load_library().vn_metro64(data, len(data)))


def simd_supported(mode: str) -> bool:
    """Whether the host CPU supports a SIMD dispatch mode by name."""
    return bool(load_library().vn_simd_supported(SIMD_MODES[mode]))


def key_hash(data: bytes, mode: str) -> int:
    """Intern-key hash under an explicit SIMD mode — test hook for the
    scalar/SSE2/AVX2 lane-hash parity contract (all modes must compute
    the identical function, or mixed-mode engines would intern the same
    identity to different shard slots)."""
    return int(load_library().vn_key_hash(data, len(data), SIMD_MODES[mode]))


def scan_tokens(data: bytes, mode: str) -> list[tuple[int, str]]:
    """Run one tokenizer pass under an explicit SIMD mode — test hook
    returning [(position, delimiter), ...] sorted by position, for
    scalar-vs-SIMD boundary parity checks."""
    lib = load_library()
    cap = max(len(data), 1)
    pos = (ctypes.c_longlong * cap)()
    cls = (ctypes.c_ubyte * cap)()
    n = int(lib.vn_scan_tokens(data, len(data), SIMD_MODES[mode],
                               pos, cls, cap))
    if n < 0:
        raise ValueError(f"unsupported SIMD mode {mode!r}")
    chars = ("\n", ":", "|")
    return [(int(pos[i]), chars[cls[i]]) for i in range(min(n, cap))]


def blast_udp(host: str, port: int, n_packets: int,
              payloads: list[bytes]) -> int:
    """Benchmark sender: cycle `payloads` via sendmmsg; returns packets
    handed to the kernel."""
    lib = load_library()
    blob = b"".join(payloads)
    offs = np.zeros(len(payloads) + 1, np.int64)
    np.cumsum([len(p) for p in payloads], out=offs[1:])
    return int(lib.vn_blast_udp(
        host.encode(), port, n_packets, blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        len(payloads)))


@dataclass
class NewKey:
    id: int
    mtype: str
    scope: MetricScope
    name: str
    joined_tags: str


@dataclass
class DrainBatch:
    c_ids: np.ndarray
    c_vals: np.ndarray
    g_ids: np.ndarray
    g_vals: np.ndarray
    h_ids: np.ndarray
    h_vals: np.ndarray
    h_wts: np.ndarray
    s_ids: np.ndarray
    s_hashes: np.ndarray
    new_keys: list[NewKey]
    other: list[bytes]
    processed: int
    malformed: int
    packets: int
    too_long: int

    @property
    def empty(self) -> bool:
        return (len(self.c_ids) == 0 and len(self.g_ids) == 0
                and len(self.h_ids) == 0 and len(self.s_ids) == 0
                and not self.new_keys and not self.other)

    @classmethod
    def void(cls) -> "DrainBatch":
        z = np.empty(0, np.uint32)
        f = np.empty(0, np.float64)
        return cls(c_ids=z, c_vals=f, g_ids=z, g_vals=f, h_ids=z, h_vals=f,
                   h_wts=f, s_ids=z, s_hashes=np.empty(0, np.uint64),
                   new_keys=[], other=[], processed=0, malformed=0,
                   packets=0, too_long=0)


def _copy_array(ptr, n, dtype):
    if n == 0 or not ptr:
        return np.empty(0, dtype)
    ct = {np.uint32: ctypes.c_uint32, np.float64: ctypes.c_double,
          np.uint64: ctypes.c_uint64}[dtype]
    return np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ct)), shape=(n,)).astype(
            dtype, copy=True)


class IngestEngine:
    """One native engine instance: reader threads + staging + intern table.

    ``simd`` / ``backend`` / ``batch`` / ``ring_slots`` mirror the
    ``ingest_*`` config knobs (0 / "auto" = engine default); an
    unsupported explicit SIMD mode raises rather than silently
    downgrading."""

    def __init__(self, max_packet: int = 4096,
                 implicit_tags: Optional[list[str]] = None,
                 simd: str = "auto", backend: str = "auto",
                 batch: int = 0, ring_slots: int = 0):
        self.lib = load_library()
        tags_nl = "\n".join(implicit_tags or [])
        self.handle = ctypes.c_void_p(self.lib.vn_engine_new(
            max_packet, tags_nl.encode()))
        self._closed = False
        self._reader_tids: list[int] = []
        if simd != "auto":
            self._set_opt("simd", SIMD_MODES[simd])
        if backend != "auto":
            self._set_opt("backend", BACKEND_MODES[backend])
        if batch:
            self._set_opt("batch", batch)
        if ring_slots:
            self._set_opt("ring_slots", ring_slots)

    def _set_opt(self, key: str, val: int) -> None:
        if int(self.lib.vn_engine_opt(self.handle, key.encode(), val)) != 0:
            raise ValueError(f"engine rejected option {key}={val}")

    # -- feeding ----------------------------------------------------------

    def new_thread(self) -> int:
        return int(self.lib.vn_thread_new(self.handle))

    def ingest(self, tid: int, datagram: bytes) -> None:
        self.lib.vn_ingest(self.handle, tid, datagram, len(datagram))

    def add_udp_reader(self, fd: int, pin_cpu: int = -1) -> int:
        """Spawn a C++ reader loop (io_uring multishot where the kernel
        supports it, recvmmsg otherwise) on a bound UDP socket fd,
        optionally pinned to a CPU (pin_cpu < 0 = unpinned)."""
        tid = int(self.lib.vn_add_udp_reader_pinned(
            self.handle, fd, pin_cpu))
        self._reader_tids.append(tid)
        return tid

    def reader_backend(self, tid: int) -> str:
        """Resolved receive backend name for a reader thread id."""
        return BACKEND_NAMES.get(
            int(self.lib.vn_reader_backend(self.handle, tid)), "none")

    def simd_mode(self) -> str:
        """Resolved SIMD dispatch mode name."""
        return SIMD_NAMES.get(int(self.lib.vn_simd_mode(self.handle)),
                              "scalar")

    def stop(self) -> None:
        if not self._closed:
            self.lib.vn_stop(self.handle)

    def close(self) -> None:
        if not self._closed:
            self.lib.vn_engine_free(self.handle)
            self._closed = True

    # -- draining ---------------------------------------------------------

    def drain(self, clear_intern: bool = False) -> DrainBatch:
        lib = self.lib
        d = ctypes.c_void_p(
            (lib.vn_drain_clear if clear_intern else lib.vn_drain)(
                self.handle))
        try:
            a = ctypes.c_void_p()
            b = ctypes.c_void_p()
            c = ctypes.c_void_p()

            def sec(which):
                return lib.vn_drain_section(
                    d, which, ctypes.byref(a), ctypes.byref(b),
                    ctypes.byref(c))

            n = sec(0)
            c_ids = _copy_array(a.value, n, np.uint32)
            c_vals = _copy_array(b.value, n, np.float64)
            n = sec(1)
            g_ids = _copy_array(a.value, n, np.uint32)
            g_vals = _copy_array(b.value, n, np.float64)
            n = sec(2)
            h_ids = _copy_array(a.value, n, np.uint32)
            h_vals = _copy_array(b.value, n, np.float64)
            h_wts = _copy_array(c.value, n, np.float64)
            n = sec(3)
            s_ids = _copy_array(a.value, n, np.uint32)
            s_hashes = _copy_array(b.value, n, np.uint64)

            n_keys = sec(4)
            blob_len = b.value or 0
            keys_blob = ctypes.string_at(a.value, blob_len) if n_keys else b""
            new_keys = []
            off = 0
            for _ in range(n_keys):
                kid, mt, sc, nlen, tlen = struct.unpack_from(
                    "<IBBII", keys_blob, off)
                off += 14
                name = keys_blob[off:off + nlen].decode(errors="replace")
                off += nlen
                joined = keys_blob[off:off + tlen].decode(errors="replace")
                off += tlen
                new_keys.append(NewKey(
                    id=kid, mtype=_TYPE_NAMES[mt], scope=MetricScope(sc),
                    name=name, joined_tags=joined))

            nbytes = sec(5)
            other = []
            if nbytes:
                oblob = ctypes.string_at(a.value, nbytes)
                off = 0
                while off < nbytes:
                    (ln,) = struct.unpack_from("<I", oblob, off)
                    off += 4
                    other.append(oblob[off:off + ln])
                    off += ln

            stats = (ctypes.c_ulonglong * 4)()
            lib.vn_drain_stats(d, stats)
            return DrainBatch(
                c_ids=c_ids, c_vals=c_vals, g_ids=g_ids, g_vals=g_vals,
                h_ids=h_ids, h_vals=h_vals, h_wts=h_wts,
                s_ids=s_ids, s_hashes=s_hashes,
                new_keys=new_keys, other=other,
                processed=int(stats[0]), malformed=int(stats[1]),
                packets=int(stats[2]), too_long=int(stats[3]))
        finally:
            lib.vn_drain_free(d)

    def totals(self) -> tuple[int, int, int, int]:
        """(processed, malformed, packets, too_long) accumulated over all
        past drains."""
        out = (ctypes.c_ulonglong * 4)()
        self.lib.vn_totals(self.handle, out)
        return tuple(int(x) for x in out)

    def intern_count(self) -> int:
        return int(self.lib.vn_intern_count(self.handle))

    def stage_stats(self) -> dict:
        """Per-stage data-plane accounting (profiling subsystem).

        Returns {"threads": [...], "totals": {...}} where each thread
        entry and the totals carry monotonic packet/call and nanosecond
        counters per pipeline stage (STAGE_NAMES order): recvmmsg covers
        the poll+recvmmsg syscalls INCLUDING the wait for packets (only
        native UDP reader threads accrue it; vn_ingest-fed threads show
        zero), parse is datagram/line scanning minus the carved-out
        intern and stage shares, intern is identity interning, stage is
        value float-parse + columnar append, drain is the engine-level
        consolidation pass (runs on the drainer thread)."""
        n = int(self.lib.vn_stage_thread_count(self.handle))
        threads = []
        if n > 0:
            buf = (ctypes.c_ulonglong * (n * 8))()
            n = int(self.lib.vn_stage_stats(self.handle, buf, n))
            for t in range(n):
                row = buf[t * 8:(t + 1) * 8]
                threads.append({
                    "recvmmsg": {"packets": int(row[0]), "ns": int(row[1])},
                    "parse": {"packets": int(row[2]), "ns": int(row[3])},
                    "intern": {"calls": int(row[4]), "ns": int(row[5])},
                    "stage": {"values": int(row[6]), "ns": int(row[7])},
                })
        d3 = (ctypes.c_ulonglong * 3)()
        self.lib.vn_stage_drain(self.handle, d3)
        totals: dict = {
            name: {k: sum(t[name][k] for t in threads)
                   for k in (STAGE_UNITS[name], "ns")}
            for name in STAGE_NAMES[:-1]}
        totals["drain"] = {"calls": int(d3[0]), "packets": int(d3[1]),
                           "ns": int(d3[2])}
        # dispatch introspection rides alongside (diagnostics flattens
        # only "totals", so these additive keys never collide with the
        # per-stage gauge namespace)
        readers = {str(t): self.reader_backend(t)
                   for t in self._reader_tids}
        return {"threads": threads, "totals": totals,
                "readers": readers, "simd": self.simd_mode()}


@dataclass
class _IdInfo:
    key: MetricKey
    row_scope: MetricScope   # arena row class (family-specific mapping)
    tags: list[str]
    uts_bytes: Optional[bytes]  # unique-timeseries HLL insert, if counted
    row: int = -1
    meta: object = None      # RowMeta identity for GC revalidation
    # histogram family dispatch: the arena this id's row binding lives
    # in (digests or moments; None until first resolution)
    arena: object = None
    # cardinality-guard epoch this row binding was resolved under; an
    # interval-end eviction/promotion bumps the guard's epoch, which
    # forces a re-resolve (the key may have changed buckets)
    card_epoch: int = -1


class NativeIngest:
    """Applies engine drains to a MetricAggregator's arenas.

    Keeps the id -> arena-row mapping, revalidating against row GC (a row
    idle for IDLE_GC_INTERVALS flushes is recycled; the engine id outlives
    it, so stale cache entries re-upsert through `row_for`).
    """

    def __init__(self, aggregator, max_packet: int = 4096,
                 implicit_tags: Optional[list[str]] = None,
                 on_other: Optional[Callable[[bytes], None]] = None,
                 simd: str = "auto", backend: str = "auto",
                 batch: int = 0, ring_slots: int = 0):
        self.agg = aggregator
        self.engine = IngestEngine(max_packet, implicit_tags,
                                   simd=simd, backend=backend,
                                   batch=batch, ring_slots=ring_slots)
        self.on_other = on_other
        self._info: list[Optional[_IdInfo]] = []
        # engine ids whose identity can NEVER produce a cube rollup
        # (no dimension matches, or the key is itself a cube/rollup
        # row) — static per identity, so the per-drain fast path skips
        # them without re-scanning tags
        self._cube_inert: set = set()
        self.malformed = 0
        self.too_long = 0
        self._drain_lock = threading.Lock()

    # -- key registration --------------------------------------------------

    def _register(self, nk: NewKey) -> None:
        while len(self._info) <= nk.id:
            self._info.append(None)
        tags = nk.joined_tags.split(",") if nk.joined_tags else []
        key = MetricKey(nk.name, nk.mtype, nk.joined_tags)
        t = nk.mtype
        if t in ("counter", "gauge"):
            row_scope = (MetricScope.GLOBAL_ONLY
                         if nk.scope == MetricScope.GLOBAL_ONLY
                         else MetricScope.MIXED)
        elif t == "set":
            row_scope = (MetricScope.LOCAL_ONLY
                         if nk.scope == MetricScope.LOCAL_ONLY
                         else MetricScope.MIXED)
        else:
            row_scope = nk.scope
        uts = None
        if self.agg.count_unique_timeseries:
            # worker.go:301-345 locality rules (see
            # MetricAggregator._sample_timeseries)
            if not self.agg.is_local:
                counted = True
            elif t in ("counter", "gauge"):
                counted = nk.scope != MetricScope.GLOBAL_ONLY
            else:  # histogram / timer / set
                counted = nk.scope == MetricScope.LOCAL_ONLY
            if counted:
                uts = metric_digest(
                    nk.name, nk.mtype, nk.joined_tags).to_bytes(8, "little")
        self._info[nk.id] = _IdInfo(key=key, row_scope=row_scope, tags=tags,
                                    uts_bytes=uts)

    def _rows_for(self, arena, ids: np.ndarray) -> np.ndarray:
        """Resolve engine ids to arena rows (vectorized via the cache;
        row_for only on first sight or after GC).  With a cardinality
        guard active, every unique id reports its staged-sample count to
        the guard (touch counts drive the seeded count-ordered
        eviction), and row bindings resolved under a stale guard epoch
        re-resolve — the key may have moved between its exact row and
        the tenant rollup row."""
        guard = getattr(self.agg, "cardinality", None)
        uids, ucounts = np.unique(ids, return_counts=True)
        lut = np.empty(int(uids[-1]) + 1 if len(uids) else 0, np.int64)
        uts = self.agg.unique_ts
        for uid, ucount in zip(uids, ucounts):
            info = self._info[uid]
            row = info.row
            resolved = None
            if guard is not None:
                resolved = guard.resolve(info.key, info.row_scope,
                                         info.tags, int(ucount))
                if info.card_epoch != guard.epoch:
                    info.card_epoch = guard.epoch
                    row = -1
            if row < 0 or arena.meta[row] is not info.meta:
                key, scope, tags = (resolved if resolved is not None
                                    else (info.key, info.row_scope,
                                          info.tags))
                row = arena.row_for(key, scope, tags)
                info.row = row
                info.meta = arena.meta[row]
            else:
                arena.touched[row] = True
            lut[uid] = row
            if uts is not None and info.uts_bytes is not None:
                uts.insert(info.uts_bytes)
        return lut[ids]

    def _hrows_for(self, ids: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray]:
        """Histogram/timer twin of _rows_for under sketch-family
        dispatch: the target arena depends on the (possibly guard-
        rolled) identity, so each id resolves its arena alongside its
        row.  Returns (rows, fam) aligned with ``ids`` where ``fam``
        codes the target arena: 0 digests, 1 moments, 2 compactors."""
        agg = self.agg
        guard = getattr(agg, "cardinality", None)
        uids, ucounts = np.unique(ids, return_counts=True)
        hi = int(uids[-1]) + 1 if len(uids) else 0
        lut = np.empty(hi, np.int64)
        mlut = np.zeros(hi, np.int8)
        uts = agg.unique_ts
        for uid, ucount in zip(uids, ucounts):
            info = self._info[uid]
            row = info.row
            arena = info.arena
            resolved = None
            if guard is not None:
                resolved = guard.resolve(info.key, info.row_scope,
                                         info.tags, int(ucount))
                if info.card_epoch != guard.epoch:
                    info.card_epoch = guard.epoch
                    row = -1
            if row < 0 or arena is None \
                    or arena.meta[row] is not info.meta:
                key, scope, tags = (resolved if resolved is not None
                                    else (info.key, info.row_scope,
                                          info.tags))
                arena = agg._histo_arena(key, tags)
                row = arena.row_for(key, scope, tags)
                info.row = row
                info.meta = arena.meta[row]
                info.arena = arena
            else:
                arena.touched[row] = True
            lut[uid] = row
            mlut[uid] = (1 if arena is agg.moments
                         else 2 if arena is agg.compactors else 0)
            if uts is not None and info.uts_bytes is not None:
                uts.insert(info.uts_bytes)
        return lut[ids], mlut[ids]

    # -- drain application -------------------------------------------------

    def drain_into(self) -> DrainBatch:
        """Drain the engine and fold the batch into the arenas.  One brief
        aggregator-lock hold; events/service checks replay through the
        Python slow path afterwards."""
        return self._drain(clear_intern=False)

    def reset_interning(self) -> DrainBatch:
        """Apply a final drain, then clear the engine's intern table + the
        id cache (cardinality-churn GC: the intern map would otherwise grow
        with every metric identity ever seen).  The engine restarts its id
        space at 0, so the Python cache stays bounded by live cardinality."""
        return self._drain(clear_intern=True)

    def drain_or_gc(self, intern_threshold: int) -> DrainBatch:
        """One drainer-loop tick: a plain drain, or a drain+intern-GC when
        the engine's identity table has outgrown `intern_threshold`."""
        return self._drain(clear_intern=False,
                           intern_threshold=intern_threshold)

    def _drain(self, clear_intern: bool,
               intern_threshold: Optional[int] = None) -> DrainBatch:
        """The single drain path: lock, consolidate+apply (optionally
        wiping the intern table and id cache), then replay punted
        events/service-check lines through the Python slow path.  All
        engine access happens under the drain lock — close() takes the
        same lock, so teardown cannot free the engine mid-call."""
        with self._drain_lock:
            if intern_threshold is not None and not self.engine._closed:
                clear_intern = (self.engine.intern_count()
                                > intern_threshold)
            batch = self._drain_apply(clear_intern)
            if clear_intern:
                self._info = []
                self._cube_inert.clear()
        if self.on_other:
            for line in batch.other:
                self.on_other(line)
        return batch

    def _drain_apply(self, clear_intern: bool = False) -> DrainBatch:
        if self.engine._closed:
            return DrainBatch.void()
        batch = self.engine.drain(clear_intern)
        if batch.malformed:
            self.malformed += batch.malformed
        if batch.too_long:
            self.too_long += batch.too_long
        if not batch.empty:
            agg = self.agg
            with agg.lock:
                for nk in batch.new_keys:
                    self._register(nk)
                agg.processed += batch.processed
                if len(batch.c_ids):
                    rows = self._rows_for(agg.counters, batch.c_ids)
                    agg.counters.sample_batch(rows, batch.c_vals)
                if len(batch.g_ids):
                    rows = self._rows_for(agg.gauges, batch.g_ids)
                    # in-order fancy assignment: last write wins
                    agg.gauges.values[rows] = batch.g_vals
                if len(batch.h_ids):
                    if getattr(agg, "family_dispatch", False):
                        rows, fam = self._hrows_for(batch.h_ids)
                        for code, arena in ((1, agg.moments),
                                            (2, agg.compactors),
                                            (0, agg.digests)):
                            sel = fam == code
                            if sel.any():
                                arena.sample_batch(
                                    rows[sel], batch.h_vals[sel],
                                    batch.h_wts[sel])
                    else:
                        rows = self._rows_for(agg.digests, batch.h_ids)
                        agg.digests.sample_batch(rows, batch.h_vals,
                                                 batch.h_wts)
                    cubes = getattr(agg, "cubes", None)
                    if cubes is not None:
                        self._apply_cube_rollups(agg, cubes, batch)
                if len(batch.s_ids):
                    rows = self._rows_for(agg.sets, batch.s_ids)
                    agg.sets.stage_hash_batch(rows, batch.s_hashes)
        return batch

    def _apply_cube_rollups(self, agg, cubes, batch) -> None:
        """Mirror the batch's histogram/timer samples into their cube
        rollup rows — the native-path twin of the materialization
        `_process_locked` does on the Python ingest edge (runs under
        the same aggregator lock, from the drain).  ``rollups`` is
        called per unique id per drain with the staged-sample count:
        budget admission, touch accounting and the conservation
        counters live there, so the call cannot be cached — only the
        never-cubes verdict (a static property of the identity) is."""
        ids = batch.h_ids
        order = np.argsort(ids, kind="stable")
        sids = ids[order]
        svals = batch.h_vals[order]
        swts = batch.h_wts[order]
        uids = np.unique(sids)
        bounds = np.searchsorted(sids, uids, side="left")
        ends = np.searchsorted(sids, uids, side="right")
        for uid, lo, hi in zip(uids, bounds, ends):
            if uid in self._cube_inert:
                continue
            info = self._info[uid]
            targets = cubes.rollups(info.key, info.row_scope,
                                    info.tags, n=int(hi - lo))
            if not targets:
                self._cube_inert.add(int(uid))
                continue
            vals = svals[lo:hi]
            wts = swts[lo:hi]
            for ck, cs, ctags in targets:
                arena = agg._histo_arena(ck, ctags)
                row = arena.row_for(ck, cs, ctags)
                arena.sample_batch(
                    np.full(len(vals), row, np.int64), vals, wts)

    def stats(self) -> Optional[dict]:
        """Safe snapshot for observability endpoints: totals + intern
        size under the drain lock (close() takes the same lock, so a
        probe racing teardown reads None instead of freed memory)."""
        with self._drain_lock:
            if self.engine._closed:
                return None
            lines, malformed, packets, too_long = self.engine.totals()
            return {"lines": lines, "malformed": malformed,
                    "packets": packets, "too_long": too_long,
                    "intern_count": self.engine.intern_count()}

    def stage_stats(self) -> Optional[dict]:
        """Per-stage counters for /debug/vars, under the drain lock so a
        probe racing teardown reads None instead of freed memory."""
        with self._drain_lock:
            if self.engine._closed:
                return None
            return self.engine.stage_stats()

    def stop(self) -> None:
        self.engine.stop()

    def close(self) -> None:
        # serialize with any in-flight drain (the drainer thread may still
        # be mid-apply when the server tears down)
        with self._drain_lock:
            self.engine.close()
