"""Multi-resolution retention: the tiered quantile timeline.

On every flush cut the finalized window snapshot compacts upward into
a ladder of coarser tiers (minute/hour/day by configuration), each a
bounded ring of mergeable buckets; buckets evicted from the coarsest
in-memory tier spill to disk in the CRC-framed ForwardSpool segment
format under a byte/age budget.  `GET /query?since=&step=` plans which
tiers cover the requested range and fuses buckets across them — the
aggregation tier serving its own recent past at bounded error and
bounded footprint.
"""

from veneur_tpu.retention.spill import (TierSegmentStore,
                                        close_tier_segment,
                                        open_tier_segment)
from veneur_tpu.retention.timeline import (RetentionTier,
                                           RetentionTimeline, TierBucket)

__all__ = ["RetentionTimeline", "RetentionTier", "TierBucket",
           "TierSegmentStore", "open_tier_segment",
           "close_tier_segment"]
