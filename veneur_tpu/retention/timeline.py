"""The multi-resolution retention timeline.

Every flush cut already produces an immutable snapshot `part` per
histogram family (the same parts the query WindowRing rotates).  The
timeline compacts those parts upward through a ladder of coarser
tiers:

    cut (seconds)  ->  tier 0 (e.g. minute)  ->  tier 1 (hour)  -> ...

Each tier is a bounded ring of `TierBucket`s.  A cut merges into the
finest tier's open bucket; when a bucket's time span completes it
closes into the tier's ring AND merges into the next tier's open
bucket — so every datum lives at every resolution simultaneously, and
a range query picks the finest tier still holding its window.  The
merges are the families' own merges (digest point-cloud concat with
the serving compress kernel past the payload cap, moments rebase-add,
compactor concat-then-compact), so every bucket stays mergeable and
every tier inherits the family's committed error envelope.

Buckets evicted from the COARSEST tier's ring spill to disk through
the TierSegmentStore (retention/spill.py) — the bounded-footprint
tail of the timeline; evictions from finer tiers are not loss (their
mass already cascaded upward) and are counted, not spilled.

Crash contract: the in-memory tiers checkpoint with the arena cut
(aggregator.checkpoint_state -> "retention" block) and the on-disk
segments re-index on boot — proven by the `timeline-crash-revive`
chaos arm.

The timeline's lock is a leaf: taken from the compaction worker and
from query threads; it never nests inside any aggregator or arena
lock.  The flush hook (compact_cut, called AFTER the aggregator lock
releases) only ENQUEUES the cut's immutable snapshot parts — the
egress-lane pattern: extraction and tier merges run on a daemon
worker, so the flush path pays a handoff, not O(live keys) work.
`drain()` (called by the checkpoint capture) fences the queue.
"""

from __future__ import annotations

import io
import json
import math
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

# fused digest clouds past this many points compress down through the
# serving compress kernel (the same bound the query payload codec uses)
BUCKET_POINT_CAP = 2048


def _jtags(tags) -> str:
    return ",".join(sorted(tags)) if tags is not None and len(tags) \
        else ""


# -- per-cut summaries (snapshot part -> per-key mergeable payloads) ----

def summarize_digest_part(part: dict, point_cap: int = BUCKET_POINT_CAP,
                          compression: float = 100.0) -> dict:
    """Digest-family snapshot part -> {(name, jtags, kind): cloud}.
    The cloud is the key's staged weighted points plus the exact
    scalar accumulators — the same extraction the query fusion does,
    over every key in the part at once."""
    rows = part["rows"]
    n = len(rows)
    if n == 0:
        return {}
    srows, svals, swts = part["staged"]
    order = np.argsort(srows, kind="stable")
    ss = srows[order]
    sv = np.asarray(svals, np.float64)[order]
    sw = np.asarray(swts, np.float64)[order]
    names, tags, kinds = part["names"], part["tags"], part["kinds"]
    # one vectorized pass for every per-key boundary and scalar (the
    # hook runs on the flush path: a per-key searchsorted here showed
    # up as flush degradation at the 5k-key shape)
    rr = np.asarray(rows, np.int64)
    lo_a = np.searchsorted(ss, rr).tolist()
    hi_a = np.searchsorted(ss, rr + 1).tolist()
    cnt_a = np.asarray(part["d_weight"], np.float64).tolist()
    min_a = np.asarray(part["d_min"], np.float64).tolist()
    max_a = np.asarray(part["d_max"], np.float64).tolist()
    sum_a = np.asarray(part["d_sum"], np.float64).tolist()
    rsum_a = np.asarray(part["d_rsum"], np.float64).tolist()
    out: dict = {}
    for i in range(n):
        lo, hi = lo_a[i], hi_a[i]
        cnt = cnt_a[i]
        if cnt <= 0 and hi <= lo:
            continue
        key = (str(names[i]), _jtags(tags[i]), str(kinds[i]))
        ent = {"v": sv[lo:hi].copy(), "w": sw[lo:hi].copy(),
               "min": min_a[i], "max": max_a[i],
               "count": cnt, "sum": sum_a[i], "rsum": rsum_a[i]}
        prev = out.get(key)
        out[key] = ent if prev is None else \
            merge_cloud(prev, ent, point_cap, compression)
    return out


def summarize_vector_part(part: dict, arena, family: str) -> dict:
    """Moments/compactor snapshot part -> {(name, jtags, kind): wire
    vector}, via ONE batched assemble_vectors walk over the part."""
    rows = part["rows"]
    n = len(rows)
    if n == 0:
        return {}
    if family == "moments":
        from veneur_tpu.sketches import moments as fam
    else:
        from veneur_tpu.sketches import compactor as fam
    srows, svals, swts = part["staged"]
    order = np.argsort(srows, kind="stable")
    sub = (srows[order], svals[order], swts[order])
    parr = np.arange(n, dtype=np.int64)
    vecs = arena.assemble_vectors(part, sub, parr)
    names, tags, kinds = part["names"], part["tags"], part["kinds"]
    out: dict = {}
    for i in range(n):
        vec = np.asarray(vecs[i], np.float64)
        if float(vec[fam.IDX_COUNT]) <= 0:
            continue
        key = (str(names[i]), _jtags(tags[i]), str(kinds[i]))
        prev = out.get(key)
        out[key] = vec.copy() if prev is None else \
            fam.merge_vectors(prev[None, :], vec[None, :])[0]
    return out


def merge_cloud(a: dict, b: dict, point_cap: int = BUCKET_POINT_CAP,
                compression: float = 100.0) -> dict:
    """Digest bucket merge: weighted point-cloud concat, compressed
    through the serving kernel only past the cap (below it the merge
    is bit-exact concatenation — the tier-compaction parity tests
    stay under the cap)."""
    v = np.concatenate([a["v"], b["v"]])
    w = np.concatenate([a["w"], b["w"]])
    if len(v) > point_cap:
        from veneur_tpu.query.engine import _compress_payload
        v, w = _compress_payload(v, w, compression)
    return {"v": v, "w": w,
            "min": min(a["min"], b["min"]),
            "max": max(a["max"], b["max"]),
            "count": a["count"] + b["count"],
            "sum": a["sum"] + b["sum"],
            "rsum": a["rsum"] + b["rsum"]}


class TierBucket:
    """One tier bucket: per-key mergeable payloads for all three
    families over [t_start, t_end).  `filled_to` tracks how far the
    bucket's data actually reaches (an open bucket covers only up to
    the last merged cut)."""

    __slots__ = ("t_start", "t_end", "filled_to", "td", "mo", "cc",
                 "cuts")

    def __init__(self, t_start: float, t_end: float):
        self.t_start = float(t_start)
        self.t_end = float(t_end)
        self.filled_to = float(t_start)
        self.td: dict = {}
        self.mo: dict = {}
        self.cc: dict = {}
        self.cuts = 0

    @property
    def points(self) -> float:
        """Total sample count across families (the conservation
        currency of the crash arm)."""
        return (sum(e["count"] for e in self.td.values())
                + sum(float(v[0]) for v in self.mo.values())
                + sum(float(v[0]) for v in self.cc.values()))

    def nbytes(self) -> int:
        n = 0
        for e in self.td.values():
            n += e["v"].nbytes + e["w"].nbytes + 48
        for v in self.mo.values():
            n += v.nbytes
        for v in self.cc.values():
            n += v.nbytes
        return n

    def absorb(self, td: dict, mov: dict, ccv: dict, upto: float,
               point_cap: int, compression: float) -> None:
        """Merge one cut's (or one finer bucket's) per-key summaries
        into this bucket — the tier compaction itself."""
        from veneur_tpu.sketches import compactor as cs
        from veneur_tpu.sketches import moments as mo
        for key, ent in td.items():
            prev = self.td.get(key)
            self.td[key] = (
                {"v": ent["v"], "w": ent["w"], "min": ent["min"],
                 "max": ent["max"], "count": ent["count"],
                 "sum": ent["sum"], "rsum": ent["rsum"]}
                if prev is None
                else merge_cloud(prev, ent, point_cap, compression))
        for key, vec in mov.items():
            prev = self.mo.get(key)
            self.mo[key] = vec.copy() if prev is None else \
                mo.merge_vectors(prev[None, :], vec[None, :])[0]
        for key, vec in ccv.items():
            prev = self.cc.get(key)
            self.cc[key] = vec.copy() if prev is None else \
                cs.merge_vectors(prev[None, :], vec[None, :])[0]
        self.filled_to = max(self.filled_to, min(float(upto),
                                                 self.t_end))
        self.cuts += 1

    def snapshot(self) -> "TierBucket":
        """Shallow copy for lock-free reads: payload dicts copy by
        reference (entries are replaced, never mutated in place)."""
        b = TierBucket(self.t_start, self.t_end)
        b.filled_to = self.filled_to
        b.td = dict(self.td)
        b.mo = dict(self.mo)
        b.cc = dict(self.cc)
        b.cuts = self.cuts
        return b


# -- the bucket codec (checkpoint arrays and the spill body share it) ---

def bucket_to_arrays(b: TierBucket) -> tuple[dict, dict]:
    """TierBucket -> (JSON-able meta, named float64 arrays): the flat
    columnar form both the checkpoint (npz arrays) and the spill body
    serialize.  Floats round-trip bit-exactly."""
    td_keys = sorted(b.td)
    mo_keys = sorted(b.mo)
    cc_keys = sorted(b.cc)
    sizes = [len(b.td[k]["v"]) for k in td_keys]
    off = np.zeros(len(td_keys) + 1, np.int64)
    off[1:] = np.cumsum(sizes)
    meta = {"t_start": b.t_start, "t_end": b.t_end,
            "filled_to": b.filled_to, "cuts": b.cuts,
            "td_keys": [list(k) for k in td_keys],
            "mo_keys": [list(k) for k in mo_keys],
            "cc_keys": [list(k) for k in cc_keys]}
    arrays = {
        "td_off": off,
        "td_vals": (np.concatenate([b.td[k]["v"] for k in td_keys])
                    if td_keys else np.zeros(0, np.float64)),
        "td_wts": (np.concatenate([b.td[k]["w"] for k in td_keys])
                   if td_keys else np.zeros(0, np.float64)),
        "td_scal": np.asarray(
            [[b.td[k]["min"], b.td[k]["max"], b.td[k]["count"],
              b.td[k]["sum"], b.td[k]["rsum"]] for k in td_keys],
            np.float64).reshape(len(td_keys), 5),
        "mo_vecs": (np.stack([b.mo[k] for k in mo_keys])
                    if mo_keys else np.zeros((0, 0), np.float64)),
        "cc_vecs": (np.stack([b.cc[k] for k in cc_keys])
                    if cc_keys else np.zeros((0, 0), np.float64)),
    }
    return meta, arrays


def bucket_from_arrays(meta: dict, arrays: dict) -> TierBucket:
    b = TierBucket(meta["t_start"], meta["t_end"])
    b.filled_to = float(meta["filled_to"])
    b.cuts = int(meta.get("cuts", 0))
    off = np.asarray(arrays["td_off"], np.int64)
    vals = np.asarray(arrays["td_vals"], np.float64)
    wts = np.asarray(arrays["td_wts"], np.float64)
    scal = np.asarray(arrays["td_scal"], np.float64)
    for i, key in enumerate(meta["td_keys"]):
        lo, hi = int(off[i]), int(off[i + 1])
        b.td[tuple(key)] = {
            "v": vals[lo:hi].copy(), "w": wts[lo:hi].copy(),
            "min": float(scal[i, 0]), "max": float(scal[i, 1]),
            "count": float(scal[i, 2]), "sum": float(scal[i, 3]),
            "rsum": float(scal[i, 4])}
    mo_vecs = np.asarray(arrays["mo_vecs"], np.float64)
    for i, key in enumerate(meta["mo_keys"]):
        b.mo[tuple(key)] = mo_vecs[i].copy()
    cc_vecs = np.asarray(arrays["cc_vecs"], np.float64)
    for i, key in enumerate(meta["cc_keys"]):
        b.cc[tuple(key)] = cc_vecs[i].copy()
    return b


def encode_bucket_body(b: TierBucket) -> bytes:
    """Bucket -> spill record body (npz-in-bytes with a JSON
    `__meta__` key table)."""
    meta, arrays = bucket_to_arrays(b)
    bio = io.BytesIO()
    np.savez(bio, __meta__=np.frombuffer(
        json.dumps(meta).encode(), np.uint8), **arrays)
    return bio.getvalue()


def decode_bucket_body(body: bytes) -> TierBucket:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        meta = json.loads(bytes(np.asarray(z["__meta__"]).tobytes()))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    return bucket_from_arrays(meta, arrays)


class RetentionTier:
    """One resolution: a bounded ring of closed buckets plus the open
    bucket currently absorbing cuts."""

    def __init__(self, name: str, bucket_seconds: float,
                 capacity: int):
        if bucket_seconds <= 0:
            raise ValueError(f"retention tier {name!r}: bucket "
                             f"seconds must be > 0, got {bucket_seconds}")
        if capacity < 1:
            raise ValueError(f"retention tier {name!r}: capacity "
                             f"must be >= 1, got {capacity}")
        self.name = name
        self.bucket_seconds = float(bucket_seconds)
        self.capacity = int(capacity)
        self.buckets: deque[TierBucket] = deque()
        self.open: Optional[TierBucket] = None
        self.closed_total = 0
        self.evicted = 0

    def stats(self) -> dict:
        held = list(self.buckets)
        if self.open is not None:
            held.append(self.open)
        return {"bucket_seconds": self.bucket_seconds,
                "capacity": self.capacity,
                "buckets": len(self.buckets),
                "open": int(self.open is not None),
                "closed_total": self.closed_total,
                "evicted": self.evicted,
                "points_held": float(sum(b.points for b in held)),
                "bytes_held": int(sum(b.nbytes() for b in held))}


class RetentionTimeline:
    """The tier ladder + the spill store + the checkpoint codec."""

    def __init__(self, tiers: list, store=None,
                 compression: float = 100.0,
                 point_cap: int = BUCKET_POINT_CAP,
                 statsd_fn=None):
        """`tiers` is the config shape: a finest-first list of
        {"seconds": float, "buckets": int[, "name": str]} dicts."""
        if not tiers:
            raise ValueError("retention needs at least one tier")
        self.tiers: list[RetentionTier] = []
        prev = 0.0
        for i, spec in enumerate(tiers):
            secs = float(spec["seconds"])
            if secs <= prev:
                raise ValueError(
                    "retention_tiers must be finest-first with "
                    f"strictly increasing seconds, got {secs} after "
                    f"{prev}")
            prev = secs
            self.tiers.append(RetentionTier(
                str(spec.get("name") or f"t{i}x{int(secs)}s"),
                secs, int(spec.get("buckets", 8))))
        self.store = store
        self.compression = float(compression)
        self.point_cap = int(point_cap)
        self._statsd_fn = statsd_fn or (lambda: None)
        self.lock = threading.Lock()
        self.compactions = 0       # cuts absorbed
        self.points_in = 0.0
        self.last_cut = 0.0
        # coarsest-tier evictions staged under the lock, spilled to
        # disk after it drops (no I/O under the timeline lock)
        self._pending_spill: list = []
        # the flush hook only ENQUEUES (the egress-lane pattern: the
        # flush path hands off, it does not pay O(live keys) part
        # summarization); this worker does extraction + tier merges.
        # Ordering is FIFO so cut positions stay monotone.
        self._cv = threading.Condition()
        self._queued: deque = deque()
        self._compacting = False
        self._stopped = False
        self._worker: Optional[threading.Thread] = None
        self.compact_errors = 0

    # -- the flush-cut hook ---------------------------------------------

    def compact_cut(self, dpart: dict, mpart: dict, cpart: dict,
                    cut_ts: float, moments_arena,
                    compactor_arena) -> None:
        """Queue one flush cut's snapshot parts (the same immutable
        parts the WindowRing slots hold — query threads already read
        them lock-free, so the compaction worker may too).  The flush
        path pays a handoff; `drain()` (and the checkpoint capture)
        waits for the worker to go idle."""
        with self._cv:
            if self._stopped:
                return
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._worker_loop, daemon=True,
                    name="retention-compact")
                self._worker.start()
            self._queued.append((dpart, mpart, cpart, cut_ts,
                                 moments_arena, compactor_arena))
            self._cv.notify_all()

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queued and not self._stopped:
                    self._cv.wait()
                if not self._queued:
                    return      # stopped and drained (or cleared)
                item = self._queued.popleft()
                self._compacting = True
            try:
                self._compact_one(*item)
            except Exception:
                self.compact_errors += 1
            finally:
                with self._cv:
                    self._compacting = False
                    self._cv.notify_all()

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every queued cut has been compacted (False on
        timeout).  Never call this holding the aggregator lock — the
        worker takes the timeline's own leaf lock only."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._queued or self._compacting:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    def close(self, drain: bool = True) -> None:
        """Stop the compaction worker.  `drain=False` is the crash
        path: queued cuts are DISCARDED (exactly what a kill -9 loses
        — they were never checkpointed) so a dying server can't keep
        spilling into a directory its revival reopened."""
        if drain:
            self.drain()
        with self._cv:
            self._queued.clear()
            self._stopped = True
            self._cv.notify_all()
        w = self._worker
        if w is not None and w is not threading.current_thread():
            w.join(timeout=5.0)

    def _compact_one(self, dpart: dict, mpart: dict, cpart: dict,
                     cut_ts: float, moments_arena,
                     compactor_arena) -> None:
        td = summarize_digest_part(dpart, self.point_cap,
                                   self.compression)
        mov = summarize_vector_part(mpart, moments_arena, "moments")
        ccv = summarize_vector_part(cpart, compactor_arena,
                                    "compactor")
        self.absorb_summaries(td, mov, ccv, cut_ts)

    def absorb_summaries(self, td: dict, mov: dict, ccv: dict,
                         cut_ts: float) -> None:
        """The compact_cut tail: merge one cut's per-key family
        summaries into the tier ladder.  Benchmarks and tests feed
        synthetic summaries (arbitrary cut timestamps) here directly;
        the flush hook arrives via compact_cut's part extraction."""
        pts = (sum(e["count"] for e in td.values())
               + sum(float(v[0]) for v in mov.values())
               + sum(float(v[0]) for v in ccv.values()))
        with self.lock:
            # position the cut by its data window's START (the
            # previous cut), so a cut landing exactly on a bucket
            # boundary files under the bucket its data came from
            pos = self.last_cut if self.last_cut > 0 else cut_ts
            self._feed_locked(0, td, mov, ccv, pos, cut_ts)
            self.last_cut = float(cut_ts)
            self.compactions += 1
            self.points_in += pts
            spills = self._pending_spill
            self._pending_spill = []
        # disk I/O happens OUTSIDE the timeline lock: queries snapshot
        # tier state under it, and a spill stall must not block them
        if self.store is not None:
            for ev in spills:
                self.store.spill(self.tiers[-1].name, ev.t_start,
                                 ev.t_end, int(round(ev.points)),
                                 encode_bucket_body(ev))
            if spills:
                self.store.expire_now()
        from veneur_tpu import scopedstatsd
        statsd = scopedstatsd.ensure(self._statsd_fn())
        statsd.count("retention.compactions_total", 1)
        if pts:
            statsd.count("retention.points_total", pts)

    def _feed_locked(self, ti: int, td: dict, mov: dict, ccv: dict,
                     pos_ts: float, upto: float) -> None:
        tier = self.tiers[ti]
        bs = tier.bucket_seconds
        if tier.open is not None and pos_ts >= tier.open.t_end:
            self._close_locked(ti, tier)
        if tier.open is None:
            start = math.floor(pos_ts / bs) * bs
            tier.open = TierBucket(start, start + bs)
        tier.open.absorb(td, mov, ccv, upto, self.point_cap,
                         self.compression)

    def _close_locked(self, ti: int, tier: RetentionTier) -> None:
        closed = tier.open
        tier.open = None
        tier.buckets.append(closed)
        tier.closed_total += 1
        if ti + 1 < len(self.tiers):
            # cascade: the closed bucket merges into the coarser
            # tier's open bucket, positioned by its OWN start
            self._feed_locked(ti + 1, closed.td, closed.mo, closed.cc,
                              closed.t_start, closed.filled_to)
        while len(tier.buckets) > tier.capacity:
            ev = tier.buckets.popleft()
            tier.evicted += 1
            if ti + 1 < len(self.tiers):
                continue     # its mass lives on in the coarser tier
            # coarsest tier: eviction leaves memory for disk — staged
            # here, written by absorb_summaries AFTER the lock drops
            if self.store is not None:
                self._pending_spill.append(ev)

    # -- the range-query read surface -----------------------------------

    def sources_overlapping(self, t0: float, t1: float) -> list:
        """Finest-first (tier name, bucket_seconds, buckets) triples
        overlapping [t0, t1), open buckets included as snapshots, the
        spill store's on-disk buckets decoded and appended as the
        coarsest source."""
        out = []
        with self.lock:
            for tier in self.tiers:
                bl = [b for b in tier.buckets
                      if b.filled_to > t0 and b.t_start < t1]
                op = tier.open
                if op is not None and op.filled_to > t0 \
                        and op.t_start < t1:
                    bl = bl + [op.snapshot()]
                out.append((tier.name, tier.bucket_seconds, bl))
        if self.store is not None:
            recs = self.store.records_overlapping(t0, t1)
            disk = []
            for rec in recs:
                try:
                    disk.append(decode_bucket_body(
                        self.store.read_body(rec)))
                except Exception:
                    self.store.io_errors += 1
            if disk:
                coarsest = self.tiers[-1]
                out.append((f"{coarsest.name}:disk",
                            coarsest.bucket_seconds, disk))
        return out

    # -- checkpoint (in-memory tiers ride the arena cut) -----------------

    def checkpoint_capture(self) -> tuple[dict, dict]:
        """(meta, arrays) for the aggregator checkpoint: every closed
        AND open bucket of every tier, through the shared codec.
        Drains the compaction queue first so the capture covers every
        cut the flush path has handed off."""
        self.drain()
        meta: dict = {"tiers": [], "compactions": self.compactions,
                      "points_in": self.points_in,
                      "last_cut": self.last_cut}
        arrays: dict = {}
        with self.lock:
            for ti, tier in enumerate(self.tiers):
                held = list(tier.buckets)
                if tier.open is not None:
                    held.append(tier.open)
                tmeta = {"name": tier.name,
                         "bucket_seconds": tier.bucket_seconds,
                         "closed_total": tier.closed_total,
                         "evicted": tier.evicted,
                         "n_buckets": len(held),
                         "open": int(tier.open is not None),
                         "buckets": []}
                for bi, b in enumerate(held):
                    bmeta, barrs = bucket_to_arrays(b)
                    tmeta["buckets"].append(bmeta)
                    for k, v in barrs.items():
                        arrays[f"t{ti}/b{bi}/{k}"] = v
                meta["tiers"].append(tmeta)
        return meta, arrays

    def checkpoint_restore(self, meta: dict, arrays: dict) -> None:
        """Restore the in-memory tiers from a checkpoint capture.
        Tier geometry must match the running config (a geometry change
        cold-starts the timeline instead of mis-filing buckets)."""
        tiers_meta = meta.get("tiers") or []
        if len(tiers_meta) != len(self.tiers) or any(
                float(tm["bucket_seconds"]) != t.bucket_seconds
                for tm, t in zip(tiers_meta, self.tiers)):
            return
        # decode every bucket BEFORE taking the lock (the codec pulls
        # array scalars — a device sync queries must not wait behind)
        decoded: list[list[TierBucket]] = []
        for ti, tm in enumerate(tiers_meta):
            held = []
            for bi, bmeta in enumerate(tm["buckets"]):
                barrs = {k: arrays[f"t{ti}/b{bi}/{k}"]
                         for k in ("td_off", "td_vals", "td_wts",
                                   "td_scal", "mo_vecs",
                                   "cc_vecs")}
                held.append(bucket_from_arrays(bmeta, barrs))
            decoded.append(held)
        with self.lock:
            self.compactions = int(meta.get("compactions", 0))
            self.points_in = float(meta.get("points_in", 0.0))
            self.last_cut = float(meta.get("last_cut", 0.0))
            for tm, tier, held in zip(tiers_meta, self.tiers,
                                      decoded):
                tier.closed_total = int(tm.get("closed_total", 0))
                tier.evicted = int(tm.get("evicted", 0))
                tier.buckets.clear()
                tier.open = None
                if tm.get("open") and held:
                    tier.open = held.pop()
                tier.buckets.extend(held)

    # -- observability ---------------------------------------------------

    def footprint_bytes(self) -> int:
        with self.lock:
            mem = sum(t.stats()["bytes_held"] for t in self.tiers)
        disk = self.store.stats()["pending_bytes"] \
            if self.store is not None else 0
        return int(mem + disk)

    def stats(self) -> dict:
        with self.lock:
            tiers = {t.name: t.stats() for t in self.tiers}
            out = {"tiers": tiers,
                   "compactions": self.compactions,
                   "points_in": self.points_in,
                   "last_cut_unix": self.last_cut,
                   "pending_cuts": len(self._queued),
                   "compact_errors": self.compact_errors,
                   "buckets": int(sum(
                       s["buckets"] + s["open"]
                       for s in tiers.values()))}
        # the spill store's ledger fields flatten to THIS level (zeros
        # when spill is off): the telemetry witness asserts the
        # closure spilled + recovered == expired + dropped + pending
        # directly over /debug/vars -> retention
        store_stats = self.store.stats() if self.store is not None \
            else {k: 0 for k in (
                "pending_buckets", "pending_bytes", "pending_points",
                "spilled_buckets", "spilled_points",
                "recovered_buckets", "recovered_points",
                "expired_buckets", "expired_points",
                "dropped_buckets", "dropped_points", "torn_records",
                "crc_rejected", "io_errors", "reads")}
        out.update(store_stats)
        out["on_disk_bytes"] = store_stats["pending_bytes"]
        out["footprint_bytes"] = self.footprint_bytes()
        return out
