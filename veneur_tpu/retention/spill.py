"""Tier-segment store: on-disk spill for evicted retention buckets.

Buckets evicted from the coarsest in-memory retention tier land here
in the ForwardSpool disk format REUSED VERBATIM (forward/spool.py):
length-prefixed CRC32-framed records appended to bounded segment
files, a torn final record truncated away on reopen, CRC-damaged
records rejected individually.  The framing structs are imported from
the spool module — one disk dialect, two subsystems.

Identity mapping onto the spool header (the record's `ident` triple):

    source    = the tier name ("hour", "day", ...)
    epoch     = the bucket's t_start in unix ms
    chunk_idx = the bucket's DURATION in ms (t_end - t_start; a u32
                holds ~49 days, far past any tier's bucket width)
    n_metrics = the bucket's total sample count

The record body is the bucket's self-describing npz codec
(timeline.encode_bucket_body): per-key digest point clouds, moments
vectors and compactor ladders plus a JSON `__meta__` key table —
bit-exact float round-trip, so a spilled bucket answers queries
identically to its in-memory form.

Unlike the forward spool there is no replayer: spilled buckets are a
READ surface (range queries page them back in), not a delivery queue.
The ledger therefore closes as

    spilled + recovered == expired + dropped + pending

(`recovered` counts records a reopen re-indexed from disk — the
kill -9 durability path; `expired` is the visible byte/age-budget
loss; `dropped` the disk-fault path).  Every counter surfaces at
/debug/vars -> retention and the telemetry witness asserts the
closure (analysis/telemetry.py LEDGERS).
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Optional

from veneur_tpu import failpoints
# the spool's disk dialect, reused verbatim: one frame/header layout
# for every segment file the process writes
from veneur_tpu.forward.spool import _FRAME, _HEADER, _VERSION, \
    encode_record

logger = logging.getLogger("veneur_tpu.retention.spill")

TIER_SEGMENT_PREFIX = "tier-"
TIER_SEGMENT_SUFFIX = ".seg"


def open_tier_segment(path: str):
    """Open (create) a tier segment for appending — paired with
    close_tier_segment on every path (vnlint resource-pairing)."""
    return open(path, "ab")


def close_tier_segment(f, fsync: bool = False) -> None:
    """Flush (optionally fsync) and close a tier segment handle."""
    try:
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    finally:
        f.close()


@dataclass
class TierRecord:
    """One spilled bucket's index entry; the body stays on disk."""
    tier: str
    t_start: float          # bucket bounds, unix seconds
    t_end: float
    ts_ms: int              # spill wall time (header ts)
    n_points: int
    seg_seq: int
    offset: int             # body offset within the segment file
    body_len: int
    disk_bytes: int         # full framed record size


class TierSegmentStore:
    """Bounded on-disk bucket store with crash recovery.

    Thread-safe.  Appends rotate segments at segment_max_bytes; the
    byte budget evicts oldest-first with accounting; `max_age_s > 0`
    additionally expires buckets whose t_end has aged out.  A reopen
    (the kill -9 revive path) re-indexes every intact record."""

    def __init__(self, directory: str, max_bytes: int = 256 << 20,
                 max_age_s: float = 0.0, fsync: str = "rotate",
                 segment_max_bytes: int = 4 << 20):
        self.dir = directory
        self.max_bytes = int(max_bytes)
        self.max_age_s = float(max_age_s)
        self.fsync = fsync
        self.segment_max_bytes = int(segment_max_bytes)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._records: list[TierRecord] = []     # oldest t_start first
        self._seg_pending: dict[int, int] = {}
        self._active = None      # (seq, file handle, bytes written)
        self._next_seq = 0
        self.pending_bytes = 0
        self.pending_points = 0
        self.spilled_buckets = 0
        self.spilled_points = 0
        self.recovered_buckets = 0
        self.recovered_points = 0
        self.expired_buckets = 0
        self.expired_points = 0
        self.dropped_buckets = 0
        self.dropped_points = 0
        self.torn_records = 0
        self.crc_rejected = 0
        self.io_errors = 0
        self.reads = 0
        self._recover()

    # -- recovery (reopen after a crash) --------------------------------

    def _segment_path(self, seq: int) -> str:
        return os.path.join(
            self.dir, f"{TIER_SEGMENT_PREFIX}{seq}{TIER_SEGMENT_SUFFIX}")

    def _recover(self) -> None:
        """Re-index every on-disk segment: intact records re-enter the
        query index (the kill -9 durability contract), a torn tail is
        truncated away, CRC-damaged records are rejected one by one —
        the ForwardSpool recovery discipline on the same framing."""
        seqs = []
        for name in os.listdir(self.dir):
            if name.startswith(TIER_SEGMENT_PREFIX) and \
                    name.endswith(TIER_SEGMENT_SUFFIX):
                try:
                    seqs.append(int(name[len(TIER_SEGMENT_PREFIX):
                                         -len(TIER_SEGMENT_SUFFIX)]))
                except ValueError:
                    continue
        for seq in sorted(seqs):
            path = self._segment_path(seq)
            try:
                good_end = self._scan_segment(seq, path)
            except OSError as e:
                self.io_errors += 1
                logger.error("retention: cannot recover segment %s: %s",
                             path, e)
                continue
            if good_end is not None:
                try:
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
                except OSError:
                    self.io_errors += 1
            if self._seg_pending.get(seq, 0) == 0:
                self._unlink_segment(seq)
        self._next_seq = max(seqs, default=-1) + 1
        self._records.sort(key=lambda r: (r.t_start, r.t_end))

    def _scan_segment(self, seq: int, path: str) -> Optional[int]:
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            if off + _FRAME.size > len(data):
                self.torn_records += 1
                return off
            plen, crc = _FRAME.unpack_from(data, off)
            start = off + _FRAME.size
            if start + plen > len(data):
                self.torn_records += 1
                return off
            payload = data[start:start + plen]
            next_off = start + plen
            if zlib.crc32(payload) != crc:
                self.crc_rejected += 1
                off = next_off
                continue
            try:
                (ver, ts_ms, t0_ms, dur_ms, n_points, _tid, _sid,
                 src_len) = _HEADER.unpack_from(payload, 0)
                tier = payload[_HEADER.size:
                               _HEADER.size + src_len].decode()
                body_off = _HEADER.size + src_len
                rec = TierRecord(
                    tier=tier, t_start=t0_ms / 1e3,
                    t_end=(t0_ms + dur_ms) / 1e3, ts_ms=ts_ms,
                    n_points=n_points, seg_seq=seq,
                    offset=start + body_off,
                    body_len=plen - body_off,
                    disk_bytes=_FRAME.size + plen)
            except (struct.error, UnicodeDecodeError):
                self.crc_rejected += 1
                off = next_off
                continue
            if ver != _VERSION:
                self.crc_rejected += 1
                off = next_off
                continue
            self._records.append(rec)
            self._seg_pending[seq] = self._seg_pending.get(seq, 0) + 1
            self.pending_bytes += rec.disk_bytes
            self.pending_points += rec.n_points
            self.recovered_buckets += 1
            self.recovered_points += rec.n_points
            off = next_off
        return None

    # -- spill (the timeline's eviction path) ---------------------------

    def spill(self, tier: str, t_start: float, t_end: float,
              n_points: int, body: bytes) -> bool:
        """Append one evicted bucket.  Returns False (after counting
        the loss in dropped_*) when disk I/O fails — eviction must
        never wedge the flush path."""
        ts_ms = int(time.time() * 1e3)
        ident = (tier, int(round(t_start * 1e3)),
                 int(round((t_end - t_start) * 1e3)))
        frame = encode_record(ident, body, n_points, ts_ms=ts_ms)
        with self._lock:
            try:
                # vnlint: disable=blocking-propagation (deliberate
                #   failpoint edge: retention.io faults the spill I/O
                #   itself, mirroring the forward spool's spool.io)
                failpoints.inject("retention.io")
                seq, f = self._active_segment_locked(len(frame))
                off = f.tell()
                f.write(frame)
                f.flush()
                if self.fsync == "always":
                    os.fsync(f.fileno())
            except Exception as e:
                self.io_errors += 1
                self.dropped_buckets += 1
                self.dropped_points += n_points
                # the drop is accounted HERE (not by the caller): the
                # evicting tier has already let go of the bucket
                self.spilled_buckets += 1
                self.spilled_points += n_points
                logger.error("retention: spill failed, bucket dropped "
                             "with accounting: %s", e)
                return False
            body_off = off + _FRAME.size + _HEADER.size \
                + len(tier.encode())
            rec = TierRecord(tier=tier, t_start=float(t_start),
                             t_end=float(t_end), ts_ms=ts_ms,
                             n_points=int(n_points), seg_seq=seq,
                             offset=body_off, body_len=len(body),
                             disk_bytes=len(frame))
            self._records.append(rec)
            self._seg_pending[seq] = self._seg_pending.get(seq, 0) + 1
            self.pending_bytes += rec.disk_bytes
            self.pending_points += rec.n_points
            self.spilled_buckets += 1
            self.spilled_points += rec.n_points
            self._enforce_bytes_locked()
        return True

    def _close_active_locked(self, fsync: bool = False) -> None:
        if self._active is None:
            return
        _, f, _ = self._active
        self._active = None
        try:
            close_tier_segment(f, fsync=fsync)
        except OSError:
            self.io_errors += 1

    def _active_segment_locked(self, need: int):
        if self._active is not None:
            seq, f, written = self._active
            if written + need <= self.segment_max_bytes:
                self._active = (seq, f, written + need)
                return seq, f
            self._close_active_locked(fsync=self.fsync != "never")
        seq = self._next_seq
        self._next_seq += 1
        f = open_tier_segment(self._segment_path(seq))
        self._active = (seq, f, need)
        self._seg_pending.setdefault(seq, 0)
        return seq, f

    def _enforce_bytes_locked(self) -> None:
        while self.pending_bytes > self.max_bytes and self._records:
            self._expire_locked(self._records.pop(0))

    def _expire_locked(self, rec: TierRecord) -> None:
        self.pending_bytes -= rec.disk_bytes
        self.pending_points -= rec.n_points
        self.expired_buckets += 1
        self.expired_points += rec.n_points
        left = self._seg_pending.get(rec.seg_seq, 0) - 1
        if left > 0:
            self._seg_pending[rec.seg_seq] = left
            return
        self._seg_pending.pop(rec.seg_seq, None)
        if self._active is not None and self._active[0] == rec.seg_seq:
            self._close_active_locked()
        self._unlink_segment(rec.seg_seq)

    def _unlink_segment(self, seq: int) -> None:
        try:
            os.unlink(self._segment_path(seq))
        except OSError:
            pass
        self._seg_pending.pop(seq, None)

    def expire_now(self, now: Optional[float] = None) -> int:
        """Expire buckets whose t_end has aged past max_age_s (0 =
        keep until the byte budget evicts).  Returns buckets expired."""
        if self.max_age_s <= 0:
            return 0
        cutoff = (time.time() if now is None else now) - self.max_age_s
        n = 0
        with self._lock:
            while self._records and self._records[0].t_end < cutoff:
                self._expire_locked(self._records.pop(0))
                n += 1
        return n

    # -- the range-query read surface -----------------------------------

    def records_overlapping(self, t0: float, t1: float
                            ) -> list[TierRecord]:
        with self._lock:
            return [r for r in self._records
                    if r.t_end > t0 and r.t_start < t1]

    def read_body(self, rec: TierRecord) -> bytes:
        """Page one bucket's codec bytes back in (CRC was verified at
        index time; `retention.io` injects here too)."""
        failpoints.inject("retention.io")
        with open(self._segment_path(rec.seg_seq), "rb") as f:
            f.seek(rec.offset)
            body = f.read(rec.body_len)
        if len(body) != rec.body_len:
            raise OSError(f"short read ({len(body)}/{rec.body_len}) "
                          f"from tier segment {rec.seg_seq}")
        self.reads += 1
        return body

    def pending_buckets(self) -> int:
        with self._lock:
            return len(self._records)

    def close(self, drain: bool = False) -> None:
        """Close the active segment.  `drain` fsyncs the tail out
        (graceful shutdown); a simulated crash passes False and relies
        on the per-append flush."""
        with self._lock:
            self._close_active_locked(
                fsync=drain and self.fsync != "never")

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "pending_buckets": len(self._records),
                "pending_bytes": self.pending_bytes,
                "pending_points": self.pending_points,
                "spilled_buckets": self.spilled_buckets,
                "spilled_points": self.spilled_points,
                "recovered_buckets": self.recovered_buckets,
                "recovered_points": self.recovered_points,
                "expired_buckets": self.expired_buckets,
                "expired_points": self.expired_points,
                "dropped_buckets": self.dropped_buckets,
                "dropped_points": self.dropped_points,
                "torn_records": self.torn_records,
                "crc_rejected": self.crc_rejected,
                "io_errors": self.io_errors,
                "reads": self.reads,
            }
