"""Cube maintenance: group-by dimensions, group budget, overflow row.

The maintainer lives next to the cardinality guard on the ingest edge
and is called under the aggregator lock for every histogram/timer
sample AFTER cardinality resolve: ``rollups()`` returns the extra cube
identities the sample must ALSO land in.  Cube rows are ordinary arena
keys — they flush, forward, and window through the existing machinery
with zero new merge code — so the maintainer's only jobs are (a)
canonical group identity and (b) the per-dimension group budget.

Identity contract (the PR-15 routing-key rule): a cube row's tags are
the dimension's ``tag:value`` pairs plus the ``veneur_cube:true``
marker, joined SORTED.  Every tier — ingest, query, proxy routing —
derives the same string for the same group regardless of the order the
caller listed the tags, so ``group_by=b,a`` and ``group_by=a,b`` hit
the same rows on the same owning global.

Budget contract (the cardinality-guard pattern): at most
``cube_group_budget`` live groups per dimension.  Over-budget groups
degrade into the dimension's ``veneur.cube.other`` row — the samples
still count, visibly, under a reserved identity — while a space-saving
candidate table (seeded fnv1a ranks, lazy min-heap) tracks the hottest
demoted groups; ``end_interval`` promotes candidates that strictly
out-touched the coldest exact groups, releasing the evicted rows
eagerly through the aggregator callback.  Nothing is silently lost:
``rollup_points == exact-group points + overflowed``.
"""
from __future__ import annotations

import fnmatch
import heapq
from typing import Callable, Iterable, Optional

from veneur_tpu.samplers.metric_key import (MetricKey, MetricScope,
                                            fnv1a_64, identity_string)

# Marker tag carried by every cube row: keeps cube identities disjoint
# from real keys (a user metric could otherwise collide with a group
# row) and lets the query plane / testbed enumerate cube rows by a
# plain tag filter.  Reserved like cardinality.ROLLUP_TAG.
CUBE_TAG = "veneur_cube:true"

# The accounted overflow row: one per (dimension, metric type, scope).
# Carries DIM_TAG_PREFIX + the dimension id so operators can see WHICH
# cube is over budget straight from the series tags.
OTHER_NAME = "veneur.cube.other"
DIM_TAG_PREFIX = "veneur_cube_dim:"

# Candidate-table sizing relative to the budget (same shape as the
# guard's bounded candidate state: enough slots to notice a regime
# change, bounded so a group storm cannot grow it).
_CAND_SLACK = 4
_CAND_FLOOR = 256


class CubeDimension:
    """One configured group-by dimension: a sorted tag-name tuple plus
    optional metric-name globs gating which keys it applies to."""

    __slots__ = ("tags", "match", "dim_id", "_prefixes")

    def __init__(self, tags: Iterable[str], match=None):
        names = [str(t) for t in tags]
        if not names:
            raise ValueError("cube dimension needs at least one tag name")
        for t in names:
            if not t or ":" in t or "," in t:
                raise ValueError(
                    f"cube dimension tag name {t!r} invalid: tag names "
                    "must be non-empty and free of ':' and ','")
        if len(set(names)) != len(names):
            raise ValueError(
                f"cube dimension {names} repeats a tag name")
        # SORTED tag names: the dimension id is order-independent just
        # like the group identity it produces
        self.tags = tuple(sorted(names))
        if match is None:
            globs = None
        elif isinstance(match, str):
            globs = (match,)
        else:
            globs = tuple(str(g) for g in match)
            if not globs:
                globs = None
        self.match = globs
        # name-gated dimensions get DISTINCT ids (and so distinct
        # overflow rows): two dimensions may group by the same tags for
        # different metric families, and their budgets/other rows must
        # not collide
        self.dim_id = "|".join(self.tags) + (
            "@" + ";".join(globs) if globs else "")
        self._prefixes = tuple(t + ":" for t in self.tags)

    def matches_name(self, name: str) -> bool:
        if self.match is None:
            return True
        return any(fnmatch.fnmatchcase(name, g) for g in self.match)

    def extract(self, tags: list) -> Optional[list]:
        """The sample's ``tag:value`` pairs for this dimension, or None
        unless the sample carries ALL the dimension's tag names (a
        partial match would smear unrelated series into one group).
        First occurrence wins for duplicated tag names, matching the
        parse-canonicalized (sorted) wire form."""
        out = []
        for pre in self._prefixes:
            for t in tags:
                if t.startswith(pre):
                    out.append(t)
                    break
            else:
                return None
        return out

    def describe(self) -> dict:
        return {"tags": list(self.tags),
                "match": list(self.match) if self.match else None}


def parse_dimensions(raw) -> list:
    """Validate the ``cube_dimensions`` config value: a list whose
    entries are either tag-name lists (``[region, endpoint]``) or dicts
    (``{tags: [...], match: "api.*"}``).  Raises ValueError with the
    offending entry — config loading surfaces it as a boot error."""
    if raw in (None, ()):
        return []
    if not isinstance(raw, (list, tuple)):
        raise ValueError(
            f"cube_dimensions must be a list, got {type(raw).__name__}")
    dims, seen = [], set()
    for ent in raw:
        if isinstance(ent, dict):
            unknown = set(ent) - {"tags", "match"}
            if unknown:
                raise ValueError(
                    f"cube dimension {ent!r}: unknown keys {sorted(unknown)}")
            dim = CubeDimension(ent.get("tags") or (), ent.get("match"))
        elif isinstance(ent, (list, tuple)):
            dim = CubeDimension(ent)
        else:
            raise ValueError(
                f"cube dimension {ent!r} must be a tag list or a dict "
                "with 'tags' (and optional 'match')")
        if dim.dim_id in seen:
            raise ValueError(
                f"cube dimension {list(dim.tags)} declared twice")
        seen.add(dim.dim_id)
        dims.append(dim)
    return dims


def is_cube_tags(tags: Iterable[str]) -> bool:
    return CUBE_TAG in tags


def group_of(tags: Iterable[str]) -> dict:
    """tag-name -> value for a cube row's group tags (markers
    stripped).  The inverse of the identity the maintainer builds."""
    out = {}
    for t in tags:
        if t == CUBE_TAG or t.startswith(DIM_TAG_PREFIX) \
                or t.startswith("veneur_cube_base:"):
            continue
        name, _, val = t.partition(":")
        out[name] = val
    return out


def project_group(jtags: str, keep: Iterable[str]) -> str:
    """Project a cube row's canonical joined-sorted-tags onto a
    coarser tag-name subset — the sub-cube roll-up's group identity
    (``region,endpoint -> region``).  Kept pairs re-join sorted with
    the cube marker, so a projected key equals the key an exact
    coarse dimension would have produced."""
    want = set(keep)
    kept = [t for t in jtags.split(",")
            if t != CUBE_TAG
            and not t.startswith(DIM_TAG_PREFIX)
            and t.partition(":")[0] in want]
    return ",".join(sorted(kept + [CUBE_TAG]))


def match_dimension(dims: list, group_by: list,
                    name: Optional[str] = None) -> Optional[tuple]:
    """Resolve a query's ``group_by`` tag list against the configured
    dimensions: an exact dimension answers directly; otherwise the
    SMALLEST configured superset answers via coarsening (the
    ``region,endpoint -> region`` sub-cube roll-up).  With ``name``,
    only dimensions whose glob gate covers that metric are considered
    (a name-gated sibling dimension holds OTHER metrics' groups).
    Returns ``(dimension, exact)`` or None when no dimension covers
    the request."""
    want = set(group_by)
    cands = [d for d in dims
             if name is None or d.matches_name(name)]
    exact = [d for d in cands if set(d.tags) == want]
    if exact:
        return exact[0], True
    supers = [d for d in cands if want < set(d.tags)]
    if not supers:
        return None
    supers.sort(key=lambda d: (len(d.tags), d.dim_id))
    return supers[0], False


class _DimState:
    """Mutable budget state for one dimension (one guard-tenant's worth
    of machinery: exact groups + space-saving candidates)."""

    __slots__ = ("exact", "cand", "heap", "other")

    def __init__(self):
        self.exact: dict = {}   # dk -> touches this interval
        self.cand: dict = {}    # dk -> [est points, rank]
        self.heap: list = []    # lazy min-heap of (est, rank, dk)
        self.other: dict = {}   # (type, scope) -> overflow identity memo


class CubeMaintainer:
    """Per-aggregator cube state.  All mutating entry points run under
    the aggregator lock (same locking discipline as CardinalityGuard —
    documented in analysis/lock_order_graph.json)."""

    def __init__(self, dimensions: list, group_budget: int,
                 seed: int = 0):
        self.dims = list(dimensions)
        self.budget = int(group_budget)
        self.seed = int(seed)
        self.cand_cap = max(_CAND_SLACK * self.budget, _CAND_FLOOR)
        self._st = [_DimState() for _ in self.dims]
        self._ranks: dict = {}
        # a membership epoch, like the guard's: bumped whenever the
        # exact-group set changes so native row caches keyed on it
        # revalidate
        self.epoch = 0
        # conservation counters (snapshot + /debug/vars):
        # rollup_points == points landed in exact group rows + overflowed
        self.rollup_points = 0
        self.overflowed = 0
        self.groups_admitted = 0
        self.groups_evicted = 0

    # -- identity ---------------------------------------------------------

    def _rank(self, dk) -> int:
        """Deterministic seeded tie-break rank for a group identity —
        the same fnv1a-over-identity_string construction the guard and
        the top-k ranking use."""
        r = self._ranks.get(dk)
        if r is None:
            r = fnv1a_64(identity_string(dk[0], dk[1]), self.seed)
            if len(self._ranks) < 4 * self.cand_cap * max(
                    1, len(self.dims)):
                self._ranks[dk] = r
        return r

    @staticmethod
    def group_identity(name: str, mtype: str, kv_pairs: list,
                       scope: MetricScope) -> tuple:
        """The canonical cube identity for one group: tags are the
        dimension's ``tag:value`` pairs plus the cube marker, joined
        SORTED (order-independence is the routing contract)."""
        ctags = sorted(list(kv_pairs) + [CUBE_TAG])
        return (MetricKey(name, mtype, ",".join(ctags)), scope, ctags)

    def _other_identity(self, st: _DimState, dim: CubeDimension,
                        mtype: str, scope: MetricScope) -> tuple:
        memo = st.other.get((mtype, int(scope)))
        if memo is None:
            ctags = sorted([CUBE_TAG, DIM_TAG_PREFIX + dim.dim_id])
            memo = (MetricKey(OTHER_NAME, mtype, ",".join(ctags)),
                    scope, ctags)
            st.other[(mtype, int(scope))] = memo
        return memo

    # -- ingest edge ------------------------------------------------------

    def rollups(self, key: MetricKey, scope: MetricScope, tags: list,
                n: int = 1) -> list:
        """The cube identities one resolved histogram/timer sample must
        ALSO land in (0..len(dims) of them).  Rollup and cube
        identities themselves never cube again — forwarded cube rows
        arrive on the import path (which does not call this), and a
        local re-materialization here would double-count."""
        out = []
        for t in tags:
            if t == CUBE_TAG or t.startswith("veneur_rollup:"):
                return out
        for di, dim in enumerate(self.dims):
            if not dim.matches_name(key.name):
                continue
            kv = dim.extract(tags)
            if kv is None:
                continue
            ckey, cscope, ctags = self.group_identity(
                key.name, key.type, kv, scope)
            dk = (ckey, scope)
            st = self._st[di]
            self.rollup_points += n
            if dk in st.exact:
                st.exact[dk] += n
                out.append((ckey, cscope, ctags))
            elif len(st.exact) < self.budget:
                st.exact[dk] = n
                self.groups_admitted += 1
                self.epoch += 1
                out.append((ckey, cscope, ctags))
            else:
                self._touch_candidate(st, dk, n)
                self.overflowed += n
                out.append(self._other_identity(st, dim, key.type, scope))
        return out

    def _touch_candidate(self, st: _DimState, dk, n: int) -> None:
        ent = st.cand.get(dk)
        if ent is None:
            if len(st.cand) >= self.cand_cap:
                evicted = self._pop_min_candidate(st)
                if evicted is None:
                    return
                # space-saving substitution: the newcomer inherits the
                # evicted minimum's estimate (classic over-estimate
                # bound, never an undercount)
                base = evicted[0]
            else:
                base = 0
            ent = st.cand[dk] = [base + n, self._rank(dk)]
        else:
            ent[0] += n
        heapq.heappush(st.heap, (ent[0], ent[1], dk))
        if len(st.heap) > _CAND_SLACK * len(st.cand) + 64:
            self._compact_heap(st)

    def _pop_min_candidate(self, st: _DimState):
        while st.heap:
            est, rank, dk = heapq.heappop(st.heap)
            ent = st.cand.get(dk)
            if ent is not None and ent[0] == est:
                del st.cand[dk]
                return ent
        return None

    def _compact_heap(self, st: _DimState) -> None:
        st.heap = [(ent[0], ent[1], dk) for dk, ent in st.cand.items()]
        heapq.heapify(st.heap)

    # -- interval boundary ------------------------------------------------

    def end_interval(self, evict_cb: Callable[[list], None]) -> None:
        """Promotion pass, after the flush snapshot reset the arenas:
        candidates that STRICTLY out-touched the coldest exact groups
        this interval swap in (two-pointer, hottest candidate vs
        coldest exact; rank breaks ties deterministically).  Evicted
        group rows release eagerly via ``evict_cb`` — the same
        ``arena.evict`` failpoint edge as the guard, so a fault there
        aborts with the cube state untouched."""
        for st in self._st:
            if not st.exact and not st.cand:
                continue
            swaps: list = []
            if st.cand:
                hot = sorted(
                    ((ent[0], ent[1], dk) for dk, ent in st.cand.items()),
                    key=lambda e: (-e[0], e[1]))
                cold = sorted(
                    ((cnt, self._rank(dk), dk)
                     for dk, cnt in st.exact.items()),
                    key=lambda e: (e[0], e[1]))
                for ci, (est, rank, dk) in enumerate(hot):
                    if ci >= len(cold) or est <= cold[ci][0]:
                        break
                    swaps.append((cold[ci][2], dk))
            if swaps:
                # release FIRST: a fault on the arena.evict edge aborts
                # the pass with the membership untouched (reclamation
                # is delayed one interval, never corrupted)
                evict_cb([out for out, _ in swaps])  # may raise
                for out, inn in swaps:
                    del st.exact[out]
                    st.exact[inn] = 0
                self.groups_evicted += len(swaps)
                self.epoch += 1
            # interval-local decay: both touch ledgers restart so one
            # hot interval cannot pin membership forever
            for dk in st.exact:
                st.exact[dk] = 0
            st.cand.clear()
            st.heap = []

    # -- introspection / persistence --------------------------------------

    def top_groups(self, di: int, k: int) -> list:
        """The dimension's live group identities, hottest first with
        the seeded rank as the deterministic tie-break (the top-k
        candidate machinery's ordering)."""
        st = self._st[di]
        rows = sorted(
            ((cnt, self._rank(dk), dk) for dk, cnt in st.exact.items()),
            key=lambda e: (-e[0], e[1]))
        return [dk for _, _, dk in rows[:k]]

    def snapshot(self) -> dict:
        """/debug/vars view (no arena walks, O(dims))."""
        return {
            "budget": self.budget,
            "seed": self.seed,
            "epoch": self.epoch,
            "groups": sum(len(st.exact) for st in self._st),
            "rollup_points": self.rollup_points,
            "overflowed": self.overflowed,
            "groups_admitted": self.groups_admitted,
            "groups_evicted": self.groups_evicted,
            "dimensions": [
                dict(dim.describe(), dim_id=dim.dim_id,
                     groups=len(st.exact), candidates=len(st.cand))
                for dim, st in zip(self.dims, self._st)],
        }

    def checkpoint_state(self) -> dict:
        """Durable membership (identities only — counts are
        interval-local and restart at zero, like the guard's)."""
        return {
            "v": 1,
            "counters": [self.rollup_points, self.overflowed,
                         self.groups_admitted, self.groups_evicted],
            "exact": [
                [[dk[0].name, dk[0].type, dk[0].joined_tags, int(dk[1])]
                 for dk in st.exact]
                for st in self._st],
        }

    def restore_state(self, state: dict) -> None:
        if not state or state.get("v") != 1:
            return
        ctrs = state.get("counters") or [0, 0, 0, 0]
        (self.rollup_points, self.overflowed,
         self.groups_admitted, self.groups_evicted) = (
            int(ctrs[0]), int(ctrs[1]), int(ctrs[2]), int(ctrs[3]))
        for st, rows in zip(self._st, state.get("exact") or []):
            st.exact.clear()
            for name, mtype, jtags, scope in rows[:self.budget]:
                dk = (MetricKey(name, mtype, jtags),
                      MetricScope(int(scope)))
                st.exact[dk] = 0
        self.epoch += 1
