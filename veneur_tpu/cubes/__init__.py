"""Tag-dimensional analytics: group-by sketch cubes.

A *cube* is a config-declared set of group-by dimensions (lists of tag
names, optionally gated by metric-name globs).  Every histogram/timer
sample whose tags carry ALL of a dimension's tag names is mirrored into
a per-group rollup row — an ordinary mergeable arena key, so a moments
group merge is one vector add and a digest group merge reuses the
staged-COO path, and the rows forward/flush/window exactly like any
other key.  Group identity is canonicalized through the shared
``identity_string``/fnv1a machinery with SORTED tag values, and bounded
by a per-dimension group budget that degrades overflow into an
accounted ``veneur.cube.other`` row (the cardinality-guard pattern — no
silent loss).
"""

from veneur_tpu.cubes.cube import (  # noqa: F401
    CUBE_TAG,
    DIM_TAG_PREFIX,
    OTHER_NAME,
    CubeDimension,
    CubeMaintainer,
    group_of,
    is_cube_tags,
    match_dimension,
    parse_dimensions,
    project_group,
)
