"""Self-profiling subsystem: host CPU profiler, data-plane stage
accounting, and the flush timeline.

The observability layer the reference exposes as its `/debug/pprof` suite
(`server.go:1366-1383`, SURVEY §5.1), rebuilt for this runtime's three
hot planes:

  * **Host CPU** (`profiling/cpu.py`): a sampling profiler behind
    `/debug/pprof/profile?seconds=N` — py-spy subprocess when the binary
    is present (samples the interpreter AND native frames), else an
    in-process `sys._current_frames()` sampler — returning folded-stack
    text ready for `flamegraph.pl` / speedscope.
  * **C++ data plane** (`native/ingest_engine.cpp` stage counters, bound
    in `veneur_tpu/ingest`): per-thread, per-stage packet and nanosecond
    counters over recvmmsg -> parse -> intern -> stage -> drain,
    surfaced as monotonic counters under `/debug/vars` and driven to
    saturation by `scripts/ingest_ceiling.py`.
  * **Flush path** (`profiling/timeline.py`): a fixed-size ring of
    structured per-flush records (interval id, segment milliseconds,
    key/device counts, bytes moved) queryable at
    `/debug/flush_timeline`, so the segment decomposition the bench
    emits is observable on a live server.

Everything here is stdlib-only and safe to import from the server's hot
path; the expensive pieces (py-spy, the sampler thread) run only while a
profile request is in flight.
"""

from veneur_tpu.profiling.cpu import CpuProfiler, profile_cpu
from veneur_tpu.profiling.timeline import FlushRecord, FlushTimeline

# Data-plane stage names, in pipeline order.  The first four are
# per-reader-thread (the C++ engine accounts them per thread); drain is
# engine-level (it runs on the Python drainer thread).
STAGES = ("recvmmsg", "parse", "intern", "stage", "drain")

# The unit each stage counts in (its counter key next to "ns").  Drain
# additionally reports "calls" (consolidation passes).  Consumers
# (ingest.stage_stats, bench.py, scripts/ingest_ceiling.py) are
# table-driven off this so a stage rename/addition has one home.
STAGE_UNITS = {"recvmmsg": "packets", "parse": "packets",
               "intern": "calls", "stage": "values", "drain": "packets"}

__all__ = ["CpuProfiler", "profile_cpu", "FlushRecord", "FlushTimeline",
           "STAGES", "STAGE_UNITS"]
