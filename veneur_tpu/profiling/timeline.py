"""Flush timeline: a fixed-size ring of structured per-flush records.

PR 1 rebuilt the flush launch path and made the bench emit a segment
decomposition (layout/dispatch/collective/readback) — but only the bench
could see it.  This ring makes the same decomposition observable on a
LIVE server: `core/server.py` appends one record per flush from the
aggregator's measured `last_flush_segments`, and `/debug/flush_timeline`
serves the ring as JSON.  The records double as the raw material for the
t-digest accuracy dossier (each carries the interval's key counts and
bytes moved alongside the timings).

Appends are O(1) under a lock and allocate one small dict per flush;
with the default capacity (512 records ≈ 85 minutes at a 10 s interval)
the ring holds a few hundred KiB.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

DEFAULT_CAPACITY = 512


class FlushRecord(dict):
    """One flush's structured record.  A dict subclass (not a dataclass)
    so the segment set can grow without a schema migration — the
    aggregator's measured segments vary by tier (meshed flushes have no
    per-chunk layout split; idle intervals have no device segment)."""

    REQUIRED = ("interval", "unix_ts", "total_ms")


def record_from_segments(interval: int, unix_ts: float, total_s: float,
                         segments: Optional[dict] = None,
                         devices: int = 1, **extra) -> FlushRecord:
    """Build a FlushRecord from the aggregator's `last_flush_segments`:
    `*_s` second segments become `*_ms` milliseconds, byte/count gauges
    pass through unchanged."""
    rec = FlushRecord(interval=int(interval),
                      unix_ts=round(float(unix_ts), 3),
                      total_ms=round(total_s * 1e3, 3),
                      devices=int(devices))
    for name, v in (segments or {}).items():
        if not isinstance(v, (int, float)):
            # structured sub-records (the chunked pipeline's per-chunk
            # upload/dispatch/drain/wait stats) are trace material —
            # the flight recorder lays them as spans; the timeline row
            # keeps only their count
            if name == "device_chunks":
                rec["device_chunks"] = len(v)
            continue
        if name.endswith("_s"):
            rec[name[:-2] + "_ms"] = round(float(v) * 1e3, 3)
        else:
            rec[name] = int(v) if float(v).is_integer() else float(v)
    for name, v in extra.items():
        if v is not None:
            rec[name] = v
    return rec


class FlushTimeline:
    """Thread-safe bounded ring of FlushRecords (newest last)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.total_recorded = 0

    def append(self, rec: FlushRecord) -> None:
        with self._lock:
            self._ring.append(rec)
            self.total_recorded += 1

    def record(self, interval: int, unix_ts: float, total_s: float,
               segments: Optional[dict] = None, devices: int = 1,
               **extra) -> FlushRecord:
        """Build + append in one call (the server's per-flush hook)."""
        rec = record_from_segments(interval, unix_ts, total_s,
                                   segments, devices, **extra)
        self.append(rec)
        return rec

    def snapshot(self, last: Optional[int] = None) -> list[dict]:
        """Newest-last copy of the ring (optionally only the last N)."""
        with self._lock:
            recs = list(self._ring)
        if last is not None and last >= 0:
            recs = recs[-last:] if last else []
        return [dict(r) for r in recs]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
