"""Host CPU sampling profiler -> folded-stack text.

The Python analog of the reference's `/debug/pprof/profile` (net/http/pprof
wired in `server.go:1366-1383`): sample what the host process is doing for
N seconds and hand back something a flamegraph renders directly.

Two backends, picked at call time:

  * **py-spy** (subprocess, when the binary is on PATH): samples the
    interpreter from OUTSIDE the process, so it sees native frames and is
    immune to GIL skew.  `py-spy record --format raw` already emits
    folded stacks.
  * **in-process sampler** (always available): a background thread walks
    `sys._current_frames()` at the configured rate and aggregates folded
    stacks per thread.  This is the `setitimer`/cProfile-class fallback —
    pure stdlib, no signal handler (signals only reach the main thread in
    CPython, which would blind the profile to the reader/flush threads
    that actually matter here), and safe to run inside a serving process.

Output format (both backends): one stack per line, frames root-first
joined by ';', a space, then the sample count —

    thread:ingest-drain;server.py:_native_drain_loop;... 42

which is exactly what `flamegraph.pl` / speedscope / pprof's folded
importer consume.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from collections import Counter
from typing import Optional

# One profile at a time per process: overlapping samplers would double
# the sampling overhead and interleave py-spy subprocesses.
_profile_lock = threading.Lock()

DEFAULT_HZ = 100
MAX_HZ = 1000
MAX_STACK_DEPTH = 64


def _frame_name(frame) -> str:
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


def _thread_names() -> dict[int, str]:
    return {t.ident: t.name for t in threading.enumerate()
            if t.ident is not None}


class CpuProfiler:
    """In-process sampling profiler over `sys._current_frames()`.

    Collects folded stacks for every live thread; the sampling thread
    excludes itself.  Sampling is cooperative with the GIL: a thread
    blocked in a C extension that released the GIL (recvmmsg readers,
    device waits) shows its last Python frame — which is the right
    attribution for "what is the HOST interpreter spending time on".
    """

    def __init__(self, hz: int = DEFAULT_HZ):
        self.hz = max(1, min(int(hz), MAX_HZ))
        self.samples: Counter = Counter()
        self.sample_count = 0

    def _sample_once(self, own_ident: Optional[int]) -> None:
        names = _thread_names()
        for ident, frame in sys._current_frames().items():
            if ident == own_ident:
                continue
            stack = []
            depth = 0
            while frame is not None and depth < MAX_STACK_DEPTH:
                stack.append(_frame_name(frame))
                frame = frame.f_back
                depth += 1
            stack.append("thread:" + names.get(ident, str(ident)))
            # frames were collected leaf-first; folded format is
            # root-first
            self.samples[";".join(reversed(stack))] += 1
        self.sample_count += 1

    def run(self, seconds: float) -> str:
        """Sample for `seconds`, then return the folded-stack text."""
        period = 1.0 / self.hz
        own = threading.get_ident()
        deadline = time.perf_counter() + seconds
        next_tick = time.perf_counter()
        while True:
            now = time.perf_counter()
            if now >= deadline:
                break
            self._sample_once(own)
            next_tick += period
            delay = next_tick - time.perf_counter()
            if delay > 0:
                time.sleep(min(delay, deadline - now))
            else:
                next_tick = time.perf_counter()  # fell behind; re-anchor
        return self.folded()

    def folded(self) -> str:
        return "".join(f"{stack} {n}\n"
                       for stack, n in sorted(self.samples.items()))


def _pyspy_profile(seconds: float, hz: int) -> Optional[str]:
    """Shell out to py-spy against our own pid; None if unavailable or
    it failed (no ptrace permission, unsupported interpreter, ...)."""
    binary = shutil.which("py-spy")
    if binary is None:
        return None
    fd, path = tempfile.mkstemp(prefix="veneur-pyspy-", suffix=".folded")
    os.close(fd)
    try:
        proc = subprocess.run(
            [binary, "record", "--pid", str(os.getpid()),
             "--duration", str(max(1, int(round(seconds)))),
             "--rate", str(hz), "--format", "raw", "--output", path,
             "--nonblocking"],
            capture_output=True, timeout=seconds + 30.0)
        if proc.returncode != 0:
            return None
        with open(path) as f:
            text = f.read()
        return text if text.strip() else None
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def profile_cpu(seconds: float, hz: int = DEFAULT_HZ,
                use_pyspy: bool = True) -> tuple[str, str]:
    """Profile this process's CPU for `seconds`; returns
    (folded_stack_text, backend) where backend is "py-spy" or
    "sampler".  Serialized process-wide: concurrent callers queue."""
    hz = max(1, min(int(hz), MAX_HZ))
    with _profile_lock:
        if use_pyspy:
            text = _pyspy_profile(seconds, hz)
            if text is not None:
                return text, "py-spy"
        return CpuProfiler(hz).run(seconds), "sampler"
