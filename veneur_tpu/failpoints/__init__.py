"""Failpoint registry: deterministic fault injection for the testbed.

The in-process analog of the freebsd/etcd `failpoint` facility
(SNIPPETS-adjacent idiom; gofail's `// gofail:` markers): production code
calls `failpoints.inject("forward.send")` at the few seams where a
distributed deployment actually fails — the forward edge, the proxy's
per-destination sends, connect/dial, the flush path — and the call is a
single module-global boolean check unless a test/chaos harness has armed
that name.  Armed failpoints execute one of four actions with SEEDED
determinism, so a chaos arm replays bit-identically:

  drop          raise FailpointDrop (the request vanishes before the wire;
                call sites treat it as a retryable transport loss)
  delay         sleep `delay_s`, then proceed normally
  grpc-error    raise FailpointRpcError(code) — a real grpc.RpcError
                subclass, so existing `except grpc.RpcError` handling and
                status-code triage see it exactly like a peer's failure
  stream-reset  grpc-error with code UNAVAILABLE and reset details (the
                shape of a mid-stream RST / GOAWAY)

Arming is scoped: `configure()` returns the Failpoint (counters included),
`clear()` disarms everything, and `active()` is a context manager for
tests.  Disabled cost: one global bool read per inject() call.

Injection sites threaded through this repo (grep `failpoints.inject`):

  forward.send        per forward attempt      (forward/client.py)
  forward.v2_stream   per V2 fan-out stream    (forward/client.py)
  proxy.connect       Destination dial         (proxy/connect.py)
  proxy.send_batch    per V1 chunk RPC         (proxy/connect.py)
  proxy.stream        V2 sender stream         (proxy/connect.py)
  destinations.add    Destinations._connect    (proxy/destinations.py)
  destinations.reshard  top of a two-phase reshard window, before any
                      membership mutation      (proxy/destinations.py)
  arena.evict         the cardinality eviction pass, before any arena
                      row is released — a fault here aborts the pass
                      with quota state intact  (core/aggregator.py)
  server.flush        top of the flush path    (core/server.py)
  server.sigstop_window  top of the global tier's V1 import handler
                      (sources/proxy.py): a `delay` action freezes the
                      handler for a bounded window — the in-process
                      twin of a SIGSTOP'd global (the RPC neither
                      refuses nor resets, it just hangs past the
                      sender's deadline, then completes), so the fast
                      tier-1 cell exercises the frozen-peer deadline +
                      dedup path without real signals
  spool.io            durable-spool disk I/O: the spill append (write/
                      fsync) and the replay read — a fault degrades to
                      drop-with-accounting, never a wedged forward
                      thread           (forward/spool.py)
  egress.sink         per metric-sink delivery attempt on the egress
                      lanes (initial attempts AND spool replays) — the
                      sink-blackhole chaos arm's edge: error/delay/drop
                      actions drive breaker trips, spool spill and
                      recovery replay   (egress/plane.py)
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Optional

import grpc

_ENABLED = False          # fast-path gate: read without a lock
_registry: dict[str, "Failpoint"] = {}
_lock = threading.Lock()

ACTIONS = ("drop", "delay", "grpc-error", "stream-reset")


class FailpointDrop(Exception):
    """The injected request vanished before reaching the wire (packet-loss
    shape).  Nothing was delivered: safe to retry."""

    def __init__(self, name: str):
        super().__init__(f"failpoint {name!r}: dropped")
        self.failpoint = name


class FailpointRpcError(grpc.RpcError):
    """An injected RPC failure carrying a real grpc StatusCode, so call
    sites' `except grpc.RpcError` + `.code()` triage is exercised
    verbatim."""

    def __init__(self, name: str, code: grpc.StatusCode,
                 details: str = ""):
        super().__init__()
        self.failpoint = name
        self._code = code
        self._details = details or f"failpoint {name!r}: injected {code}"

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details

    def __str__(self) -> str:
        return f"<FailpointRpcError {self._code} {self._details!r}>"


class Failpoint:
    """One armed failpoint.  Counters are cumulative for the arm's
    lifetime; `evaluated` counts inject() passes through this name,
    `fired` counts the times the action actually executed."""

    def __init__(self, name: str, action: str, *,
                 code: str = "UNAVAILABLE", delay_s: float = 0.0,
                 prob: float = 1.0, times: Optional[int] = None,
                 after: int = 0, seed: int = 0):
        if action not in ACTIONS:
            raise ValueError(f"unknown failpoint action {action!r} "
                             f"(want one of {ACTIONS})")
        self.name = name
        self.action = action
        self.code = getattr(grpc.StatusCode, code)
        self.delay_s = float(delay_s)
        self.prob = float(prob)
        self.times = times          # None = unlimited
        self.after = int(after)     # skip the first `after` evaluations
        self.seed = seed
        self._rng = random.Random(seed)
        self._flock = threading.Lock()
        self.evaluated = 0
        self.fired = 0

    def _should_fire(self) -> bool:
        with self._flock:
            self.evaluated += 1
            if self.evaluated <= self.after:
                return False
            if self.times is not None and self.fired >= self.times:
                return False
            if self.prob < 1.0 and self._rng.random() >= self.prob:
                return False
            self.fired += 1
            return True

    def evaluate(self) -> None:
        if not self._should_fire():
            return
        if self.action == "delay":
            time.sleep(self.delay_s)
            return
        if self.action == "drop":
            raise FailpointDrop(self.name)
        if self.action == "stream-reset":
            raise FailpointRpcError(
                self.name, grpc.StatusCode.UNAVAILABLE,
                f"failpoint {self.name!r}: stream reset")
        raise FailpointRpcError(self.name, self.code)

    def snapshot(self) -> dict:
        with self._flock:
            return {"action": self.action, "evaluated": self.evaluated,
                    "fired": self.fired,
                    "times": self.times, "prob": self.prob}


def inject(name: str) -> None:
    """The production-code hook.  A single global bool read when nothing
    is armed; otherwise evaluates the named failpoint (missing names are
    still no-ops, so sites can be added freely)."""
    if not _ENABLED:
        return
    fp = _registry.get(name)
    if fp is not None:
        fp.evaluate()


def configure(name: str, action: str, **kwargs) -> Failpoint:
    """Arm `name` with `action` (see ACTIONS); returns the Failpoint so
    callers can read its counters.  Re-configuring a name replaces it."""
    global _ENABLED
    fp = Failpoint(name, action, **kwargs)
    with _lock:
        _registry[name] = fp
        _ENABLED = True
    return fp


def disarm(name: str) -> None:
    global _ENABLED
    with _lock:
        _registry.pop(name, None)
        if not _registry:
            _ENABLED = False


def clear() -> None:
    """Disarm everything (test teardown)."""
    global _ENABLED
    with _lock:
        _registry.clear()
        _ENABLED = False


def stats() -> dict[str, dict]:
    with _lock:
        return {n: fp.snapshot() for n, fp in _registry.items()}


@contextlib.contextmanager
def active(name: str, action: str, **kwargs):
    """`with failpoints.active("forward.send", "drop", times=2) as fp:`
    — arms for the block, disarms on exit (other armed names are kept)."""
    fp = configure(name, action, **kwargs)
    try:
        yield fp
    finally:
        disarm(name)
