"""Source interfaces and plugin registry.

Mirrors `sources/sources.go:1-19`: a Source is a pluggable pull/push input
with `Start(Ingest)` / `Stop()`; `Ingest` accepts parsed UDPMetrics (and,
for the gRPC import path, forwarded protobuf metrics).  The registry map
parallels `SourceTypes` (`server.go:62-90`), filled from the YAML
`sources` list.
"""

from __future__ import annotations

from typing import Any, Callable, Protocol, runtime_checkable

from veneur_tpu.samplers.metric_key import UDPMetric


@runtime_checkable
class Ingest(Protocol):
    def ingest_metric(self, m: UDPMetric) -> None: ...


@runtime_checkable
class Source(Protocol):
    def name(self) -> str: ...
    def start(self, ingest: Ingest) -> None: ...
    def stop(self) -> None: ...


SOURCE_TYPES: dict[str, Callable[..., Any]] = {}


def register_source(kind: str):
    def deco(factory):
        SOURCE_TYPES[kind] = factory
        return factory
    return deco


def create_source(spec, server_config=None):
    factory = SOURCE_TYPES.get(spec.kind)
    if factory is None:
        raise ValueError(f"unknown source kind {spec.kind!r}")
    return factory(spec, server_config)


# registration imports at the bottom (modules decorate with the registry)
from veneur_tpu.sources import openmetrics as _openmetrics  # noqa: E402,F401
from veneur_tpu.sources import mock as _mock  # noqa: E402,F401
