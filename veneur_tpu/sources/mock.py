"""Recording source double (capability twin of `sources/mock/`)."""

from __future__ import annotations

from veneur_tpu import sources as sources_mod


class MockSource:
    KIND = "mock"

    def __init__(self, spec=None, server_config=None):
        self._name = getattr(spec, "name", "") or self.KIND
        self.started = False
        self.stopped = False
        self.ingest = None

    def name(self) -> str:
        return self._name

    def start(self, ingest) -> None:
        self.started = True
        self.ingest = ingest

    def stop(self) -> None:
        self.stopped = True


sources_mod.register_source("mock")(MockSource)
