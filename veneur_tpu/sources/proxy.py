"""gRPC import source: the global tier's receive path.

Mirrors `sources/proxy/server.go`: a Forward service whose
`SendMetricsV2` recv-loop feeds each metric into the aggregation core
(`server.go:144-162` -> `ingest.IngestMetricProto` -> worker
`ImportMetric`), registered when `grpc_address` is configured
(`server.go:673-682`).  `SendMetrics` (V1) — which the reference leaves
UNIMPLEMENTED (`sources/proxy/server.go:138-142`) — is implemented here
as the fleet-internal batch import fast path: a strict superset, since
reference senders only ever call V2, while this framework's
proxies/forwarders probe V1 and fall back to V2 against reference
globals (python-grpc streams cap at ~20k msgs/s).

Also exposes the gRPC ingest listeners for SSF spans and raw dogstatsd
packet bytes (`networking.go:326-391`).
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import logging
import threading
import time
from typing import Callable, Optional

import grpc
from google.protobuf import empty_pb2

from veneur_tpu.forward import convert
from veneur_tpu.protocol import (dogstatsd_grpc_pb2, forward_pb2, metric_pb2,
                                 ssf_grpc_pb2, ssf_pb2)

logger = logging.getLogger("veneur_tpu.sources.proxy")


class DedupLedger:
    """Bounded per-source ledger of imported chunk identities — the
    receiving half of the exactly-once contract (forward/client.py
    CHUNK_ID_KEY).  A chunk delivered both directly and via spool
    replay (an ambiguous timeout, a sender crash mid-ack, a receiver
    crash after import) merges ONCE: the second delivery is recognized
    and skipped.

    Concurrency: `run_once(ident, import_fn)` RESERVES the identity
    under the ledger condition (O(1)), runs the import OUTSIDE it —
    concurrent V1 payloads keep parsing in parallel; only the
    aggregator-lock merge serializes, as before — and un-reserves on
    import failure so a failed delivery can retry.  Reservation at
    entry also makes two concurrent deliveries of the SAME chunk merge
    once.  The checkpoint writer takes `paused()` around its snapshot:
    new imports block and in-flight ones drain first, so a checkpoint
    can never capture a chunk's data without its ledger entry (or vice
    versa) — restore replays stay exact, not approximate.  The window
    is a per-source FIFO (`window` identities, oldest evicted), sized
    far beyond any spool's pending depth."""

    def __init__(self, window: int = 4096):
        self.window = max(16, int(window))
        self._cond = threading.Condition()
        # source -> (deque of idents in arrival order, set for O(1))
        self._sources: dict = {}
        self._active = 0          # imports between reserve and finish
        self._inflight: set = set()   # reserved idents not yet settled
        self._paused = False      # checkpoint cut in progress
        self.recorded = 0
        self.duplicates = 0

    def _seen_locked(self, ident: tuple) -> bool:
        entry = self._sources.get(ident[0])
        return entry is not None and ident in entry[1]

    def _record_locked(self, ident: tuple) -> None:
        entry = self._sources.get(ident[0])
        if entry is None:
            import collections
            entry = self._sources[ident[0]] = (collections.deque(), set())
        dq, seen = entry
        if ident in seen:
            return
        dq.append(ident)
        seen.add(ident)
        if len(dq) > self.window:
            seen.discard(dq.popleft())
        self.recorded += 1

    def _unrecord_locked(self, ident: tuple) -> None:
        entry = self._sources.get(ident[0])
        if entry is None or ident not in entry[1]:
            return
        entry[1].discard(ident)
        try:
            entry[0].remove(ident)
        except ValueError:
            pass
        self.recorded -= 1

    def run_once(self, ident, import_fn):
        """Execute `import_fn()` exactly once per identity.  Returns
        (result, duplicate): on a duplicate the import is skipped and
        result is None.  ident=None (an unidentified sender) always
        imports (still draining through the pause gate so the
        checkpoint cut covers every in-flight import)."""
        with self._cond:
            if ident is None:
                while self._paused:
                    self._cond.wait()
            else:
                # wait out BOTH a checkpoint cut and any in-flight
                # import of this same identity — a duplicate must not
                # be acked as success while the original could still
                # fail (the spool would settle the record and the
                # chunk would be lost silently)
                while self._paused or ident in self._inflight:
                    self._cond.wait()
                if self._seen_locked(ident):
                    # recorded AND no longer in flight = the original
                    # import completed successfully
                    self.duplicates += 1
                    logger.info("dedup: skipping duplicate chunk %s",
                                ident)
                    return None, True
                # reserve NOW: a concurrent duplicate delivery of the
                # same chunk parks on _inflight above
                self._record_locked(ident)
                self._inflight.add(ident)
            self._active += 1
        try:
            result = import_fn()
        except BaseException:
            with self._cond:
                if ident is not None:
                    # failed import: allow the sender's retry/replay
                    self._unrecord_locked(ident)
                    self._inflight.discard(ident)
                self._active -= 1
                self._cond.notify_all()
            raise
        with self._cond:
            if ident is not None:
                self._inflight.discard(ident)
            self._active -= 1
            self._cond.notify_all()
        return result, False

    @contextlib.contextmanager
    def paused(self):
        """The checkpoint cut: block new imports and drain in-flight
        ones, so ledger + aggregator snapshot as one coherent state."""
        with self._cond:
            while self._paused:      # one cut at a time
                self._cond.wait()
            self._paused = True
            while self._active > 0:
                self._cond.wait()
        try:
            yield
        finally:
            with self._cond:
                self._paused = False
                self._cond.notify_all()

    # -- checkpoint plumbing (core/server.py) ------------------------------

    def snapshot(self) -> dict:
        """JSON-able state for the crash checkpoint.  Callers that need
        the import-atomic cut (checkpoint_now) wrap this AND the
        aggregator snapshot in `paused()`."""
        with self._cond:
            return {
                "window": self.window,
                "sources": {
                    src: [[s, int(e), int(i)] for (s, e, i) in dq]
                    for src, (dq, _) in self._sources.items()},
            }

    def restore(self, state: dict) -> None:
        with self._cond:
            for src, idents in (state.get("sources") or {}).items():
                for s, e, i in idents:
                    self._record_locked((str(s), int(e), int(i)))

    def stats(self) -> dict:
        with self._cond:
            return {"recorded": self.recorded,
                    "duplicates": self.duplicates,
                    "sources": len(self._sources),
                    "window": self.window}


class GrpcImportServer:
    """Hosts forwardrpc.Forward (+ optional SSF/dogstatsd ingest) on one
    grpc.Server."""

    def __init__(self, address: str,
                 import_metric: Optional[Callable[[object], None]] = None,
                 ingest_span: Optional[Callable[[object], None]] = None,
                 handle_packet: Optional[Callable[[bytes], None]] = None,
                 max_workers: int = 64,
                 server_credentials: Optional[grpc.ServerCredentials] = None,
                 import_payload: Optional[Callable] = None,
                 trace_hook: Optional[Callable] = None,
                 dedup: Optional[DedupLedger] = None):
        """With import_metric=None the Forward service is omitted — the
        ingest-only shape of `grpc_listen_addresses` edge listeners
        (StartGRPC, networking.go:326-391), vs the global tier's
        `grpc_address` which serves all three.  import_payload, when
        provided, takes the whole V1 MetricList as RAW BYTES in one
        call (native wire scan + single aggregator lock — the
        fleet-rate inbound path).  trace_hook(ctxs, n_metrics,
        start_ns, transport) receives the propagated trace contexts of
        each import RPC (veneur_tpu/trace/recorder.py metadata dialect)
        so the server can continue the sender's flush trace with an
        import span."""
        self.import_metric = import_metric
        self.import_payload = import_payload
        self.ingest_span = ingest_span
        self.handle_packet = handle_packet
        self.trace_hook = trace_hook
        self.dedup = dedup
        self.imported_count = 0
        # metrics that arrived but failed to import (malformed pb,
        # aggregator rejection): visible loss, part of the import-edge
        # ledger (surfaced at /debug/vars -> import_errors_total and as
        # the import.errors_total series)
        self.import_errors = 0
        self._count_lock = threading.Lock()
        # Each long-lived client stream (a proxy destination keeps 8 of
        # them open per global, proxy/connect.py) pins one worker thread
        # for its lifetime, so the pool is sized for a fleet of proxies
        # plus per-flush forward streams, not for short RPCs.
        self.server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="grpc-import"))
        self.server.add_generic_rpc_handlers([self._make_handlers()])
        if server_credentials is not None:
            self.port = self.server.add_secure_port(address,
                                                    server_credentials)
        else:
            self.port = self.server.add_insecure_port(address)
        if self.port == 0:
            # grpc returns 0 instead of raising; fail startup like the
            # reference's net.Listen error path (server.go:673-682)
            raise OSError(f"could not bind gRPC import server to {address}")

    # -- service wiring ----------------------------------------------------

    def _make_handlers(self):
        def _trace_ctxs(context):
            """Propagated trace contexts on this RPC, [] when the
            sender is untraced (or no hook is installed)."""
            if self.trace_hook is None:
                return []
            from veneur_tpu.trace import recorder as trace_rec
            return trace_rec.extract_contexts(
                context.invocation_metadata())

        def _chunk_ident(context):
            """The sender's chunk identity on this RPC, or None for an
            unidentified sender (reference veneurs, V2 streams)."""
            from veneur_tpu.forward.client import (CHUNK_ID_KEY,
                                                   parse_chunk_id)
            for entry in (context.invocation_metadata() or ()):
                try:
                    if entry[0] == CHUNK_ID_KEY:
                        return parse_chunk_id(entry[1])
                # vnlint: disable=silent-loss (a malformed metadata
                #   entry only degrades dedup to the unidentified path —
                #   the chunk itself still imports below, nothing drops)
                except (IndexError, TypeError):
                    continue
            return None

        def _import_v1_body(request):
            if self.import_payload is not None:
                # RAW bytes straight to the native scan path — no
                # python protobuf materialization on the fleet edge
                count, failed = self.import_payload(bytes(request))
                if failed:
                    with self._count_lock:
                        self.import_errors += failed
                    logger.error("failed to import %d metrics in a V1 "
                                 "batch", failed)
                return count
            ml = forward_pb2.MetricList.FromString(bytes(request))
            count = 0
            for pb in ml.metrics:
                try:
                    self.import_metric(convert.from_pb(pb))
                    count += 1
                except Exception as e:
                    with self._count_lock:
                        self.import_errors += 1
                    logger.error("failed to import metric %s: %s",
                                 pb.name, e)
            return count

        def send_metrics(request, context):
            # V1 batch import — the fleet-internal fast path.  The
            # reference leaves this UNIMPLEMENTED (sources/proxy/
            # server.go:138-142) and its locals/proxies only speak the
            # V2 stream, so accepting batches here is a strict superset:
            # reference senders are unaffected, while this framework's
            # proxies/forwarders probe V1 and fall back to V2 against
            # reference globals (python-grpc streams cap at ~20k msgs/s;
            # one MetricList carries thousands per RPC).
            #
            # A chunk-identity header routes through the dedup ledger:
            # a chunk already imported (delivered pre-crash, or an
            # ambiguous timeout the sender's spool replays) is skipped
            # — merged exactly once — and the RPC still succeeds so the
            # replayer settles the record.
            #
            # server.sigstop_window (delay action) freezes THIS handler
            # for a bounded window — the in-process twin of a SIGSTOP'd
            # global: the RPC neither refuses nor resets, it just
            # hangs past the sender's deadline, and when the window
            # ends the import still completes — so the sender's retry
            # and the thawed original collide at the dedup ledger,
            # which must merge the chunk exactly once.
            from veneur_tpu import failpoints
            failpoints.inject("server.sigstop_window")
            ctxs = _trace_ctxs(context)
            start_ns = time.time_ns()
            if self.dedup is not None:
                count, duplicate = self.dedup.run_once(
                    _chunk_ident(context),
                    lambda: _import_v1_body(request))
                if duplicate:
                    return empty_pb2.Empty()
            else:
                count = _import_v1_body(request)
            with self._count_lock:
                self.imported_count += count
            if ctxs:
                self.trace_hook(ctxs, count, start_ns, "v1")
            return empty_pb2.Empty()

        def send_metrics_v2(request_iterator, context):
            ctxs = _trace_ctxs(context)
            start_ns = time.time_ns()
            count = 0
            for pb in request_iterator:
                try:
                    self.import_metric(convert.from_pb(pb))
                    count += 1
                except Exception as e:
                    with self._count_lock:
                        self.import_errors += 1
                    logger.error("failed to import metric %s: %s",
                                 pb.name, e)
            with self._count_lock:
                self.imported_count += count
            if ctxs:
                self.trace_hook(ctxs, count, start_ns, "v2")
            return empty_pb2.Empty()

        handlers = []
        if self.import_metric is not None:
            forward_handlers = {
                "SendMetrics": grpc.unary_unary_rpc_method_handler(
                    send_metrics,
                    request_deserializer=lambda b: b,
                    response_serializer=empty_pb2.Empty.SerializeToString),
                "SendMetricsV2": grpc.stream_unary_rpc_method_handler(
                    send_metrics_v2,
                    request_deserializer=metric_pb2.Metric.FromString,
                    response_serializer=empty_pb2.Empty.SerializeToString),
            }
            handlers.append(grpc.method_handlers_generic_handler(
                "forwardrpc.Forward", forward_handlers))

        if self.ingest_span is not None:
            def send_span(request, context):
                self.ingest_span(request)
                return ssf_grpc_pb2.Empty()
            handlers.append(grpc.method_handlers_generic_handler(
                "ssf.SSFGRPC", {
                    "SendSpan": grpc.unary_unary_rpc_method_handler(
                        send_span,
                        request_deserializer=ssf_pb2.SSFSpan.FromString,
                        response_serializer=(
                            ssf_grpc_pb2.Empty.SerializeToString)),
                }))
        if self.handle_packet is not None:
            def send_packet(request, context):
                self.handle_packet(request.packetBytes)
                return dogstatsd_grpc_pb2.Empty()
            handlers.append(grpc.method_handlers_generic_handler(
                "dogstatsd.DogstatsdGRPC", {
                    "SendPacket": grpc.unary_unary_rpc_method_handler(
                        send_packet,
                        request_deserializer=(
                            dogstatsd_grpc_pb2.DogstatsdPacket.FromString),
                        response_serializer=(
                            dogstatsd_grpc_pb2.Empty.SerializeToString)),
                }))

        # grpc.health.v1 Health/Check, always registered (the reference
        # sets SetServingStatus("veneur", SERVING), networking.go:377-384)
        # — k8s gRPC probes expect it.  Hand-rolled protos: request field
        # 1 is the service name; a SERVING response is field 1 varint 1.
        # Unknown service names get NOT_FOUND per the health protocol.
        def health_check(request, context):
            service = ""
            if len(request) >= 2 and request[0] == 0x0A:
                # length is a varint: service names of 128+ bytes use
                # multiple bytes
                n, shift, i = 0, 0, 1
                while i < len(request):
                    b = request[i]
                    n |= (b & 0x7F) << shift
                    i += 1
                    if not b & 0x80:
                        break
                    shift += 7
                service = request[i:i + n].decode(errors="replace")
            if service not in ("", "veneur"):
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"unknown service {service!r}")
            return b"\x08\x01"
        handlers.append(grpc.method_handlers_generic_handler(
            "grpc.health.v1.Health", {
                "Check": grpc.unary_unary_rpc_method_handler(
                    health_check,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b),
            }))

        class _Multi(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                for h in handlers:
                    r = h.service(handler_call_details)
                    if r is not None:
                        return r
                return None

        return _Multi()

    # -- sources.Source lifecycle (sources/sources.go:1-19) ---------------

    def name(self) -> str:
        return "proxy"

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop(grace=1.0)
