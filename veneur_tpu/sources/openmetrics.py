"""OpenMetrics source: poll a Prometheus /metrics endpoint.

Capability twin of `sources/openmetrics/openmetrics.go`
(`openmetrics.go:35,117,157,205-399`): on each `scrape_interval` tick,
fetch the endpoint, parse the text exposition format, and convert:

  * counter    -> veneur counter of the *delta* since the previous scrape
    (cumulative->delta cache keyed by name+labels; first sight or a
    counter reset emits nothing/the new value respectively)
  * gauge      -> gauge
  * histogram  -> one counter delta per `le` bucket + `_sum`/`_count`
    counter deltas
  * summary    -> one gauge per quantile + `_sum`/`_count` counter deltas
  * untyped    -> gauge

A regex allow/deny pair filters metric names, like the reference's
`allowlist`/`denylist` options.
"""

from __future__ import annotations

import logging
import math
import re
import threading
from typing import Optional

import requests

from veneur_tpu import sources as sources_mod
from veneur_tpu.samplers.metric_key import UDPMetric

logger = logging.getLogger("veneur_tpu.sources.openmetrics")

_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+"
    r"(?P<value>[^ ]+)(?:\s+(?P<ts>\d+))?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_labels(text: str) -> list[tuple[str, str]]:
    out = []
    for m in _LABEL_RE.finditer(text or ""):
        value = m.group(2).replace(r"\"", '"').replace(r"\n", "\n") \
            .replace("\\\\", "\\")
        out.append((m.group(1), value))
    return out


def parse_exposition(text: str):
    """Yield (name, labels, value, type) from Prometheus text format."""
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        name = m.group("name")
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                base = name[:-len(suffix)]
                break
        yield name, parse_labels(m.group("labels")), value, \
            types.get(base, types.get(name, "untyped"))


class OpenMetricsSource:
    KIND = "openmetrics"

    def __init__(self, spec=None, server_config=None,
                 session: Optional[requests.Session] = None):
        cfg = dict(getattr(spec, "config", None) or {})
        self._name = getattr(spec, "name", "") or self.KIND
        from veneur_tpu.config import parse_duration
        self.url = cfg.get("scrape_target", "")
        self.interval_s = parse_duration(cfg.get("scrape_interval", 10.0))
        self.timeout_s = parse_duration(
            cfg.get("scrape_timeout", self.interval_s))
        self.allow = re.compile(cfg["allowlist"]) if cfg.get("allowlist") \
            else None
        self.deny = re.compile(cfg["denylist"]) if cfg.get("denylist") \
            else None
        self.extra_tags = list(cfg.get("tags", []))
        self.session = session or requests.Session()
        self._prev: dict[tuple, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def name(self) -> str:
        return self._name

    def start(self, ingest) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(ingest,), daemon=True,
            name=f"openmetrics-{self._name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self, ingest) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once(ingest)
            except Exception:
                logger.exception("openmetrics scrape failed")

    def scrape_once(self, ingest) -> int:
        resp = self.session.get(self.url, timeout=self.timeout_s)
        resp.raise_for_status()
        return self.ingest_exposition(resp.text, ingest)

    def ingest_exposition(self, text: str, ingest) -> int:
        n = 0
        for name, labels, value, mtype in parse_exposition(text):
            if self.allow and not self.allow.search(name):
                continue
            if self.deny and self.deny.search(name):
                continue
            if math.isnan(value):
                continue
            tags = [f"{k}:{v}" for k, v in labels] + self.extra_tags
            is_cumulative = (
                mtype == "counter"
                or (mtype == "histogram" and not name.endswith("_sum"))
                or (mtype in ("histogram", "summary")
                    and name.endswith(("_sum", "_count"))))
            if mtype == "summary" and not name.endswith(("_sum", "_count")):
                is_cumulative = False  # quantile gauges
            if is_cumulative:
                key = (name, tuple(sorted(tags)))
                prev = self._prev.get(key)
                self._prev[key] = value
                if prev is None:
                    continue  # first scrape: no delta yet
                delta = value - prev
                if delta < 0:
                    delta = value  # counter reset: emit the new total
                if delta == 0:
                    continue
                # keep fractional deltas (histogram/summary _sum series
                # grow by fractions; int() would zero them forever)
                m = UDPMetric(name=name, type="counter", value=delta,
                              sample_rate=1.0)
            else:
                m = UDPMetric(name=name, type="gauge", value=float(value),
                              sample_rate=1.0)
            m.update_tags(tags, None)
            ingest.ingest_metric(m)
            n += 1
        return n


sources_mod.register_source("openmetrics")(OpenMetricsSource)
