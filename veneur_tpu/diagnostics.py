"""Runtime diagnostics loop.

Capability twin of `diagnostics/diagnostics_metrics.go:11,38`: every flush
interval, report uptime plus runtime memory/GC statistics as self-metrics.
The Go memstats become the CPython equivalents: RSS, GC generation
counts/collections, thread count, and open-fd count.

The loop also accepts extra gauge SOURCES (callables returning
name -> value): the server plugs in the profiling subsystem's data-plane
stage counters (`ingest_stage_gauges`) so the per-stage nanosecond/packet
totals that /debug/vars serves on demand are ALSO pushed as periodic
self-metrics — dashboards get the stage decomposition without polling
the debug port.
"""

from __future__ import annotations

import gc
import os
import re
import threading
import time
from typing import Optional

from veneur_tpu import scopedstatsd


def collect(start_time: float) -> dict[str, float]:
    """One snapshot of runtime stats (name -> value)."""
    stats: dict[str, float] = {
        "uptime_ms": (time.time() - start_time) * 1000.0,
        "threads": float(threading.active_count()),
    }
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        stats["mem.rss_bytes"] = float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        stats["fds"] = float(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass
    for i, gen in enumerate(gc.get_stats()):
        stats[f"gc.gen{i}.collections"] = float(gen.get("collections", 0))
        stats[f"gc.gen{i}.collected"] = float(gen.get("collected", 0))
    counts = gc.get_count()
    for i, c in enumerate(counts):
        stats[f"gc.gen{i}.pending"] = float(c)
    return stats


def ingest_stage_gauges(native) -> dict[str, float]:
    """Flatten the native data plane's per-stage totals into gauge names
    (`ingest.stage.<stage>.{ns,packets|calls|values}`).  `native` is the
    server's NativeIngest (or None); returns {} when the engine is gone,
    so the source is safe to leave wired across teardown."""
    if native is None:
        return {}
    st = native.stage_stats()
    if st is None:
        return {}
    out: dict[str, float] = {}
    for stage, counters in st["totals"].items():
        for k, v in counters.items():
            out[f"ingest.stage.{stage}.{k}"] = float(v)
    # resolved dispatch: reader count per receive backend plus the SIMD
    # mode in use (encoded as its enum value so it stays a gauge)
    for backend in ("recvmmsg", "io_uring"):
        out[f"ingest.backend.{backend}.readers"] = float(
            sum(1 for b in st.get("readers", {}).values() if b == backend))
    from veneur_tpu.ingest import SIMD_MODES
    out["ingest.simd.mode"] = float(SIMD_MODES.get(st.get("simd", "auto"), 0))
    return out


# per-tenant cardinality gauges are capped to the worst offenders: the
# self-metric namespace must never itself become the unbounded,
# attacker-influenced key space the guard exists to prevent
CARDINALITY_GAUGE_TENANTS = 8
_TENANT_NAME_SAFE = re.compile(r"[^A-Za-z0-9_-]")


def cardinality_gauges(aggregator) -> dict[str, float]:
    """Per-tenant quota/eviction counters from the cardinality guard
    (`cardinality.*`); {} when the defense is off, so the source is safe
    to wire unconditionally.  Per-tenant gauges cover only over-budget
    tenants, capped at the CARDINALITY_GAUGE_TENANTS worst offenders by
    rollup points, with the tenant value sanitized before it lands in a
    metric name (raw values may carry statsd metacharacters); the full
    uncapped ledger stays at /debug/vars -> cardinality."""
    guard = getattr(aggregator, "cardinality", None)
    if guard is None:
        return {}
    snap = guard.snapshot()
    out = {
        "cardinality.keys_evicted": float(snap["keys_evicted"]),
        "cardinality.rollup_points": float(snap["rollup_points"]),
        "cardinality.tenants_over_budget":
            float(snap["tenants_over_budget"]),
        "cardinality.tenants": float(len(snap["tenants"])),
    }
    offenders = sorted(
        ((t, st) for t, st in snap["tenants"].items()
         if st["over_budget"]),
        key=lambda kv: kv[1]["rollup_points"], reverse=True)
    for tenant, st in offenders[:CARDINALITY_GAUGE_TENANTS]:
        name = _TENANT_NAME_SAFE.sub("_", tenant)[:64] or "_"
        out[f"cardinality.tenant.{name}.exact_keys"] = \
            float(st["exact_keys"])
        out[f"cardinality.tenant.{name}.keys_evicted"] = \
            float(st["evicted_total"])
        out[f"cardinality.tenant.{name}.rollup_points"] = \
            float(st["rollup_points"])
    return out


class Diagnostics:
    """Background reporter thread (CollectDiagnosticsMetrics loop)."""

    def __init__(self, statsd=None, interval_s: float = 10.0,
                 tags: Optional[list[str]] = None,
                 prefix: str = "", sources=None):
        # the "veneur." namespace comes from the statsd client
        # (ScopedClient mirrors cmd/veneur/main.go:92); a non-empty
        # prefix here would double it
        self.statsd = scopedstatsd.ensure(statsd)
        self.interval_s = interval_s
        self.tags = list(tags or [])
        self.prefix = prefix
        # extra gauge sources: callables returning name -> value, merged
        # into every report (a failing source skips that report only)
        self.sources = list(sources or [])
        self.start_time = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def report_once(self) -> dict[str, float]:
        stats = collect(self.start_time)
        for source in self.sources:
            try:
                stats.update(source())
            except Exception:
                pass
        for name, value in stats.items():
            self.statsd.gauge(self.prefix + name, value, tags=self.tags)
        return stats

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="diagnostics")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.report_once()
            except Exception:
                pass
