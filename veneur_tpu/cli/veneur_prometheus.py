"""Legacy Prometheus poller CLI (capability twin of `cmd/veneur-prometheus`).

Scrapes a Prometheus /metrics endpoint on an interval and re-emits the
samples as DogStatsD datagrams (`cmd/veneur-prometheus/main.go:32-108`) —
the predecessor of the in-server openmetrics source, kept for CLI parity.
"""

from __future__ import annotations

import argparse
import logging
import socket
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="veneur-tpu-prometheus")
    p.add_argument("-m", dest="metrics_url", required=True,
                   help="Prometheus /metrics URL to scrape")
    p.add_argument("-s", dest="statsd", default="127.0.0.1:8125",
                   help="statsd host:port to emit to")
    p.add_argument("-i", dest="interval", type=float, default=10.0)
    p.add_argument("-p", dest="prefix", default="")
    p.add_argument("-a", dest="added_tags", action="append", default=[])
    p.add_argument("-once", action="store_true",
                   help="scrape once and exit (for tests)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)

    from veneur_tpu.config import SourceSpec
    from veneur_tpu.sources.openmetrics import OpenMetricsSource

    source = OpenMetricsSource(SourceSpec(
        kind="openmetrics", name="veneur-prometheus",
        config={"scrape_target": args.metrics_url,
                "scrape_interval": args.interval,
                "tags": args.added_tags}))

    from veneur_tpu.util import netaddr
    dest = netaddr.split_hostport(args.statsd)
    sock = socket.socket(netaddr.family(dest[0]), socket.SOCK_DGRAM)

    class StatsdIngest:
        """Ingest shim that re-emits as DogStatsD lines."""

        def ingest_metric(self, m):
            name = args.prefix + m.name
            mtype = "c" if m.type == "counter" else "g"
            line = f"{name}:{m.value}|{mtype}"
            if m.tags:
                line += "|#" + ",".join(m.tags)
            sock.sendto(line.encode(), dest)

    ingest = StatsdIngest()
    if args.once:
        source.scrape_once(ingest)
        return 0
    try:
        while True:
            t0 = time.time()
            try:
                source.scrape_once(ingest)
            except Exception:
                logging.exception("scrape failed")
            time.sleep(max(0.0, args.interval - (time.time() - t0)))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
