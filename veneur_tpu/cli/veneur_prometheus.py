"""Legacy Prometheus poller CLI (capability twin of `cmd/veneur-prometheus`).

Scrapes a Prometheus /metrics endpoint on an interval and re-emits the
samples as DogStatsD datagrams with the reference's translation semantics
(`cmd/veneur-prometheus/main.go:32-108`, `translate.go`):

  * counter            -> count of the delta since the previous scrape
                          (cumulative->delta cache; first sight skipped,
                          reset emits the new total)
  * gauge / untyped    -> gauge
  * summary            -> `.sum` gauge, `.count` count delta, and one
                          `name.<N>percentile` gauge per quantile (NaN
                          quantiles skipped)
  * histogram          -> `.sum` gauge, `.count` count delta, and one
                          `name.le<bound %f>` count delta per bucket

plus the reference's label pipeline: `-ignored-labels` name regexes,
`-r old=new` renames, `-a k=v` added tags (sorted), `-ignored-metrics`
family regexes, `-p` prefix, mTLS scrape flags, and the
`veneur.prometheus.*` self-stats.
"""

from __future__ import annotations

import argparse
import logging
import math
import re
import socket
import sys
import time
from typing import Optional

from veneur_tpu.sources.openmetrics import parse_exposition

logger = logging.getLogger("veneur_tpu.cli.veneur_prometheus")


class Translator:
    """Label pipeline + cumulative->delta cache (translate.go + cache.go)."""

    def __init__(self, ignored_labels: Optional[str] = None,
                 renamed: Optional[dict] = None,
                 added: Optional[dict] = None,
                 ignored_metrics: Optional[str] = None):
        self.ignored = re.compile(ignored_labels) if ignored_labels else None
        self.renamed = renamed or {}
        self.added = added or {}
        self.ignored_metrics = (re.compile(ignored_metrics)
                                if ignored_metrics else None)
        # Double-map cumulative->delta cache (cache.go:9-55): `_last` is
        # the previous scrape sweep, `_next` accumulates the current one,
        # swapped by _cycle_done().  Distinguishes "the cache is new"
        # (global first sweep: no basis, delta 0) from "the metric is
        # new" (count its full value, stats.go:85-88) and keeps memory
        # bounded as series come and go.
        self._last: Optional[dict[tuple, float]] = None
        self._next: dict[tuple, float] = {}
        self.decode_errors = 0
        self.unknown_types = 0

    def tags(self, labels: list[tuple[str, str]],
             drop: tuple = ()) -> list[str]:
        out = []
        for k, v in labels:
            if k in drop:
                continue
            if self.ignored is not None and self.ignored.search(k):
                continue
            out.append(f"{self.renamed.get(k, k)}:{v}")
        # added tags in sorted name order (cache-key stability,
        # translate.go Tags)
        for k in sorted(self.added):
            out.append(f"{k}:{self.added[k]}")
        return out

    def _count_delta(self, name: str, tags: list[str],
                     value: float) -> Optional[float]:
        key = (name, tuple(sorted(tags)))
        self._next[key] = value
        if self._last is None:
            return 0.0              # global first sweep: no basis
                                    # (stats.go:78-83 emits 0)
        prev = self._last.get(key)
        if prev is None:
            return value            # new series mid-stream: count it all
        if prev > value:
            return value            # counter reset: emit the new total
        return value - prev         # normal diff (0 emitted, like the
                                    # reference)

    def translate(self, text: str) -> list[tuple[str, float, str, list]]:
        """Exposition text -> [(name, value, statsd type, tags)]."""
        out = []
        for name, labels, value, mtype in parse_exposition(text):
            base = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix):
                    base = name[: -len(suffix)]
                    break
            if self.ignored_metrics is not None and \
                    self.ignored_metrics.search(base):
                continue
            if mtype == "counter":
                tags = self.tags(labels)
                d = self._count_delta(name, tags, value)
                if d is not None:
                    out.append((name, d, "c", tags))
            elif mtype in ("gauge", "untyped"):
                out.append((name, value, "g", self.tags(labels)))
            elif mtype == "summary":
                tags = self.tags(labels, drop=("quantile",))
                if name.endswith("_sum"):
                    out.append((f"{base}.sum", value, "g", tags))
                elif name.endswith("_count"):
                    d = self._count_delta(f"{base}.count", tags, value)
                    if d is not None:
                        out.append((f"{base}.count", d, "c", tags))
                else:
                    q = dict(labels).get("quantile", "")
                    if not q or math.isnan(value):
                        continue
                    out.append((
                        f"{name}.{int(float(q) * 100)}percentile",
                        value, "g", tags))
            elif mtype == "histogram":
                tags = self.tags(labels, drop=("le",))
                if name.endswith("_sum"):
                    out.append((f"{base}.sum", value, "g", tags))
                elif name.endswith("_count"):
                    d = self._count_delta(f"{base}.count", tags, value)
                    if d is not None:
                        out.append((f"{base}.count", d, "c", tags))
                elif name.endswith("_bucket"):
                    le = dict(labels).get("le", "")
                    try:
                        bound = float(le)
                    except ValueError:
                        continue
                    if math.isnan(bound):
                        continue
                    # reference naming: %s.le%f (translate.go:176); Go %f
                    # renders infinities as "+Inf"/"-Inf", python as
                    # "inf" — match Go for name parity
                    if math.isinf(bound):
                        le_str = "+Inf" if bound > 0 else "-Inf"
                    else:
                        le_str = f"{bound:f}"
                    mname = f"{base}.le{le_str}"
                    d = self._count_delta(mname, tags, value)
                    if d is not None:
                        out.append((mname, d, "c", tags))
            else:
                self.unknown_types += 1
        # one observation sweep done: swap the double-map cache so next
        # sweep can tell a brand-new series from a returning one
        # (cache.go Done, :40-55)
        self._last = self._next
        self._next = {}
        return out


def statsd_lines(stats, prefix: str = "") -> list[bytes]:
    lines = []
    for name, value, mtype, tags in stats:
        v = int(value) if float(value).is_integer() else value
        line = f"{prefix}{name}:{v}|{mtype}"
        if tags:
            line += "|#" + ",".join(tags)
        lines.append(line.encode())
    return lines


def _parse_kv(s: str) -> dict:
    out = {}
    for part in (s or "").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k] = v
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="veneur-tpu-prometheus")
    p.add_argument("-m", "--metrics-url", dest="metrics_url",
                   help="deprecated alias of -host")
    p.add_argument("-host", dest="host",
                   default="http://localhost:9090/metrics",
                   help="full URL to query for Prometheus metrics")
    p.add_argument("-s", dest="statsd", default="127.0.0.1:8126",
                   help="statsd host:port to emit to")
    p.add_argument("-i", dest="interval", type=float, default=10.0)
    p.add_argument("-p", dest="prefix", default="",
                   help="prefix for emitted metrics (trailing period)")
    p.add_argument("-a", dest="added", default="",
                   help="comma-separated tags to add (k=v,...)")
    p.add_argument("-r", dest="renamed", default="",
                   help="comma-separated label renames (old=new,...)")
    p.add_argument("-ignored-labels", dest="ignored_labels", default="")
    p.add_argument("-ignored-metrics", dest="ignored_metrics", default="")
    p.add_argument("-cert", default="", help="mTLS client cert for scrapes")
    p.add_argument("-key", default="", help="mTLS client key for scrapes")
    p.add_argument("-cacert", default="",
                   help="CA cert validating the scraped server")
    p.add_argument("-d", dest="debug", action="store_true")
    p.add_argument("-once", action="store_true",
                   help="scrape once and exit (for tests)")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.debug else logging.INFO)

    import requests

    url = args.metrics_url or args.host
    session = requests.Session()
    if args.cert and args.key:
        session.cert = (args.cert, args.key)
    if args.cacert:
        session.verify = args.cacert

    from veneur_tpu.util import netaddr
    dest = netaddr.split_hostport(args.statsd)
    sock = socket.socket(netaddr.family(dest[0]), socket.SOCK_DGRAM)
    tr = Translator(ignored_labels=args.ignored_labels or None,
                    renamed=_parse_kv(args.renamed),
                    added=_parse_kv(args.added),
                    ignored_metrics=args.ignored_metrics or None)

    def scrape_once() -> None:
        try:
            resp = session.get(url, timeout=args.interval)
            resp.raise_for_status()
            stats = tr.translate(resp.text)
        except Exception:
            tr.decode_errors += 1
            logger.exception("scrape failed")
            stats = []
        # self-stats mirror translate.go's statID set
        stats = list(stats) + [
            ("veneur.prometheus.metrics_flushed_total",
             len(stats) + 2, "c", []),
        ]
        if tr.unknown_types:
            stats.append(("veneur.prometheus.unknown_metric_type_total",
                          tr.unknown_types, "c", []))
            tr.unknown_types = 0
        if tr.decode_errors:
            stats.append(("veneur.prometheus.decode_errors_total",
                          tr.decode_errors, "c", []))
            tr.decode_errors = 0
        for line in statsd_lines(stats, args.prefix):
            sock.sendto(line, dest)

    if args.once:
        scrape_once()
        return 0
    try:
        while True:
            t0 = time.time()
            scrape_once()
            time.sleep(max(0.0, args.interval - (time.time() - t0)))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
