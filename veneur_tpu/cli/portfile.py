"""Resolved-address readback shared by both CLI entrypoints.

Every listener may bind port 0; the supervising harness
(testbed/proccluster.py, systemd, k8s) reads the REAL ports from the
port file.  tempfile + os.replace so a reader never sees a torn JSON —
the file's appearance doubles as the boot-complete marker, so writers
must install their signal handlers BEFORE calling this.
"""

import json
import os


def write_port_file(path: str, ports: dict) -> None:
    ports = dict(ports)
    ports["pid"] = os.getpid()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(ports))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
