"""Main server entry point (capability twin of `cmd/veneur/main.go:44-215`).

`python -m veneur_tpu.cli.veneur -f config.yaml` loads the YAML config
(template expansion + env overrides, `util/config/config.go:16-63`),
boots the server + HTTP API, and serves until signalled.
`-validate-config[-strict]` parse-checks and exits; `-print-secrets`
disables redaction on the config dump.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from veneur_tpu import config as config_mod
from veneur_tpu.util.build import VERSION, BUILD_DATE


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="veneur-tpu")
    p.add_argument("-f", dest="config", metavar="FILE",
                   help="The config file to read for settings.")
    p.add_argument("-validate-config", action="store_true",
                   dest="validate_config",
                   help="Validate the config file and exit.")
    p.add_argument("-validate-config-strict", action="store_true",
                   dest="validate_strict",
                   help="Validate the config file, rejecting unknown "
                        "fields, and exit.")
    p.add_argument("-print-secrets", action="store_true",
                   dest="print_secrets",
                   help="Disable redaction when dumping config.")
    p.add_argument("-version", action="store_true", dest="version")
    return p


def _write_port_file(path: str, server, api) -> None:
    from veneur_tpu.cli.portfile import write_port_file
    ports = server.resolved_ports()
    ports["http"] = list(api.address) if api is not None else None
    write_port_file(path, ports)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        print(f"veneur-tpu {VERSION} (built {BUILD_DATE})")
        return 0
    if not args.config:
        print("You must specify a config file", file=sys.stderr)
        return 1

    strict = args.validate_strict
    try:
        cfg = config_mod.read_config(args.config, strict=strict)
    except Exception as e:
        print(f"error reading config file: {e}", file=sys.stderr)
        return 1
    if args.validate_config or args.validate_strict:
        import yaml as yaml_mod
        dump = (config_mod.redacted_dict(cfg) if not args.print_secrets
                else config_mod.redacted_dict(cfg, redact=False))
        print(yaml_mod.safe_dump(dump, default_flow_style=False), end="")
        print("config valid")
        return 0

    logging.basicConfig(
        level=getattr(logging, cfg.debug and "DEBUG" or "INFO", logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s")

    # panic plumbing: a dying thread reports (optionally to Sentry) and
    # kills the process so the supervisor restarts it
    # (cmd/veneur/main.go:63-79 + sentry.go:22-64)
    from veneur_tpu import crash
    crash.install(sentry_dsn=cfg.sentry_dsn, terminate=True)
    logging.getLogger().addHandler(crash.SentryLogHandler())

    from veneur_tpu.core.server import Server
    from veneur_tpu.http_api import HttpApi

    # boot failures must be a crisp nonzero exit with the cause on
    # stderr, not a stack trace racing daemon-thread teardown — the
    # supervising process (systemd, k8s, testbed/proccluster.py) keys
    # restart/giving-up decisions off this
    server = None
    api = None
    try:
        server = Server(cfg)
        server.start()
        if cfg.http_address:
            api = HttpApi(server, cfg.http_address)
            api.start()
    except Exception as e:
        logging.getLogger("veneur_tpu").exception("server boot failed")
        print(f"server boot failed: {e}", file=sys.stderr)
        if server is not None:
            try:
                server.shutdown()
            except Exception:
                pass
        return 1

    def on_signal(signum, frame):
        # only unblock serve(); the full teardown (which may flush and
        # take locks the interrupted frame already holds) runs below
        server.stop_serving()

    # handlers BEFORE the port file: its appearance is the
    # boot-complete marker, and a supervisor may react to it with a
    # signal immediately — the default disposition would kill the
    # process without the checkpoint-on-shutdown pass
    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    # SIGUSR2 = zero-drop restart handoff (server.go:1365-1413): the
    # supervisor starts the replacement (which joins the SO_REUSEPORT
    # group), then signals this process to drain and exit
    signal.signal(signal.SIGUSR2,
                  lambda s, f: server.request_graceful_restart())

    if cfg.port_file:
        _write_port_file(cfg.port_file, server, api)

    try:
        server.serve()  # blocking flush-ticker loop
    finally:
        if api is not None:
            api.stop()
        if server._graceful_restart:
            server.graceful_restart_drain()
        else:
            server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
