"""Metric/span emitter CLI (capability twin of `cmd/veneur-emit`).

Modes, mirroring `cmd/veneur-emit/main.go:169,383,546,594`:
  * statsd datagrams:  -hostport udp://host:port -count/-gauge/-timing
    plus -tag k:v pairs
  * SSF:               -ssf sends the metric as an SSF span-sample frame
  * -grpc:             route the same payloads over the server's gRPC
    ingest edge instead of UDP (main.go:240-258,318-341): statsd bytes
    as dogstatsd.DogstatsdGRPC/SendPacket, SSF spans as
    ssf.SSFGRPC/SendSpan
  * -command:          run a subprocess, time it, emit a span (SSF) or
    timing metric (statsd)
  * events / service checks: -event_* / -sc_* flags build the DogStatsD
    `_e{}`/`_sc` wire forms
"""

from __future__ import annotations

import argparse
import shlex
import socket
import subprocess
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="veneur-tpu-emit")
    p.add_argument("-hostport", default="udp://127.0.0.1:8125",
                   help="udp://host:port destination")
    p.add_argument("-name", help="metric name")
    p.add_argument("-count", type=int, help="counter increment")
    p.add_argument("-gauge", type=float, help="gauge value")
    p.add_argument("-timing", type=float, help="timing value (ms)")
    p.add_argument("-set", dest="set_value", help="set member")
    p.add_argument("-tag", action="append", default=[],
                   help="tag, repeatable (k:v)")
    p.add_argument("-ssf", action="store_true",
                   help="send over SSF instead of statsd")
    p.add_argument("-grpc", action="store_true", dest="grpc",
                   help="send over gRPC: statsd packets via "
                        "dogstatsd SendPacket, SSF spans via SendSpan")
    p.add_argument("-command", help="run command, emit its timing")
    # events
    p.add_argument("-event_title")
    p.add_argument("-event_text")
    p.add_argument("-event_alert_type")
    # service checks
    p.add_argument("-sc_name")
    p.add_argument("-sc_status", type=int)
    p.add_argument("-sc_msg", default="")
    return p


def _dest(hostport: str) -> tuple[str, int]:
    from veneur_tpu.util import netaddr
    addr = hostport.split("://", 1)[-1]
    return netaddr.split_hostport(addr)


def statsd_lines(args) -> list[bytes]:
    tags = ("|#" + ",".join(args.tag)) if args.tag else ""
    lines = []
    if args.count is not None:
        lines.append(f"{args.name}:{args.count}|c{tags}".encode())
    if args.gauge is not None:
        lines.append(f"{args.name}:{args.gauge}|g{tags}".encode())
    if args.timing is not None:
        lines.append(f"{args.name}:{args.timing}|ms{tags}".encode())
    if args.set_value is not None:
        lines.append(f"{args.name}:{args.set_value}|s{tags}".encode())
    if args.event_title:
        title, text = args.event_title, args.event_text or ""
        ev = f"_e{{{len(title)},{len(text)}}}:{title}|{text}"
        if args.event_alert_type:
            ev += f"|t:{args.event_alert_type}"
        if args.tag:
            ev += "|#" + ",".join(args.tag)
        lines.append(ev.encode())
    if args.sc_name:
        sc = f"_sc|{args.sc_name}|{args.sc_status or 0}"
        if args.tag:
            sc += "|#" + ",".join(args.tag)
        if args.sc_msg:
            sc += f"|m:{args.sc_msg}"
        lines.append(sc.encode())
    return lines


def _build_ssf_span(args, duration_ns: int = 0, error: bool = False):
    from veneur_tpu import ssf as ssf_mod
    from veneur_tpu.trace import Span
    span = Span(args.name or (args.command and "veneur-emit.command")
                or "veneur-emit", service="veneur-emit")
    if args.count is not None:
        span.add(ssf_mod.count(args.name, args.count,
                               _tag_dict(args.tag)))
    if args.gauge is not None:
        span.add(ssf_mod.gauge(args.name, args.gauge, _tag_dict(args.tag)))
    if args.timing is not None:
        span.add(ssf_mod.timing(args.name, args.timing / 1e3,
                                tags=_tag_dict(args.tag)))
    pb = span.to_proto()
    if duration_ns:
        pb.end_timestamp = pb.start_timestamp + duration_ns
    pb.error = error
    return pb


def emit_ssf(args, dest: tuple[str, int],
             duration_ns: int = 0, error: bool = False) -> None:
    pb = _build_ssf_span(args, duration_ns, error)
    if args.grpc:
        _grpc_send_span(args.hostport, pb)
        return
    from veneur_tpu.util import netaddr
    sock = socket.socket(netaddr.family(dest[0]), socket.SOCK_DGRAM)
    sock.sendto(pb.SerializeToString(), dest)
    sock.close()


# -- gRPC emission (main.go:240-258 dogstatsd packets, 318-341 SSF) -------

class EmitError(Exception):
    """Emission failure surfaced as a clean CLI error, not a traceback."""


def _grpc_channel(hostport: str):
    import grpc
    addr = hostport.split("://", 1)[-1]
    ch = grpc.insecure_channel(addr)
    try:
        grpc.channel_ready_future(ch).result(timeout=10)
    except grpc.FutureTimeoutError:
        ch.close()
        raise EmitError(f"could not connect to gRPC server at {addr} "
                        "within 10s") from None
    return ch


def _grpc_send_span(hostport: str, span_pb) -> None:
    from veneur_tpu.protocol import ssf_grpc_pb2, ssf_pb2
    ch = _grpc_channel(hostport)
    try:
        send = ch.unary_unary(
            "/ssf.SSFGRPC/SendSpan",
            request_serializer=ssf_pb2.SSFSpan.SerializeToString,
            response_deserializer=ssf_grpc_pb2.Empty.FromString)
        send(span_pb, timeout=10)
    finally:
        ch.close()


def _grpc_send_packet(hostport: str, packet: bytes) -> None:
    from veneur_tpu.protocol import dogstatsd_grpc_pb2 as dg
    ch = _grpc_channel(hostport)
    try:
        send = ch.unary_unary(
            "/dogstatsd.DogstatsdGRPC/SendPacket",
            request_serializer=dg.DogstatsdPacket.SerializeToString,
            response_deserializer=dg.Empty.FromString)
        send(dg.DogstatsdPacket(packetBytes=packet), timeout=10)
    finally:
        ch.close()


def _tag_dict(tags: list[str]) -> dict:
    out = {}
    for t in tags:
        k, _, v = t.partition(":")
        out[k] = v
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return _run(args)
    except EmitError as e:
        print(f"veneur-emit: {e}", file=sys.stderr)
        return 1
    except Exception as e:       # noqa: BLE001 - CLI boundary
        # a clean one-line error beats a traceback for an emitter that
        # runs inside cron jobs and deploy scripts
        print(f"veneur-emit: emission failed: {e}", file=sys.stderr)
        return 1


def _run(args) -> int:
    dest = _dest(args.hostport)
    rc = 0
    if args.command:
        t0 = time.perf_counter()
        proc = subprocess.run(shlex.split(args.command))
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        rc = proc.returncode
        if args.name is None:
            args.name = "veneur-emit.command.duration_ms"
        args.timing = elapsed_ms
        if args.ssf:
            emit_ssf(args, dest,
                     duration_ns=int(elapsed_ms * 1e6),
                     error=rc != 0)
            return rc
    if args.ssf:
        emit_ssf(args, dest)
        return rc
    lines = statsd_lines(args)
    if not lines:
        print("nothing to emit (need -count/-gauge/-timing/-set/"
              "-event_title/-sc_name)", file=sys.stderr)
        return 1
    if args.grpc:
        _grpc_send_packet(args.hostport, b"\n".join(lines))
        return rc
    from veneur_tpu.util import netaddr
    sock = socket.socket(netaddr.family(dest[0]), socket.SOCK_DGRAM)
    sock.sendto(b"\n".join(lines), dest)
    sock.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
