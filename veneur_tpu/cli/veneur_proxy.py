"""Proxy entry point (capability twin of `cmd/veneur-proxy/main.go:29-136`).

Boots the consistent-hash fan-in tier with either static destinations or
a discoverer polled every `discovery_interval`.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import time

import yaml


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="veneur-tpu-proxy")
    p.add_argument("-f", dest="config", metavar="FILE", required=False)
    p.add_argument("-validate-config", action="store_true",
                   dest="validate_config")
    args = p.parse_args(argv)

    data = {}
    if args.config:
        with open(args.config) as f:
            data = yaml.safe_load(f) or {}
    if args.validate_config:
        print("config valid")
        return 0

    logging.basicConfig(level=logging.INFO)

    from veneur_tpu import crash
    crash.install(sentry_dsn=str(data.get("sentry_dsn") or ""),
                  terminate=True)

    from veneur_tpu.proxy.proxy import Proxy, proxy_config_from_dict

    cfg = proxy_config_from_dict(data)
    discoverer = None
    disc_kind = data.get("discoverer", "")
    if disc_kind == "kubernetes":
        from veneur_tpu.discovery import KubernetesDiscoverer
        discoverer = KubernetesDiscoverer()
    elif disc_kind == "consul":
        from veneur_tpu.discovery import ConsulDiscoverer
        discoverer = ConsulDiscoverer(data.get("consul_url",
                                               "http://127.0.0.1:8500"))

    try:
        proxy = Proxy(cfg, discoverer=discoverer)
        proxy.start()
    except Exception as e:
        logging.exception("proxy boot failed")
        print(f"proxy boot failed: {e}", file=sys.stderr)
        return 1
    logging.info("proxy serving grpc=:%d http=:%d", proxy.grpc_port,
                 proxy.http_port)
    stop = {"done": False}

    def on_signal(signum, frame):
        stop["done"] = True

    # handlers BEFORE the port file (its appearance is the
    # boot-complete marker — see cli/portfile.py)
    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    if cfg.port_file:
        from veneur_tpu.cli.portfile import write_port_file
        write_port_file(cfg.port_file, {"grpc": proxy.grpc_port,
                                        "http": proxy.http_port})
    try:
        while not stop["done"]:
            time.sleep(0.2)
    finally:
        proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
