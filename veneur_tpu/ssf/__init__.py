"""SSF: the Sensor Sample Format — spans + framing + sample constructors.

Wire framing mirrors `protocol/wire.go:5-49`: a frame is one version byte
(only version 0 exists: a protobuf ssf.SSFSpan follows), a 32-bit
big-endian length capped at 16MiB, then the protobuf bytes.  Framing
errors poison the stream (`protocol/errors.go`): there are no re-sync
hints, so callers must close on any framing error.

Span normalization and validity mirror `protocol/wire.go:137-173,80-98`;
sample constructors mirror `ssf/samples.go:134-209`.
"""

from __future__ import annotations

import random
import struct
from typing import BinaryIO, Optional

from veneur_tpu.protocol import ssf_pb2

SSFSample = ssf_pb2.SSFSample
SSFSpan = ssf_pb2.SSFSpan

MAX_SSF_PACKET_LENGTH = 16 * 1024 * 1024
SSF_FRAME_LENGTH = 5
_VERSION0 = 0


# -- framing errors (protocol/errors.go) ------------------------------------

class FramingError(Exception):
    """The stream is poisoned and must be closed."""


class FramingIOError(FramingError):
    pass


class FrameVersionError(FramingError):
    def __init__(self, version: int):
        super().__init__(f"unknown SSF frame version {version}")
        self.version = version


class FrameLengthError(FramingError):
    def __init__(self, length: int):
        super().__init__(
            f"frame of length {length} exceeds maximum "
            f"{MAX_SSF_PACKET_LENGTH}")
        self.length = length


def is_framing_error(err: Exception) -> bool:
    return isinstance(err, FramingError)


class InvalidTrace(ValueError):
    pass


# -- span validity (wire.go:80-98) ------------------------------------------

def valid_trace(span: SSFSpan) -> bool:
    return (span.id != 0 and span.trace_id != 0
            and span.start_timestamp != 0 and span.end_timestamp != 0
            and span.name != "")


def validate_trace(span: SSFSpan) -> None:
    if not valid_trace(span):
        raise InvalidTrace(f"not a valid trace span: {span}")


# -- parse + normalize (wire.go:137-173) ------------------------------------

def parse_ssf(packet: bytes) -> SSFSpan:
    span = SSFSpan.FromString(packet)
    # name fallback from a "name" tag (backwards compatibility)
    if not span.name and "name" in span.tags:
        span.name = span.tags["name"]
        del span.tags["name"]
    for sample in span.metrics:
        if sample.sample_rate == 0:
            sample.sample_rate = 1.0
    return span


# -- stream framing (wire.go:102-212) ---------------------------------------

def read_ssf(stream: BinaryIO) -> Optional[SSFSpan]:
    """Read one framed span; returns None on clean EOF at a message
    boundary; raises FramingError on any mid-message failure."""
    first = stream.read(1)
    if first == b"":
        return None  # clean hang-up between messages
    version = first[0]
    if version != _VERSION0:
        raise FrameVersionError(version)
    raw_len = _read_exact(stream, 4)
    (length,) = struct.unpack(">I", raw_len)
    if length > MAX_SSF_PACKET_LENGTH:
        raise FrameLengthError(length)
    body = _read_exact(stream, length)
    return parse_ssf(body)


def _read_exact(stream: BinaryIO, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            raise FramingIOError(f"EOF mid-frame after {len(buf)}/{n} bytes")
        buf += chunk
    return buf


def write_ssf(stream: BinaryIO, span: SSFSpan) -> int:
    data = span.SerializeToString()
    if len(data) > MAX_SSF_PACKET_LENGTH:
        raise FrameLengthError(len(data))
    try:
        stream.write(struct.pack(">BI", _VERSION0, len(data)))
        n = stream.write(data)
    except OSError as e:
        raise FramingIOError(str(e))
    return n


def frame_bytes(span: SSFSpan) -> bytes:
    data = span.SerializeToString()
    return struct.pack(">BI", _VERSION0, len(data)) + data


# -- sample constructors (ssf/samples.go:134-209) ---------------------------

def _mk(metric, name: str, value: float = 0.0,
        tags: Optional[dict[str, str]] = None, unit: str = "",
        timestamp: Optional[int] = None,
        sample_rate: float = 1.0, message: str = "") -> SSFSample:
    return SSFSample(
        metric=metric, name=name, value=value,
        tags=tags or {}, unit=unit,
        timestamp=timestamp if timestamp is not None else 0,
        sample_rate=sample_rate, message=message)


def count(name: str, value: float,
          tags: Optional[dict[str, str]] = None, **kw) -> SSFSample:
    return _mk(SSFSample.COUNTER, name, value, tags, **kw)


def gauge(name: str, value: float,
          tags: Optional[dict[str, str]] = None, **kw) -> SSFSample:
    return _mk(SSFSample.GAUGE, name, value, tags, **kw)


def histogram(name: str, value: float,
              tags: Optional[dict[str, str]] = None, **kw) -> SSFSample:
    return _mk(SSFSample.HISTOGRAM, name, value, tags, **kw)


def set_sample(name: str, member: str,
               tags: Optional[dict[str, str]] = None, **kw) -> SSFSample:
    return _mk(SSFSample.SET, name, 0.0, tags, message=member, **kw)


def timing(name: str, duration_s: float, resolution_s: float = 1e-9,
           tags: Optional[dict[str, str]] = None, **kw) -> SSFSample:
    """Duration expressed in `resolution_s` units with a unit string
    (ssf/samples.go Timing)."""
    units = {1e-9: "ns", 1e-6: "us", 1e-3: "ms", 1.0: "s"}
    return _mk(SSFSample.HISTOGRAM, name, duration_s / resolution_s, tags,
               unit=units.get(resolution_s, ""), **kw)


def status(name: str, state: int,
           tags: Optional[dict[str, str]] = None,
           message: str = "", **kw) -> SSFSample:
    s = _mk(SSFSample.STATUS, name, 0.0, tags, message=message, **kw)
    s.status = state
    return s


def randomly_sample(rate: float, *samples: SSFSample) -> list[SSFSample]:
    """Client-side sampling (ssf/samples.go RandomlySample): keep each
    sample with probability `rate`, recording the rate."""
    out = []
    for s in samples:
        if rate >= 1.0 or random.random() < rate:
            s.sample_rate = rate
            out.append(s)
    return out
