"""The veneur_tpu server: listeners, flush ticker, sink fan-out, watchdog.

Composition root mirroring the reference `Server`
(`server.go:106-174,462-868`): DogStatsD listeners (UDP with SO_REUSEPORT
multi-reader parallelism as in `networking.go:54-107`/`socket_linux.go`,
TCP with optional TLS client-cert auth, UNIX datagram/stream), the interval
flush ticker with per-flush deadline, metric-sink fan-out handed to the
async egress data plane (central filtering per `flusher.go:115-247` runs
on the per-sink lanes, veneur_tpu/egress/), event/service-check handling
(`server.go:942-993`), the flush watchdog (`server.go:877-912`), and
pluggable sources/sinks/forwarder.

The aggregation core is the batched MetricAggregator (one arena set instead
of N worker goroutines; the key-shard parallelism lives on the device mesh,
see veneur_tpu/parallel/).
"""

from __future__ import annotations

import collections
import concurrent.futures
import logging
import os
import socket
import ssl
import threading
import time
from typing import Callable, Optional

from veneur_tpu import config as config_mod
from veneur_tpu import sinks as sink_mod
from veneur_tpu.core.aggregator import MetricAggregator
from veneur_tpu.profiling.timeline import FlushTimeline
from veneur_tpu.samplers import parser as parser_mod
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.sketches import hll as hll_mod
from veneur_tpu.util import matcher as matcher_mod
from veneur_tpu.util import netaddr
from veneur_tpu.util import tagging

logger = logging.getLogger("veneur_tpu.server")


def parse_listen_addr(addr: str) -> tuple[str, str]:
    """'udp://host:port' -> (scheme, rest); bare 'host:port' -> udp."""
    if "://" in addr:
        scheme, rest = addr.split("://", 1)
        return scheme, rest
    return "udp", addr


def _split_hostport(rest: str) -> tuple[str, int]:
    """host:port with RFC-3986 bracketed IPv6 support; unbracketed IPv6
    literals fail loudly (util/netaddr.py, the reference's ResolveAddr
    dialect)."""
    return netaddr.split_hostport(rest)


def _sock_family(host: str) -> int:
    return netaddr.family(host)


class _SpanSinkWorker:
    """One span sink's bounded queue + drain thread(s).

    The isolation analog of the reference SpanWorker's per-sink goroutine
    with a 9s ingest timeout (`worker.go:603-652`): each sink drains its
    own queue, so a hung or slow sink blocks only itself — its queue fills
    and further spans are dropped with accounting, while every other sink
    keeps receiving.  Per-sink cumulative ingest time backs the
    `sink.span_ingest_total_duration_ns` metric (worker.go:647-652)."""

    def __init__(self, sink, capacity: int, n_threads: int,
                 shutdown: threading.Event, excluded_tags=None):
        import queue as queue_mod
        self.sink = sink
        # tags_exclude keys stripped from spans before this sink sees them
        # (setSinkExcludedTags covers span sinks too, server.go:1456-1463)
        self.excluded_tags = excluded_tags or None
        self.queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=capacity)
        self.dropped = 0
        self.ingested = 0
        self.errors = 0
        self.ingest_duration_ns = 0
        self._reported = (0, 0, 0, 0)
        self._shutdown = shutdown
        self.threads = []
        for i in range(max(1, n_threads)):
            t = threading.Thread(
                target=self._run, daemon=True,
                name=f"span-sink-{sink.name()}-{i}")
            t.start()
            self.threads.append(t)

    def submit(self, span) -> None:
        try:
            self.queue.put_nowait(span)
        except Exception:
            self.dropped += 1

    def interval_stats(self) -> tuple[int, int, int, int]:
        """(ingested, dropped, errors, duration_ns) since last call."""
        cur = (self.ingested, self.dropped, self.errors,
               self.ingest_duration_ns)
        delta = tuple(c - p for c, p in zip(cur, self._reported))
        self._reported = cur
        return delta

    def _run(self) -> None:
        import queue as queue_mod
        while not self._shutdown.is_set():
            try:
                span = self.queue.get(timeout=0.1)
            except queue_mod.Empty:
                continue
            if self.excluded_tags and any(
                    k in self.excluded_tags for k in span.tags):
                # copy-on-strip: the same span object fans out to the
                # other sinks, which may not share this exclusion
                # (SSFSpan.tags is a map<string,string>)
                stripped = type(span)()
                stripped.CopyFrom(span)
                for k in list(stripped.tags):
                    if k in self.excluded_tags:
                        del stripped.tags[k]
                span = stripped
            t0 = time.perf_counter_ns()
            try:
                self.sink.ingest(span)
                self.ingested += 1
            except Exception as e:
                self.errors += 1
                logger.warning("span sink %s ingest error: %s",
                               self.sink.name(), e)
            finally:
                self.ingest_duration_ns += time.perf_counter_ns() - t0


class _IngestShim:
    """sources.Ingest implementation handed to every source
    (the `ingest` shim, server.go:328-355)."""

    def __init__(self, server: "Server"):
        self._server = server

    def ingest_metric(self, m) -> None:
        self._server.aggregator.process_metric(m)

    def ingest_metric_proto(self, fm) -> None:
        self._server.aggregator.import_metric(fm)


class Server:
    def __init__(self, cfg: config_mod.Config,
                 extra_metric_sinks: Optional[list] = None,
                 extra_span_sinks: Optional[list] = None,
                 forwarder: Optional[Callable[[list[sm.ForwardMetric]], None]] = None):
        self.config = cfg
        self.extend_tags = tagging.ExtendTags(cfg.extend_tags)
        self.parser = parser_mod.Parser(self.extend_tags)
        # device mesh: the sharded serving flush runs over (shard, replica)
        # when mesh_devices is set (the multi-chip production path).  With
        # a distributed coordinator configured, join the multi-host
        # cluster FIRST so the mesh spans every host's chips (DCN story:
        # parallel/multihost.py).
        self.mesh = None
        from veneur_tpu.parallel import multihost
        # cluster join MUST precede any backend initialization (including
        # the default_backend() probe below)
        multihost.maybe_init_from_config(cfg)  # no-op without coordinator
        if cfg.compilation_cache_dir:
            # persistent XLA compile cache: recompiles of known flush
            # buckets across process restarts become disk hits instead
            # of multi-second (or, at 1M keys, minute-scale) compiles.
            # TPU-backend only: XLA:CPU AOT cache entries are machine-
            # feature-specific and can SIGILL when reloaded on a
            # different host generation.
            import jax as _jax
            cache_dir = os.path.expanduser(cfg.compilation_cache_dir)
            try:
                if _jax.default_backend() == "tpu":
                    _jax.config.update("jax_compilation_cache_dir",
                                       cache_dir)
                    _jax.config.update(
                        "jax_persistent_cache_min_compile_time_secs", 0.5)
            except Exception as e:
                logger.warning("compilation cache unavailable: %s", e)
        if cfg.mesh_devices > 0:
            from veneur_tpu.parallel import mesh as mesh_mod
            self.mesh = mesh_mod.make_mesh(
                cfg.mesh_devices, cfg.mesh_replicas or None)
        self.aggregator = MetricAggregator(
            percentiles=list(cfg.percentiles),
            aggregates=sm.parse_aggregates(cfg.aggregates),
            compression=cfg.tdigest_compression,
            set_precision=cfg.set_precision,
            count_unique_timeseries=cfg.count_unique_timeseries,
            mesh=self.mesh,
            ingest_lanes=cfg.ingest_lanes or None,
            is_local=cfg.is_local,
            initial_capacity=cfg.arena_initial_capacity,
            set_initial_capacity=cfg.set_arena_initial_capacity,
            hll_legacy_migration=cfg.hll_legacy_migration,
            digest_float64=cfg.digest_float64,
            digest_bf16_staging=cfg.digest_bf16_staging,
            flush_upload_chunks=cfg.flush_upload_chunks,
            flush_presharded_staging=cfg.flush_presharded_staging,
            flush_resident_arenas=cfg.flush_resident_arenas,
            flush_delta_chunk_keys=cfg.flush_delta_chunk_keys,
            flush_delta_nbuf=cfg.flush_delta_nbuf,
            resident_device_assembly=cfg.flush_resident_device_assembly,
            cardinality_key_budget=cfg.cardinality_key_budget,
            cardinality_tenant_tag=cfg.cardinality_tenant_tag,
            cardinality_seed=cfg.cardinality_seed,
            sketch_family_default=cfg.sketch_family_default,
            sketch_family_rules=list(cfg.sketch_family_rules),
            sketch_moments_k=cfg.sketch_moments_k,
            sketch_compactor_cap=cfg.sketch_compactor_cap,
            sketch_compactor_levels=cfg.sketch_compactor_levels,
            sketch_compactor_seed=cfg.sketch_compactor_seed,
            cardinality_rollup_family=cfg.cardinality_rollup_family,
            query_window_slots=cfg.query_window_slots,
            query_slot_seconds=(cfg.query_slot_seconds
                                or cfg.interval),
            cube_dimensions=list(cfg.cube_dimensions),
            cube_group_budget=cfg.cube_group_budget,
            cube_seed=cfg.cube_seed,
            retention_tiers=list(cfg.retention_tiers),
            retention_dir=(os.path.expanduser(cfg.retention_dir)
                           if cfg.retention_dir else ""),
            retention_max_bytes=cfg.retention_max_bytes,
            retention_max_age_s=cfg.retention_max_age,
            # lazy: self.statsd is created at start(); the timeline
            # resolves the client per emission via scopedstatsd.ensure
            retention_statsd_fn=lambda: self.statsd)
        self.forwarder = forwarder

        # sinks: configured kinds + directly injected instances
        self.metric_sinks: list[tuple[sink_mod.SinkSpec, object]] = []
        for spec in cfg.metric_sinks:
            self.metric_sinks.append(
                (spec, sink_mod.create_metric_sink(spec, cfg)))
        for s in (extra_metric_sinks or []):
            self.metric_sinks.append(
                (sink_mod.SinkSpec(kind=s.kind(), name=s.name()), s))
        self.span_sinks: list[object] = []
        for spec in cfg.span_sinks:
            self.span_sinks.append(sink_mod.create_span_sink(spec, cfg))
        self.span_sinks.extend(extra_span_sinks or [])

        # metric extraction from spans is always installed
        # (ssfmetrics, server.go:645-657)
        from veneur_tpu.sinks.ssfmetrics import MetricExtractionSink
        self.metric_extraction = MetricExtractionSink(
            self.parser, self.aggregator.process_metric,
            indicator_timer_name=cfg.indicator_span_timer_name,
            objective_timer_name=cfg.objective_span_timer_name)
        self.span_sinks.append(self.metric_extraction)

        # self-tracing flight recorder (veneur_tpu/trace/recorder.py):
        # an always-on bounded ring of finished spans, installed as a
        # span sink so everything on the span plane — the server's own
        # flush traces included — is queryable at /debug/trace
        from veneur_tpu.trace import recorder as trace_rec
        self.flight_recorder = trace_rec.FlightRecorder(
            cfg.trace_ring_capacity)
        self.span_sinks.append(self.flight_recorder)
        # per-interval distributed tracing: the deterministic seeded
        # sampler decides which flush intervals get the full treatment
        # (segment children, per-attempt forward spans, gRPC metadata
        # propagation); None = interval tracing off
        self.trace_sampler = (
            trace_rec.DeterministicSampler(cfg.trace_flush_sample_rate,
                                           cfg.trace_seed)
            if cfg.trace_flush_enabled else None)
        # live query plane (veneur_tpu/query/): the /query read path
        # over the aggregator's window rings.  The engine exists even
        # with the rings disabled so /query answers a clean 404.
        from veneur_tpu.query.engine import QueryEngine
        self.query = QueryEngine(
            self.aggregator, recorder=self.flight_recorder,
            statsd_fn=lambda: self.statsd,
            tier="local" if cfg.is_local else "global",
            hostname=cfg.hostname)
        # trace ids imported since the last flush (global tier): the
        # flush root span tags them so the cross-tier assembler can join
        # this global flush onto each settled local interval's trace
        self._imported_traces: set = set()
        self._imported_traces_lock = threading.Lock()

        # event/service-check accumulation (EventWorker, worker.go:491-536)
        self._events: list[parser_mod.SSFSample] = []
        self._events_lock = threading.Lock()

        # span pipeline: per-sink bounded queues, each drained by its own
        # worker thread(s) (SpanChan + SpanWorker with per-sink isolation,
        # worker.go:539-654)
        self.span_workers: list[_SpanSinkWorker] = []
        self.ssf_received = 0

        # self-telemetry loops back into our own span pipeline
        # (trace.NewChannelClient, server.go:518-521)
        from veneur_tpu import trace as trace_mod
        self.trace_client = trace_mod.new_channel_client(self.handle_span)

        # pluggable pull/push sources (sources/sources.go, wired like
        # createSources server.go:660-670); each gets the ingest shim at
        # start (server.go:328-355 — here the aggregator shards internally)
        from veneur_tpu import sources as sources_mod
        self.sources: list = [sources_mod.create_source(spec, cfg)
                              for spec in cfg.sources]
        self.ingest_shim = _IngestShim(self)
        self.statsd = None        # self-metrics client (stats_address)
        self.diagnostics = None   # runtime stats loop
        # opt-in runtime lock witness (analysis/witness.py): set a
        # LockWitness BEFORE start() and the named locks are wrapped to
        # record acquisition-order edges for the static cross-check
        self.lock_witness = None

        # crash durability (core/checkpoint.py + forward/spool.py):
        # the dedup ledger exists whenever this instance imports (its
        # state rides the checkpoint, so replayed chunks merge exactly
        # once across a receiver crash); checkpoint_stats is the
        # /debug/vars -> checkpoint ledger
        self.dedup = None
        if cfg.grpc_address:
            from veneur_tpu.sources.proxy import DedupLedger
            self.dedup = DedupLedger(cfg.spool_dedup_window)
        self.checkpoint_stats = {
            "enabled": bool(cfg.checkpoint_dir),
            "writes": 0, "restores": 0, "errors": 0,
            # checkpoints skipped at boot because a later flush had
            # already delivered their arena contents (flush marker)
            "stale_skips": 0,
            "last_bytes": 0, "last_unix": 0.0,
            # age of the restored checkpoint at boot (how much ingest
            # the crash window could have cost), 0 on a cold start
            "age_ms": 0.0,
        }
        self._checkpoint_write_lock = threading.Lock()
        # set by crash() (the testbed's simulated kill -9): shutdown
        # skips the final flush, the checkpoint write and the spool
        # drain — in-memory state is dropped, disk state is kept
        self._crashed = False
        self._listeners: list[socket.socket] = []
        # (lockfile path, open file) pairs guarding unix socket paths
        self._socket_locks: list[tuple[str, object]] = []
        # set by request_graceful_restart (SIGUSR2)
        self._graceful_restart = False
        # datagram readers stop on THIS event, not _shutdown: a graceful
        # restart sets _shutdown to unblock serve() but must keep readers
        # alive through the drain grace so the queued tail is consumed
        self._readers_stop = threading.Event()
        self._legacy_hll_reported = 0
        self._compiles_reported = (0, 0.0)
        self._threads: list[threading.Thread] = []
        self._shutdown = threading.Event()
        # the pool now carries only forward submissions — sink fan-out
        # moved to the egress data plane's per-sink lanes (below)
        self._flush_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.FORWARD_MAX_IN_FLIGHT + 2,
            thread_name_prefix="flush")
        self.last_flush_unix = time.time()
        self.flush_count = 0
        self._flush_serial = threading.Lock()
        # profiling subsystem: per-flush structured records, served at
        # /debug/flush_timeline (veneur_tpu/profiling/timeline.py)
        self.flush_timeline = FlushTimeline(cfg.profiling_timeline_capacity)
        # tags_exclude rules: "key" (every sink) or "key|sink1|sink2"
        # (those sinks only) — setSinkExcludedTags, server.go:660,1456-1463
        self._tags_exclude_global: set[str] = set()
        self._tags_exclude_by_sink: dict[str, set[str]] = {}
        for rule in cfg.tags_exclude:
            parts = str(rule).split("|")
            key = parts[0]
            if not key:
                continue
            if len(parts) > 1:
                for sink_name in parts[1:]:
                    if sink_name:
                        self._tags_exclude_by_sink.setdefault(
                            sink_name, set()).add(key)
            else:
                self._tags_exclude_global.add(key)
        # egress data plane (veneur_tpu/egress/): bounded per-sink
        # queues + worker lanes that take the whole sink fan-out —
        # filtering, serialization, HTTP, retries, spool spill — off
        # the flush critical path.  _flush_body_locked just enqueues.
        from veneur_tpu.egress import EgressPlane
        from veneur_tpu.forward.client import RetryPolicy as _RetryPolicy
        self.egress = EgressPlane(
            interval_s=cfg.interval,
            queue_depth=cfg.egress_queue_depth,
            retry=_RetryPolicy(
                attempts=cfg.egress_max_retries + 1,
                backoff_base_s=cfg.egress_retry_backoff,
                seed=cfg.egress_retry_seed),
            breaker_threshold=cfg.egress_breaker_threshold,
            breaker_reset_s=cfg.egress_breaker_reset,
            spool_dir=(os.path.expanduser(cfg.egress_spool_dir)
                       if cfg.egress_spool_dir else ""),
            spool_max_bytes=cfg.egress_spool_max_bytes,
            spool_max_age_s=cfg.egress_spool_max_age,
            spool_fsync=cfg.spool_fsync,
            spool_replay_interval_s=cfg.egress_spool_replay_interval,
            routing_enabled=cfg.enable_metric_sink_routing,
            excluded_tags_for=self._excluded_tags_for,
            recorder=self.flight_recorder,
            statsd_fn=lambda: self.statsd)
        for spec, sink in self.metric_sinks:
            self.egress.add_metric_sink(spec, sink)
        for sink in self.span_sinks:
            self.egress.add_span_sink(sink)
        # last-reported egress totals (egress.* per-interval deltas)
        self._egress_reported: dict = {}
        # per-protocol received-packet tallies, drained each flush into
        # listen.received_per_protocol_total (flusher.go:280,455-475).
        # Plain int increments; GIL-atomic enough for telemetry.  Batch
        # adds from the native drain and flush()'s swap take _proto_lock
        # (a lost batch add is thousands of packets, not one).
        self.proto_received: collections.Counter = collections.Counter()
        self._proto_lock = threading.Lock()
        # last-reported native parse-error/too-long totals (flush deltas)
        self._native_err_reported = (0, 0)
        # host-path loss counters (the no-silent-loss ledger for lines
        # the PYTHON paths discard — the native plane keeps its own):
        # unparseable statsd lines, unparseable SSF datagrams, span-sink
        # ingest raises, and import-edge failures; drained each flush
        # into listen.parse_errors_total / worker.span.* /
        # import.errors_total deltas
        self.parse_errors = 0
        self.ssf_parse_errors = 0
        self.span_ingest_errors = 0
        self._host_err_reported = (0, 0, 0)
        self._import_err_reported = 0
        # Bounded-concurrency forwarding: the reference gives each flush its
        # own goroutine with a one-interval ctx deadline (flusher.go:81-86),
        # so in-flight forwards are implicitly bounded by deadline/interval.
        # With the deadline floored at 10s (see start()), we bound explicitly
        # instead: up to FORWARD_MAX_IN_FLIGHT concurrent streams, and drop
        # the batch when all slots are stalled (UDP-heritage loss model).
        self._forward_slots = threading.BoundedSemaphore(
            self.FORWARD_MAX_IN_FLIGHT)
        self.forward_dropped = 0
        # last-reported forward-client (retries, dropped) totals, for
        # per-interval forward.retries_total/forward.dropped_total deltas
        self._forward_client_reported = (0, 0)
        # last-reported spool ledger totals (forward.spool.* deltas)
        self._spool_reported: dict = {}
        # accepted stream connections, closed on shutdown so reader
        # threads blocked in recv are unblocked
        self._stream_conns: set = set()
        self._stream_conns_lock = threading.Lock()
        # resolved addresses (after binding port 0)
        self.statsd_addrs: list[tuple[str, object]] = []
        self.ssf_addrs: list[tuple[str, object]] = []
        self.grpc_import = None
        # edge gRPC ingest listeners (grpc_listen_addresses)
        self.grpc_ingest_listeners: list = []
        # native ingest data plane (created in start(); None = Python path)
        self.native = None
        self.shutdown_hook: Callable[[], None] = lambda: os._exit(2)

    @property
    def is_local(self) -> bool:
        return self.config.is_local

    def resolved_ports(self) -> dict:
        """The ACTUAL bound addresses after start() — what a
        supervising harness needs when every listener bound port 0
        (config.port_file; cli/veneur.py writes this dict as JSON)."""
        return {
            "statsd": [[scheme, list(addr) if isinstance(addr, tuple)
                        else str(addr)]
                       for scheme, addr in self.statsd_addrs],
            "grpc": (self.grpc_import.port
                     if self.grpc_import is not None else 0),
            "hostname": self.config.hostname,
        }

    # -- ingestion handlers (server.go:942-1011) ---------------------------

    def handle_metric_packet(self, packet: bytes) -> None:
        """Dispatch one line: event / service check / metric."""
        if not packet:
            return
        try:
            if packet.startswith(b"_e{"):
                sample = self.parser.parse_event(packet)
                with self._events_lock:
                    self._events.append(sample)
            elif packet.startswith(b"_sc"):
                m = self.parser.parse_service_check(packet)
                self.aggregator.process_metric(m)
            else:
                self.parser.parse_metric(
                    packet, self.aggregator.process_metric)
        except parser_mod.ParseError as e:
            # visible loss: joins listen.parse_errors_total
            # (protocol:python) at the next interval accounting.
            # Locked: several reader threads hit this path, and the
            # loss ledger itself must not lose increments.
            with self._proto_lock:
                self.parse_errors += 1
            logger.debug("could not parse packet %r: %s", packet[:64], e)

    def process_packet_buffer(self, buf: bytes) -> None:
        """Newline-split a datagram (processMetricPacket,
        server.go:1109-1133)."""
        if len(buf) > self.config.metric_max_length:
            logger.debug("packet too long (%d bytes)", len(buf))
            return
        for line in buf.split(b"\n"):
            if line:
                self.handle_metric_packet(line)

    # -- listeners (networking.go) ----------------------------------------

    def start(self) -> None:
        # restore from the crash checkpoint FIRST — before any
        # listener, import server or drain thread can race the arena
        # rebuild (the arenas must be fresh for restore_state)
        if self.config.checkpoint_dir:
            self._maybe_restore_checkpoint()
        has_udp_statsd = any(
            parse_listen_addr(a)[0] == "udp"
            for a in self.config.statsd_listen_addresses)
        if self.config.native_ingest and has_udp_statsd:
            # the C++ edge data plane (UDP readers + parser + staging);
            # the Python chain stays as fallback and slow path.  Only
            # built when a UDP listener exists to feed it — TCP/unix-only
            # configs skip the engine (and its first-run g++ compile)
            try:
                from veneur_tpu.ingest import NativeIngest
                self.native = NativeIngest(
                    self.aggregator,
                    max_packet=self.config.metric_max_length,
                    implicit_tags=list(self.config.extend_tags),
                    on_other=self.handle_metric_packet,
                    simd=self.config.ingest_simd,
                    backend=self.config.ingest_backend,
                    batch=self.config.ingest_reader_batch,
                    ring_slots=self.config.ingest_ring_slots)
            # vnlint: disable=silent-loss (engine unavailability is a
            #   FALLBACK, not a drop: native=None routes every packet
            #   through the Python path, which has its own parse-error
            #   accounting)
            except Exception as e:
                logger.warning(
                    "native ingest engine unavailable (%s); "
                    "using the Python packet path", e)
                self.native = None
        for sspec, sink in self.metric_sinks:
            sink.start(None)
        for sink in self.span_sinks:
            sink.start(None)
        # spin up the egress lanes (sinks are started; the lanes may
        # immediately replay any spool records a crash left behind)
        self.egress.start()
        for addr in self.config.statsd_listen_addresses:
            self._start_statsd(addr)
        for addr in self.config.ssf_listen_addresses:
            self._start_ssf(addr)
        for addr in self.config.grpc_listen_addresses:
            self._start_grpc_ingest(addr)
        for sink in self.span_sinks:
            self.span_workers.append(_SpanSinkWorker(
                sink, self.config.span_channel_capacity,
                self.config.num_span_workers, self._shutdown,
                excluded_tags=self._excluded_tags_for(sink.name())))
        if self.config.grpc_address:
            # global tier: gRPC import source (server.go:673-682)
            from veneur_tpu.sources.proxy import GrpcImportServer

            def _import_counted(fm):
                self.proto_received["grpc"] += 1
                self.aggregator.import_metric(fm)

            def _import_payload_counted(payload):
                ok, failed = self.aggregator.import_payload(payload)
                with self._proto_lock:
                    self.proto_received["grpc"] += ok
                return ok, failed

            self.grpc_import = GrpcImportServer(
                self.config.grpc_address,
                _import_counted,
                ingest_span=self._grpc_span_counted,
                handle_packet=self._grpc_packet_counted,
                import_payload=_import_payload_counted,
                trace_hook=self._record_import_span,
                dedup=self.dedup)
            self.grpc_import.start()
        if self.config.forward_address and self.forwarder is None:
            # local tier: persistent forward connection (server.go:810-828)
            from veneur_tpu.forward.client import ForwardClient, RetryPolicy
            spool = None
            if self.config.spool_dir:
                from veneur_tpu.forward.spool import ForwardSpool
                spool = ForwardSpool(
                    os.path.expanduser(self.config.spool_dir),
                    max_bytes=self.config.spool_max_bytes,
                    max_age_s=self.config.spool_max_age,
                    fsync=self.config.spool_fsync,
                    segment_max_bytes=self.config.spool_segment_max_bytes,
                    replay_interval_s=self.config.spool_replay_interval)
            # The reference bounds each forward by one flush interval
            # (flusher.go:516-591).  Here at most FORWARD_MAX_IN_FLIGHT
            # forwards run concurrently (later flushes drop theirs once the
            # semaphore is exhausted — see flush()), so the deadline can be
            # floored at the reference's default interval without unbounded
            # pileup; sub-second test intervals would otherwise starve a
            # cold-start peer mid-stream.  Transient failures retry under
            # the config-driven bounded policy (exhaustion is accounted in
            # forward.dropped_total / /debug/vars).
            self.forwarder = ForwardClient(
                self.config.forward_address,
                timeout_s=self.config.forward_timeout
                or max(self.config.interval, 10.0),
                max_streams=self.config.forward_streams,
                retry=RetryPolicy(
                    attempts=self.config.forward_max_retries + 1,
                    backoff_base_s=self.config.forward_retry_backoff),
                spool=spool, source=self.config.hostname,
                trace_recorder=self.flight_recorder,
                deadline_retry_safe=self.config
                .forward_deadline_retry_safe)
        if self.lock_witness is not None:
            # testbed/dryrun lock witness (analysis/witness.py): wrap
            # the named locks NOW — native plane and forwarder exist,
            # none of the contending threads (ticker, drain loop,
            # watchdog, prewarm) have spawned yet, so no lock is
            # replaced while another thread can hold it
            from veneur_tpu.analysis import witness as witness_mod
            witness_mod.install_server(self, self.lock_witness)
        if self.config.flush_watchdog_missed_flushes > 0:
            t = threading.Thread(target=self._watchdog, daemon=True,
                                 name="flush-watchdog")
            t.start()
            self._threads.append(t)
        if self.config.checkpoint_dir and self.config.checkpoint_interval > 0:
            t = threading.Thread(target=self._checkpoint_loop,
                                 daemon=True, name="checkpoint-loop")
            t.start()
            self._threads.append(t)
        if self.config.prewarm_flush_shapes:
            # boot-time background compile of the configured flush
            # buckets (compile-churn hardening; persists via the
            # compilation cache, so later boots replay from disk)
            cap = self.config.arena_initial_capacity or 8192
            # prewarm rounds up to the arena's pow2 capacity internally,
            # so the top bucket a ramp can reach is always covered
            t = threading.Thread(
                target=lambda: self.aggregator.prewarm(
                    list(self.config.prewarm_depths), cap,
                    stop=self._shutdown),
                daemon=True, name="flush-prewarm")
            t.start()
            self._threads.append(t)
        # self-metrics statsd client + runtime diagnostics loop
        # (cmd/veneur/main.go:85-94, diagnostics/diagnostics_metrics.go).
        # A telemetry-witness recorder (analysis/telemetry.py) may have
        # wrapped a pre-start None: the configured client slots in as
        # its inner target so recording composes instead of suppressing.
        if self.config.stats_address and (
                self.statsd is None
                or hasattr(self.statsd, "replace_inner")):
            from veneur_tpu import scopedstatsd
            sc = self.config.veneur_metrics_scopes or {}
            client = scopedstatsd.ScopedClient(
                self.config.stats_address,
                scopes=scopedstatsd.MetricScopes(
                    counter=sc.get("counter", ""),
                    gauge=sc.get("gauge", ""),
                    histogram=sc.get("histogram", ""),
                    set_=sc.get("set", ""),
                    timing=sc.get("timing", "")),
                tags=list(self.config.veneur_metrics_additional_tags))
            if self.statsd is None:
                self.statsd = client
            else:
                self.statsd.replace_inner(client)
        if self.config.diagnostics_metrics_enabled:
            from veneur_tpu import diagnostics as diag_mod
            self.diagnostics = diag_mod.Diagnostics(
                self.statsd, interval_s=self.config.interval,
                tags=list(self.config.veneur_metrics_additional_tags),
                # push the data-plane stage totals alongside the runtime
                # stats (reads self.native at call time: safe across the
                # engine's whole lifecycle, {} once it is torn down)
                sources=[
                    lambda: diag_mod.ingest_stage_gauges(self.native),
                    # per-tenant quota/eviction counters (cardinality.*)
                    lambda: diag_mod.cardinality_gauges(self.aggregator),
                ])
            self.diagnostics.start()
        for source in self.sources:
            source.start(self.ingest_shim)
        if self.native is not None:
            t = threading.Thread(target=self._native_drain_loop, daemon=True,
                                 name="ingest-drain")
            t.start()
            self._threads.append(t)

    def _drain_native(self) -> None:
        """Fold the native engine's staged batches into the arenas and
        account the drained datagrams (the coarse-grained analog of the
        reference's per-packet worker channel sends, worker.go:274-290)."""
        if self.native is None:
            return
        batch = self.native.drain_into()
        self._count_drained(batch)

    def _count_drained(self, batch) -> None:
        if batch.packets:
            # under _proto_lock so flush()'s counter swap cannot strand a
            # batch-sized increment on the already-reported Counter
            with self._proto_lock:
                self.proto_received["udp"] += batch.packets

    def _native_drain_loop(self) -> None:
        iv = self.config.ingest_drain_interval or min(
            self.config.interval / 10.0, 0.5)
        while not self._shutdown.wait(iv):
            try:
                self._count_drained(self.native.drain_or_gc(
                    self.config.intern_gc_threshold))
            except Exception:
                logger.exception("native ingest drain failed")
                continue
            if (self.config.eager_device_sync
                    or self.config.flush_resident_arenas):
                # P7 pipelining: push this tick's staged samples into
                # the device lanes NOW so flush-time sync only covers
                # the final partial tick, instead of the whole
                # interval's backlog arriving at the snapshot.  With
                # resident arenas the same tick also STREAMS the
                # consolidated delta chunks into HBM, which is the whole
                # point of the mode — upload amortized into the
                # interval — so the gate is implied by the flag
                try:
                    self.aggregator.sync_staged()
                except Exception:
                    logger.exception("eager device sync failed")

    def stop_serving(self) -> None:
        """Unblock serve() without tearing down (signal-handler safe:
        takes no locks, so it may run while a flush is mid-flight)."""
        self._shutdown.set()

    def request_graceful_restart(self) -> None:
        """Signal-handler-safe SIGUSR2 entry: flag the serve loop to run
        the zero-drop handoff (the einhorn/goji analog of
        server.go:1365-1413)."""
        self._graceful_restart = True
        self._shutdown.set()

    def graceful_restart_drain(self, grace_s: float = 0.5) -> None:
        """Zero-drop restart handoff (server.go:1365-1413 SIGUSR2
        semantics, re-imagined on SO_REUSEPORT): the REPLACEMENT process
        binds the same UDP addresses first (the kernel's reuseport group
        admits it immediately), then this process

          1. connect()s each of its UDP sockets to a blackhole peer —
             atomically steering all NEW datagrams to the replacement's
             sockets while the already-queued tail stays readable;
          2. keeps its readers running for `grace_s` to consume that
             tail;
          3. drains the native engine and runs the final flush
             (flush_on_shutdown path) before tearing down.

        Unix sockets have no reuseport group: their listeners drain and
        close FIRST (flock released immediately), so the replacement can
        bind the path during the grace window — `_bind_unix` retries a
        locked path briefly for exactly this ordering.  A unixgram sender
        hitting the brief gap gets ECONNREFUSED (visible, not silent
        loss), which matches the reference's behavior without einhorn."""
        unix_socks = [s for s in self._listeners
                      if s.family == socket.AF_UNIX
                      and s.type == socket.SOCK_DGRAM]
        for sock in unix_socks:
            # consume whatever is queued, then close + release the lock
            sock.setblocking(False)
            while True:
                try:
                    data = sock.recv(self.config.metric_max_length + 1)
                # vnlint: disable=silent-loss (EWOULDBLOCK is the
                #   drain-until-empty terminator of the shutdown sweep:
                #   no datagram was read, so none can be lost here)
                except (BlockingIOError, OSError):
                    break
                if data:
                    self.handle_metric_packet(data)
            try:
                self._listeners.remove(sock)
                sock.close()
            except (ValueError, OSError):
                pass
        for lock_path, lock_f in self._socket_locks:
            try:
                lock_f.close()
                os.unlink(lock_path)
            except OSError:
                pass
        self._socket_locks = []
        for sock in self._listeners:
            if sock.type != socket.SOCK_DGRAM:
                continue
            if sock.family == socket.AF_UNIX:
                continue
            try:
                # discard port; never actually sent to
                target = ("127.0.0.1", 9) if sock.family == socket.AF_INET \
                    else ("::1", 9)
                sock.connect(target)
            except OSError:
                logger.exception("graceful restart: connect() failed")
        time.sleep(grace_s)      # readers consume the queued tail
        self._drain_native()
        self.shutdown()

    def _bind_unix(self, path: str, socktype: int) -> socket.socket:
        """Bind a unix socket path with the reference's semantics:
        `@`-prefixed paths use the Linux abstract namespace (tested
        server_test.go:477-1053 — no filesystem entry, no unlink), and
        filesystem paths take an exclusive flock on a sidecar lockfile
        before unlinking a possibly-live socket (networking.go:395-408),
        so two servers cannot silently steal each other's path."""
        sock = socket.socket(socket.AF_UNIX, socktype)
        if path.startswith("@"):
            sock.bind("\0" + path[1:])
            return sock
        import fcntl
        lock_f = open(path + ".lock", "w")
        # bounded retry: a replacement started just before the old
        # instance's SIGUSR2 drain releases the lock within the grace
        # window (graceful_restart_drain ordering)
        deadline = time.time() + 1.0
        while True:
            try:
                fcntl.flock(lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.time() >= deadline:
                    lock_f.close()
                    sock.close()
                    raise RuntimeError(
                        f"socket path {path!r} is locked by another "
                        f"instance")
                time.sleep(0.05)
        self._socket_locks.append((path + ".lock", lock_f))
        if os.path.exists(path):
            os.unlink(path)
        sock.bind(path)
        return sock

    def _start_statsd(self, addr: str) -> None:
        scheme, rest = parse_listen_addr(addr)
        if scheme == "udp":
            host, port = _split_hostport(rest)
            first_sock = None
            # shard count: the flow-sharded native plane can run more
            # reader sockets than the Python fallback's thread count
            n_shards = (self.config.ingest_reader_shards
                        if self.native is not None
                        and self.config.ingest_reader_shards > 0
                        else max(1, self.config.num_readers))
            n_cpus = os.cpu_count() or 1
            for i in range(n_shards):
                sock = socket.socket(_sock_family(host),
                                     socket.SOCK_DGRAM)
                # SO_REUSEPORT kernel load balancing (socket_linux.go:26-28)
                if hasattr(socket, "SO_REUSEPORT"):
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                self.config.read_buffer_size_bytes)
                if first_sock is None:
                    sock.bind((host, port))
                    first_sock = sock
                    port = sock.getsockname()[1]  # resolve port 0
                else:
                    sock.bind((host, port))
                self._listeners.append(sock)
                if self.native is not None:
                    # C++ reader loop owns this socket's hot path
                    # (io_uring multishot or recvmmsg, runtime-probed)
                    pin = (i % n_cpus
                           if self.config.ingest_reader_pinning else -1)
                    self.native.engine.add_udp_reader(sock.fileno(),
                                                      pin_cpu=pin)
                else:
                    t = threading.Thread(target=self._read_udp, args=(sock,),
                                         daemon=True, name=f"statsd-udp-{i}")
                    t.start()
                    self._threads.append(t)
            self.statsd_addrs.append(("udp", first_sock.getsockname()))
        elif scheme in ("tcp", "tcp+tls"):
            host, port = _split_hostport(rest)
            sock = socket.socket(_sock_family(host), socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, port))
            sock.listen(128)
            self._listeners.append(sock)
            ctx = self._tls_context() if (
                scheme == "tcp+tls" or self.config.tls_key) else None
            t = threading.Thread(target=self._accept_tcp,
                                 args=(sock, ctx, "tcp"),
                                 daemon=True, name="statsd-tcp")
            t.start()
            self._threads.append(t)
            self.statsd_addrs.append(("tcp", sock.getsockname()))
        elif scheme == "unixgram":
            path = rest
            sock = self._bind_unix(path, socket.SOCK_DGRAM)
            self._listeners.append(sock)
            t = threading.Thread(target=self._read_udp,
                                 args=(sock, "unixgram"),
                                 daemon=True, name="statsd-unixgram")
            t.start()
            self._threads.append(t)
            self.statsd_addrs.append(("unixgram", path))
        elif scheme == "unix":
            path = rest
            sock = self._bind_unix(path, socket.SOCK_STREAM)
            sock.listen(128)
            self._listeners.append(sock)
            t = threading.Thread(target=self._accept_tcp,
                                 args=(sock, None, "unix"),
                                 daemon=True, name="statsd-unix")
            t.start()
            self._threads.append(t)
            self.statsd_addrs.append(("unix", path))
        else:
            raise ValueError(f"unknown statsd listener scheme {scheme!r}")

    def _grpc_packet_counted(self, buf: bytes) -> None:
        """dogstatsd bytes over gRPC (DOGSTATSD_GRPC, networking.go:347);
        counted identically on edge and global-tier listeners."""
        with self._proto_lock:
            self.proto_received["dogstatsd-grpc"] += 1
        self.process_packet_buffer(buf)

    def _grpc_span_counted(self, span) -> None:
        """SSF spans over gRPC (SSF_GRPC, networking.go:353)."""
        with self._proto_lock:
            self.proto_received["ssf-grpc"] += 1
        self.handle_span(span)

    def _grpc_server_credentials(self):
        """mTLS credentials for gRPC listeners when the server TLS config
        is set (networking.go:363-374: the reference encrypts the gRPC
        listener with the same tlsConfig as the statsd TCP listener,
        requiring client certs when an authority is configured)."""
        key_set = bool(self.config.tls_key)
        cert_set = bool(self.config.tls_certificate)
        if not key_set and not cert_set:
            return None
        if key_set != cert_set:
            # fail LOUD like the statsd TCP path's load_cert_chain would —
            # a half-configured TLS setup must never bind plaintext
            raise ValueError(
                "tls_key and tls_certificate must both be set for TLS "
                "gRPC listeners (got only one)")
        import grpc as grpc_mod
        with open(self.config.tls_key, "rb") as f:
            key = f.read()
        with open(self.config.tls_certificate, "rb") as f:
            cert = f.read()
        ca = None
        if self.config.tls_authority_certificate:
            with open(self.config.tls_authority_certificate, "rb") as f:
                ca = f.read()
        return grpc_mod.ssl_server_credentials(
            [(key, cert)], root_certificates=ca,
            require_client_auth=ca is not None)

    def _start_grpc_ingest(self, addr: str) -> None:
        """Edge gRPC ingest: SSF SendSpan + raw dogstatsd SendPacket on
        one listener (StartGRPC, networking.go:326-391) — available on
        any instance, unlike grpc_address's global-tier Forward import."""
        from veneur_tpu.sources.proxy import GrpcImportServer

        scheme, rest = parse_listen_addr(addr)
        if scheme not in ("tcp", "grpc"):
            raise ValueError(
                f"unknown grpc listener scheme {scheme!r} in {addr!r}")
        srv = GrpcImportServer(
            rest, import_metric=None,
            ingest_span=self._grpc_span_counted,
            handle_packet=self._grpc_packet_counted,
            server_credentials=self._grpc_server_credentials())
        srv.start()
        self.grpc_ingest_listeners.append(srv)

    def _tls_context(self) -> ssl.SSLContext:
        """TLS with required client certs when an authority is configured
        (server.go:1257-1281)."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.config.tls_certificate,
                            self.config.tls_key)
        if self.config.tls_authority_certificate:
            ctx.load_verify_locations(self.config.tls_authority_certificate)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def _read_udp(self, sock: socket.socket, proto: str = "udp") -> None:
        # +1 so an oversized datagram still trips the too-long guard
        # instead of being silently truncated into a parseable prefix
        # (the reference allocates metricMaxLength+1, server.go:734).
        bufsize = self.config.metric_max_length + 1
        while not self._readers_stop.is_set():
            try:
                data = sock.recv(bufsize)
            except OSError:
                return
            if data:
                # always through the attribute: flush() swaps in a fresh
                # Counter each interval, so a cached reference would be
                # orphaned after the first drain
                self.proto_received[proto] += 1
                self.process_packet_buffer(data)

    def _accept_tcp(self, sock: socket.socket,
                    ctx: Optional[ssl.SSLContext],
                    proto: str = "tcp") -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._read_stream,
                                 args=(conn, ctx, proto), daemon=True)
            t.start()

    # idle timeout for stream connections (the reference arms a read
    # deadline per connection, server.go:1283-1295)
    STREAM_IDLE_TIMEOUT_S = 600.0
    FORWARD_MAX_IN_FLIGHT = 4

    def _track_conn(self, conn) -> None:
        with self._stream_conns_lock:
            self._stream_conns.add(conn)

    def _untrack_conn(self, conn) -> None:
        with self._stream_conns_lock:
            self._stream_conns.discard(conn)

    def _read_stream(self, conn: socket.socket,
                     ctx: Optional[ssl.SSLContext],
                     proto: str = "tcp") -> None:
        max_line = max(65536, self.config.metric_max_length)
        raw_conn = conn
        self._track_conn(raw_conn)
        try:
            conn.settimeout(self.STREAM_IDLE_TIMEOUT_S)
            if ctx is not None:
                conn = ctx.wrap_socket(conn, server_side=True)
            buf = b""
            while not self._shutdown.is_set():
                data = conn.recv(65536)
                if not data:
                    break
                buf += data
                *lines, buf = buf.split(b"\n")
                for line in lines:
                    if line:
                        self.proto_received[proto] += 1
                        self.handle_metric_packet(line)
                if len(buf) > max_line:
                    # a line that never ends: drop the connection rather
                    # than buffer unboundedly (bufio.Scanner's token cap)
                    logger.debug("stream line exceeded %d bytes; closing",
                                 max_line)
                    return
            if buf:
                self.handle_metric_packet(buf)
        # vnlint: disable=silent-loss (connection teardown: every
        #   COMPLETE line was already handled above; only the torn tail
        #   of a dying stream is unreadable, and the peer owns
        #   reconnect-and-resend per the statsd-TCP contract)
        except (ssl.SSLError, OSError, TimeoutError) as e:
            logger.debug("stream connection error: %s", e)
        finally:
            self._untrack_conn(raw_conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- spans (SSF pipeline) ----------------------------------------------

    def handle_trace_packet(self, packet: bytes) -> None:
        """One raw SSFSpan protobuf datagram (HandleTracePacket,
        server.go:1015-1044)."""
        from veneur_tpu import ssf as ssf_mod
        if not packet:
            return
        try:
            span = ssf_mod.parse_ssf(packet)
        except Exception as e:
            # visible loss: joins listen.parse_errors_total
            # (protocol:ssf) at the next interval accounting (locked:
            # concurrent SSF readers share this counter)
            with self._proto_lock:
                self.ssf_parse_errors += 1
            logger.debug("could not parse SSF packet: %s", e)
            return
        self.handle_span(span)

    def handle_span(self, span) -> None:
        """Fan one span out to every span sink's queue (handleSSF,
        server.go:1046-1093 + SpanWorker fan-out, worker.go:603-652);
        a full sink queue drops for that sink only."""
        self.ssf_received += 1
        if self.span_workers:
            for w in self.span_workers:
                w.submit(span)
        else:
            # not started yet (or no sinks): synchronous fallback so tests
            # and pre-start self-telemetry are not silently lost
            self.ingest_span(span)

    @property
    def spans_dropped(self) -> int:
        return sum(w.dropped for w in self.span_workers)

    def ingest_span(self, span) -> None:
        for sink in self.span_sinks:
            try:
                sink.ingest(span)
            except Exception as e:
                # visible loss: this direct path (gRPC SendSpan) has no
                # _SpanSinkWorker error counter in front of it (locked:
                # the gRPC pool runs these handlers concurrently)
                with self._proto_lock:
                    self.span_ingest_errors += 1
                logger.warning("span sink %s ingest error: %s",
                               sink.name(), e)

    def _start_ssf(self, addr: str) -> None:
        """SSF listeners (StartSSF, networking.go:223-319): UDP datagrams
        carry a raw SSFSpan protobuf; unix/tcp streams carry framed
        spans, where any framing error poisons the stream."""
        scheme, rest = parse_listen_addr(addr)
        if scheme == "udp":
            host, port = _split_hostport(rest)
            sock = socket.socket(_sock_family(host), socket.SOCK_DGRAM)
            if hasattr(socket, "SO_REUSEPORT"):
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                            self.config.read_buffer_size_bytes)
            sock.bind((host, port))
            self._listeners.append(sock)
            t = threading.Thread(target=self._read_ssf_udp, args=(sock,),
                                 daemon=True, name="ssf-udp")
            t.start()
            self._threads.append(t)
            self.ssf_addrs.append(("udp", sock.getsockname()))
        elif scheme in ("unix", "tcp"):
            if scheme == "unix":
                sock = self._bind_unix(rest, socket.SOCK_STREAM)
                bound = rest
            else:
                host, port = _split_hostport(rest)
                sock = socket.socket(_sock_family(host),
                                     socket.SOCK_STREAM)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                sock.bind((host, port))
                bound = sock.getsockname()
            sock.listen(128)
            self._listeners.append(sock)
            t = threading.Thread(target=self._accept_ssf, args=(sock,),
                                 daemon=True, name=f"ssf-{scheme}")
            t.start()
            self._threads.append(t)
            self.ssf_addrs.append((scheme, bound))
        else:
            raise ValueError(f"unknown SSF listener scheme {scheme!r}")

    def _read_ssf_udp(self, sock: socket.socket) -> None:
        # a UDP datagram can't exceed 64KiB; don't allocate the full
        # (16MiB default) trace_max_length_bytes per recv
        bufsize = min(self.config.trace_max_length_bytes, 65536)
        while not self._readers_stop.is_set():
            try:
                data = sock.recv(bufsize)
            except OSError:
                return
            if data:
                self.proto_received["ssf-udp"] += 1
                self.handle_trace_packet(data)

    def _accept_ssf(self, sock: socket.socket) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._read_ssf_stream,
                                 args=(conn,), daemon=True)
            t.start()

    def _read_ssf_stream(self, conn: socket.socket) -> None:
        from veneur_tpu import ssf as ssf_mod
        self._track_conn(conn)
        try:
            # No idle timeout here: trace clients hold one long-lived SSF
            # stream and may go quiet for arbitrary stretches; closing an
            # idle stream server-side makes the client's next span die on
            # EPIPE (the statsd stream path keeps the timeout for reference
            # parity with server.go:1283-1295, but SSF streams are
            # reconnect-on-error, not reconnect-before-send).
            f = conn.makefile("rb")
            while not self._shutdown.is_set():
                span = ssf_mod.read_ssf(f)
                if span is None:
                    return
                self.proto_received["ssf-stream"] += 1
                self.handle_span(span)
        # vnlint: disable=silent-loss (stream teardown: every parsed
        #   span was counted into proto_received above; a poisoned or
        #   dying stream closes and the SSF client reconnects — no
        #   complete span is dropped here)
        except ssf_mod.FramingError as e:
            # the stream is poisoned; close it (protocol/wire.go:26-28)
            logger.debug("SSF framing error, closing stream: %s", e)
        # vnlint: disable=silent-loss (same teardown contract as the
        #   framing-error arm above: nothing parsed is in flight)
        except OSError:
            pass
        finally:
            self._untrack_conn(conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- crash durability (core/checkpoint.py) -----------------------------

    def _maybe_restore_checkpoint(self) -> None:
        """Boot-time restore: rebuild arenas, resume the interval count
        and refill the dedup ledger from the last committed checkpoint.
        A missing/corrupt file is a cold start, never a boot failure."""
        from veneur_tpu.core import checkpoint as ckpt_mod
        ckpt_dir = os.path.expanduser(self.config.checkpoint_dir)
        loaded = ckpt_mod.read_checkpoint(ckpt_dir)
        if loaded is None:
            return
        meta, arrays = loaded
        marker = ckpt_mod.read_flush_marker(ckpt_dir)
        if (marker is not None and int(marker.get("flush_count", 0))
                > int(meta.get("flush_count", 0))):
            # a flush COMPLETED after this checkpoint was written: its
            # arenas hold data that was already forwarded/emitted, and
            # a revived sender would re-deliver it under a fresh boot
            # nonce the dedup ledger cannot match.  Skip the arena
            # restore (honest crash-window loss: at most the ingest
            # since that flush), but still resume the interval count
            # and the receiver-side dedup ledger.
            self.flush_count = int(marker["flush_count"])
            if self.dedup is not None and meta.get("dedup") is not None:
                self.dedup.restore(meta["dedup"])
            self.checkpoint_stats["stale_skips"] = (
                self.checkpoint_stats.get("stale_skips", 0) + 1)
            logger.warning(
                "checkpoint (interval %s) predates the last completed "
                "flush (interval %s): skipping arena restore to avoid "
                "re-forwarding delivered data; interval count and "
                "dedup ledger resumed",
                meta.get("flush_count"), marker["flush_count"])
            return
        from veneur_tpu.core.arena import CheckpointIncompatible
        try:
            self.aggregator.restore_state(meta["aggregator"], arrays)
            self.flush_count = int(meta.get("flush_count", 0))
            if self.dedup is not None and meta.get("dedup") is not None:
                self.dedup.restore(meta["dedup"])
        except CheckpointIncompatible as e:
            # prechecked BEFORE any mutation: the arenas are still
            # fresh, so continuing as a cold start is safe (the
            # operator changed sketch parameters across the restart)
            logger.warning("checkpoint incompatible with the current "
                           "configuration (%s); cold start", e)
            return
        except Exception:
            # restore failed MID-mutation: the arenas may hold a mix
            # of restored and fresh state — refusing to boot is safer
            # than emitting stale pre-crash data as if newly ingested
            logger.critical("checkpoint restore failed mid-rebuild; "
                            "refusing to run half-restored (delete %s "
                            "to cold-start)",
                            self.config.checkpoint_dir)
            raise
        age_ms = max(0.0, (time.time()
                           - float(meta.get("written_unix", 0.0))) * 1e3)
        self.checkpoint_stats["restores"] += 1
        self.checkpoint_stats["age_ms"] = round(age_ms, 1)
        logger.info(
            "restored checkpoint: interval %d, %d processed / %d "
            "imported, %.0f ms old", self.flush_count,
            self.aggregator.processed, self.aggregator.imported, age_ms)
        # restore is an operational event on the flush timeline, so the
        # crash window is visible next to the flush records it gapped
        self.flush_timeline.record(
            interval=self.flush_count, unix_ts=time.time(),
            total_s=0.0, event="restore", checkpoint_age_ms=age_ms)

    def checkpoint_now(self) -> bool:
        """Write one checkpoint: a coherent (arenas, interval, dedup
        ledger) cut — the ledger's pause gate drains in-flight imports
        and blocks new ones across both snapshots, so a chunk's data
        and its identity can never split across the cut — then the
        atomic tempfile->rename write OUTSIDE every lock.  Returns
        False (with accounting) on disk failure; the previous
        checkpoint stays live either way."""
        from veneur_tpu.core import checkpoint as ckpt_mod
        import contextlib
        t0 = time.perf_counter()
        # fold the C++ engine's staged batches into the arenas first —
        # mid-interval ingest parked in the data plane must be part of
        # the cut, or a crash right after the checkpoint loses it
        self._drain_native()
        with self._checkpoint_write_lock:
            # drain + block imports for the cut: a chunk's data and
            # its ledger identity must land on the same side
            gate = (self.dedup.paused() if self.dedup is not None
                    else contextlib.nullcontext())
            with gate:
                # vnlint: disable=blocking-propagation (the snapshot's
                #   flagged chain is host COO consolidation inside
                #   checkpoint_state; _checkpoint_write_lock only
                #   serializes checkpoint writers — nothing on the
                #   ingest or flush path ever takes it)
                agg_meta, arrays = self.aggregator.checkpoint_state()
                meta = {
                    "aggregator": agg_meta,
                    "flush_count": self.flush_count,
                    "hostname": self.config.hostname,
                    "dedup": (self.dedup.snapshot()
                              if self.dedup is not None else None),
                }
            try:
                nbytes = ckpt_mod.write_checkpoint(
                    os.path.expanduser(self.config.checkpoint_dir),
                    meta, arrays)
            except Exception as e:
                self.checkpoint_stats["errors"] += 1
                logger.error("checkpoint write failed (previous "
                             "checkpoint stays live): %s", e)
                return False
        dur = time.perf_counter() - t0
        self.checkpoint_stats["writes"] += 1
        self.checkpoint_stats["last_bytes"] = nbytes
        self.checkpoint_stats["last_unix"] = time.time()
        self.flush_timeline.record(
            interval=self.flush_count, unix_ts=time.time(),
            total_s=dur, event="checkpoint", checkpoint_bytes=nbytes)
        return True

    def _checkpoint_loop(self) -> None:
        iv = self.config.checkpoint_interval
        while not self._shutdown.wait(iv):
            try:
                self.checkpoint_now()
            except Exception:
                logger.exception("periodic checkpoint failed")

    def crash(self) -> None:
        """Simulated kill -9 for the crash chaos arms: tear down
        listeners and threads WITHOUT the graceful exits — no final
        flush, no shutdown checkpoint, no spool drain.  Everything
        in memory is dropped; whatever already reached the spool/
        checkpoint directories is what the revived instance gets."""
        self._crashed = True
        self.shutdown()

    # -- flush (flusher.go:26-122) ----------------------------------------

    def flush(self) -> None:
        """One flush interval, traced as a span through the server's own
        pipeline (flusher.go:26-122: Flush is itself a span, and the flush
        path reports the standard self-metrics).  Serialized: callers
        beyond the ticker (tests, /debug/profile, flush_on_shutdown) race
        the non-atomic per-interval counters otherwise."""
        with self._flush_serial:
            # vnlint: disable=blocking-propagation (_flush_serial
            #   exists to hold the entire flush — device waits
            #   included; ingest threads never contend on it)
            self._flush_locked()
            if self.config.checkpoint_dir:
                # stamp the completed flush: a checkpoint OLDER than
                # this marker must not restore its arenas (the data
                # was delivered; re-forwarding it post-crash would
                # double-count — see checkpoint.write_flush_marker)
                from veneur_tpu.core import checkpoint as ckpt_mod
                try:
                    ckpt_mod.write_flush_marker(
                        os.path.expanduser(self.config.checkpoint_dir),
                        self.flush_count)
                except OSError as e:
                    logger.warning("flush marker write failed: %s", e)

    # bound on the flush root span's imported_traces tag (the tag is
    # operator-facing JSON, not a database; the assembler only needs
    # the ids of the intervals this flush settled)
    IMPORTED_TRACES_TAG_MAX = 64

    def _record_import_span(self, ctxs, n_metrics: int, start_ns: int,
                            transport: str) -> None:
        """gRPC import trace hook (sources/proxy.py): continue each
        inbound RPC's propagated trace context with one child span
        covering the import, and remember the trace ids so the next
        flush's root span can tag the intervals it settles."""
        from veneur_tpu.trace import recorder as trace_rec
        for tid, sid in ctxs:
            span = trace_rec.continue_span(
                "global.import", tid, sid, client=self.trace_client,
                tags={"metrics": str(n_metrics), "transport": transport,
                      "host": self.config.hostname},
                start_ns=start_ns)
            span.finish()
        if ctxs:
            with self._imported_traces_lock:
                if len(self._imported_traces) < 4096:
                    self._imported_traces.update(t for t, _ in ctxs)

    # canonical order for the synthesized segment child spans.  The
    # aggregator measures segment DURATIONS, not timestamps (device_s is
    # the residual wait after the overlapped host accounting), so the
    # children are laid end to end from the flush start: their summed
    # extent vs the root's wall is exactly the overlap signal the
    # critical-path table reports.
    _SEGMENT_ORDER = ("snapshot", "build", "layout", "dispatch",
                      "device", "emit")

    def _emit_segment_spans(self, span, flush_start: float) -> None:
        """One child span per measured flush segment (the staging/
        upload/kernel/readback decomposition from last_flush_segments).
        Synthesized children go straight into the flight-recorder ring
        (record_span's proto-free fast path): they exist for trace
        assembly, and the full SSF submission pipeline — built for
        externally-sourced spans — would cost more per flush than the
        segments it annotates."""
        elapsed_ns = int((time.perf_counter() - flush_start) * 1e9)
        t0 = time.time_ns() - elapsed_ns   # wall-clock of flush start
        off = 0
        segs = self.aggregator.last_flush_segments
        for seg_name in self._SEGMENT_ORDER:
            v = segs.get(f"{seg_name}_s")
            if v is None:
                continue
            dur_ns = int(float(v) * 1e9)
            if seg_name == "device":
                win = segs.get("device_window_s")
                if win is not None:
                    # chunked pipeline: the device span's extent is the
                    # device-BUSY window since the first chunk's
                    # dispatch — it reaches BACK over the later chunks'
                    # layout/dispatch children, so sum(flush.seg.*)
                    # exceeding the root wall IS the overlap, visible in
                    # the trace without any derived metric
                    win_ns = int(float(win) * 1e9)
                    child = span.child("flush.seg.device")
                    child.end_ns = t0 + off + dur_ns
                    child.start_ns = child.end_ns - win_ns
                    child.client = None
                    child.finish()
                    self.flight_recorder.record_span(child)
                    self._emit_chunk_spans(child, child.start_ns,
                                           segs.get("device_chunks"))
                    off += dur_ns
                    continue
            child = span.child(f"flush.seg.{seg_name}")
            child.start_ns = t0 + off
            child.end_ns = child.start_ns + dur_ns
            child.client = None          # ring fast path below
            child.finish()
            self.flight_recorder.record_span(child)
            off += dur_ns

    def _emit_chunk_spans(self, span, t0_ns: int, chunks) -> None:
        """Per-chunk grandchildren under flush.seg.device: one span per
        pipelined upload chunk laid from its measured upload/dispatch/
        drain/wait durations, so a traced interval shows chunk i+1's
        upload riding on top of chunk i's device window."""
        if not chunks:
            return
        off = 0
        for i, c in enumerate(chunks):
            dur = (c.get("upload_s", 0.0) + c.get("dispatch_s", 0.0)
                   + c.get("drain_s", 0.0) + c.get("wait_s", 0.0))
            dur_ns = int(float(dur) * 1e9)
            child = span.child(f"flush.seg.device.chunk{i}")
            try:
                child.start_ns = t0_ns + off
                child.end_ns = child.start_ns + dur_ns
                child.tags = {"rows": str(c.get("rows", 0))}
                child.client = None
            finally:
                child.finish()
            self.flight_recorder.record_span(child)
            off += dur_ns

    def _flush_locked(self) -> None:
        from veneur_tpu import failpoints
        from veneur_tpu import scopedstatsd

        # vnlint: disable=blocking-propagation (deliberate failpoint
        #   edge: the chaos delay arm exists to stall the flush path
        #   itself; disarmed cost is one module-global bool read)
        failpoints.inject("server.flush")
        self.last_flush_unix = time.time()
        statsd = scopedstatsd.ensure(self.statsd)
        interval = self.flush_count + 1
        traced = (self.trace_sampler is not None
                  and self.trace_sampler.sample(interval))
        flush_start = time.perf_counter()
        # the interval's ROOT span: every flush is a distributed trace
        # over the pipeline's own span plane (context propagates through
        # forward metadata -> proxy -> global import).  The with-exit
        # finishes it — error-flagged on an exception — and submission
        # lands it in the flight-recorder ring (/debug/trace).
        with self.trace_client.span(
                "flush", service="veneur_tpu",
                tags={"veneurglobalonly": str(not self.is_local).lower(),
                      "tier": "local" if self.is_local else "global",
                      "interval": str(interval),
                      "host": self.config.hostname,
                      "forward_metrics": "0",
                      "sampled": str(traced).lower()}) as span:
            # vnlint: disable=blocking-propagation (the body IS the
            #   flush — _flush_serial deliberately covers its one
            #   device wait, pending.emit; ingest threads never
            #   contend on _flush_serial, and sink fan-out is a
            #   non-blocking egress-queue handoff.  Same rationale as
            #   the suppression at the wait itself)
            self._flush_body_locked(span, statsd, flush_start, traced)

    def _flush_body_locked(self, span, statsd, flush_start: float,
                           traced: bool) -> None:
        from veneur_tpu import ssf as ssf_mod

        self._drain_native()
        # swap the imported-trace set out NOW, just before the snapshot:
        # a trace id belongs on THIS flush's imported_traces tag only if
        # its metrics were imported before the snapshot this flush
        # evaluates — imports landing mid-flush are the NEXT flush's to
        # settle (the tag drives the assembler's global-flush join)
        if not self.is_local:
            with self._imported_traces_lock:
                settled_tids, self._imported_traces = (
                    self._imported_traces, set())
        else:
            settled_tids = ()
        # overlapped launch: snapshot + stage + dispatch the device
        # program, then run this interval's host-side self-metric
        # accounting WHILE the kernel executes; pending.emit() — the
        # only device wait — happens once the host work is done.  The
        # try/finally guarantees exactly one emit even if an accounting
        # statsd call raises.
        # vnlint: disable=blocking-propagation (the dispatch's host
        #   staging build + unique-ts estimate run under _flush_serial
        #   by definition — the flush serialization lock covers the
        #   whole flush and is never taken on the ingest path)
        pending = self.aggregator.flush_dispatch(is_local=self.is_local)
        self.flush_count += 1

        try:
            self._flush_interval_accounting(statsd)
        finally:
            # vnlint: disable=sync-under-lock,blocking-propagation (the
            #   emit IS the flush's one deliberate device wait, already
            #   overlapped behind the host-side accounting above;
            #   _flush_serial only serializes flush callers — ticker,
            #   tests, /debug — and is never taken on the ingest path)
            res = pending.emit()

        # worker.metrics_processed_total (worker.go:477)
        statsd.count("worker.metrics_processed_total",
                     res.processed + res.imported)
        # flush.unique_timeseries_total (flusher.go:42-44)
        if res.unique_ts is not None:
            statsd.count("flush.unique_timeseries_total", res.unique_ts,
                         tags=["global_veneur:"
                               + str(not self.is_local).lower()])
        # measured decomposition of the flush that just ran (snapshot/
        # build/layout/dispatch/device/emit + bytes moved) — read after
        # emit so device_s reflects THIS flush, not the last one
        for seg_name, v in list(
                self.aggregator.last_flush_segments.items()):
            if not isinstance(v, (int, float)):
                continue   # structured values (per-chunk stats list)
            if seg_name.endswith("_s"):
                statsd.timing(f"flush.segment.{seg_name[:-2]}_ms",
                              v * 1e3)
            else:
                statsd.gauge(f"flush.{seg_name}", float(v))
        # sketch-family observability: per-family key counts of the
        # flush that just ran, and the moments solver's worst moment
        # residual (a converged maxent solve sits at ~1e-4; a blowup
        # here is the canary for degenerate moment inputs)
        segs = self.aggregator.last_flush_segments
        statsd.gauge("sketch.keys", float(segs.get("keys_digest", 0)),
                     tags=["family:tdigest"])
        statsd.gauge("sketch.keys", float(segs.get("keys_moments", 0)),
                     tags=["family:moments"])
        if segs.get("keys_moments"):
            statsd.gauge("sketch.moments.solver_resid",
                         float(self.aggregator.last_moments_resid))

        with self._events_lock:
            events, self._events = self._events, []

        # sink routing (flusher.go:97-113)
        if self.config.enable_metric_sink_routing:
            res.metrics.apply_routing(self.config.metric_sink_routing,
                                      matcher_mod.match)

        if self.forwarder is not None and self.is_local and res.forward:
            if self._forward_slots.acquire(blocking=False):
                try:
                    self._flush_pool.submit(
                        self._forward_safely, res.forward, span,
                        traced, self.flush_count)
                    # the assembler requires a complete 3-tier trace
                    # only for intervals whose forward was SUBMITTED
                    # (slot-exhausted drops are accounted, not traced)
                    span.tags["forward_metrics"] = str(len(res.forward))
                except RuntimeError:  # pool shut down mid-flush
                    # the batch never forwards: account it exactly like
                    # the slots-exhausted drop below, not silently
                    self.forward_dropped += len(res.forward)
                    statsd.count("forward.error_total",
                                 len(res.forward),
                                 tags=["cause:pool_shutdown"])
                    self._forward_slots.release()
            else:
                # all forward slots stalled: drop this interval's batch
                # rather than queue unboundedly
                self.forward_dropped += len(res.forward)
                statsd.count("forward.error_total", len(res.forward),
                             tags=["cause:slots_exhausted"])
                logger.warning("%d forwards in flight; dropped %d "
                               "forward metrics",
                               self.FORWARD_MAX_IN_FLIGHT, len(res.forward))
        # sink fan-out: hand the rendered interval to the egress data
        # plane and return — filtering, serialization, HTTP, bounded
        # retries, breaker trips and spool spill all run on per-sink
        # lanes off this lock.  A slow or blackholed backend costs its
        # own lane, never the flush p99 (ROADMAP #8).
        fanout_start_ns = time.time_ns()
        self.egress.submit_interval(
            res.metrics, events, statsd, self.flush_count,
            trace_id=span.trace_id, parent_span_id=span.span_id,
            traced=traced)
        if traced:
            # segment children (staging/upload/kernel/readback) + the
            # egress handoff, as spans on the interval's own trace.
            # flush.seg.fanout now covers only the ENQUEUE — sink I/O
            # happens on the lanes, visible as flush.sink.<name> spans
            fanout_end_ns = time.time_ns()
            self._emit_segment_spans(span, flush_start)
            fanout = span.child("flush.seg.fanout")
            fanout.start_ns = fanout_start_ns
            fanout.end_ns = fanout_end_ns
            fanout.client = None         # ring fast path, like segments
            fanout.finish()
            self.flight_recorder.record_span(fanout)
        if settled_tids:
            # tag the intervals this global flush settled (bounded), so
            # the assembler can join it onto each local trace
            sample = sorted(settled_tids)[:self.IMPORTED_TRACES_TAG_MAX]
            span.tags["imported_traces"] = ",".join(
                f"{t:x}" for t in sample)
        span.add(ssf_mod.timing(
            "flush.total_duration_ns",
            time.perf_counter() - flush_start))
        # one structured record per flush into the timeline ring: the
        # measured segment decomposition (snapshot/build/layout/dispatch/
        # device/emit + bytes + per-family key counts), the interval id,
        # what the interval carried, and the trace/span ids that make
        # timeline rows cross-link into /debug/trace
        from veneur_tpu.parallel import serving as serving_mod
        self.flush_timeline.record(
            interval=self.flush_count,
            unix_ts=self.last_flush_unix,
            total_s=time.perf_counter() - flush_start,
            segments=self.aggregator.last_flush_segments,
            devices=serving_mod.mesh_device_count(self.mesh),
            processed=res.processed, imported=res.imported,
            metrics_emitted=len(res.metrics),
            forward_metrics=len(res.forward),
            trace_id=f"{span.trace_id:x}",
            span_id=f"{span.span_id:x}")

    def _flush_interval_accounting(self, statsd) -> None:
        """Host-side per-interval self-metric accounting that does not
        depend on the flush result — run between flush_dispatch() and
        emit() so it overlaps the device kernel."""
        # listen.received_per_protocol_total (flusher.go:280,455-475)
        with self._proto_lock:
            drained, self.proto_received = (self.proto_received,
                                            collections.Counter())
        for proto, n in drained.items():
            statsd.count("listen.received_per_protocol_total", n,
                         tags=[f"protocol:{proto}"])
        if self.native is not None:
            # parse-error/too-long accounting from the native data plane
            mal, tl = self.native.malformed, self.native.too_long
            pm, pt = self._native_err_reported
            if mal > pm:
                statsd.count("listen.parse_errors_total", mal - pm,
                             tags=["protocol:udp"])
            if tl > pt:
                statsd.count("listen.packets_too_long_total", tl - pt,
                             tags=["protocol:udp"])
            self._native_err_reported = (mal, tl)
        # host-path loss deltas (the silent-loss lint's ledger): python
        # parse errors, SSF parse errors, direct span-sink ingest raises
        pe, se, si = (self.parse_errors, self.ssf_parse_errors,
                      self.span_ingest_errors)
        ppe, pse, psi = self._host_err_reported
        if pe > ppe:
            statsd.count("listen.parse_errors_total", pe - ppe,
                         tags=["protocol:python"])
        if se > pse:
            statsd.count("listen.parse_errors_total", se - pse,
                         tags=["protocol:ssf"])
        if si > psi:
            statsd.count("worker.span.ingest_errors_total", si - psi,
                         tags=["sink:direct"])
        self._host_err_reported = (pe, se, si)
        # import-edge failures (sources/proxy.py GrpcImportServer):
        # metrics that arrived at this global but failed to import
        gi = getattr(self, "grpc_import", None)
        if gi is not None:
            ie = getattr(gi, "import_errors", 0)
            if ie > self._import_err_reported:
                statsd.count("import.errors_total",
                             ie - self._import_err_reported)
                self._import_err_reported = ie
        # legacy VH HLL payload accounting (mixed-hash inflation warning
        # lives in sketches/hll.py; the metric makes it monitorable)
        vh_total = hll_mod.legacy_vh_total
        if vh_total > self._legacy_hll_reported:
            statsd.count("listen.legacy_hll_total",
                         vh_total - self._legacy_hll_reported)
            self._legacy_hll_reported = vh_total
        # compile-churn observability: first-bucket XLA compiles this
        # interval (flush-path or prewarm) and their wall seconds
        ce, cs = (self.aggregator.compile_events,
                  self.aggregator.compile_seconds_total)
        if ce > self._compiles_reported[0]:
            statsd.count("flush.compile_events_total",
                         ce - self._compiles_reported[0])
            statsd.timing("flush.compile_duration_ms",
                          (cs - self._compiles_reported[1]) * 1e3)
            self._compiles_reported = (ce, cs)
        # forward retry/drop accounting from the client's bounded retry
        # policy (forward/client.py): interval deltas, so dashboards see
        # retry storms and exhausted-retry drops as they happen
        fw = self.forwarder
        if fw is not None and hasattr(fw, "stats"):
            st = fw.stats()
            pr, pd = self._forward_client_reported
            if st["retries"] > pr:
                statsd.count("forward.retries_total", st["retries"] - pr)
            if st["dropped"] > pd:
                statsd.count("forward.dropped_total", st["dropped"] - pd)
            self._forward_client_reported = (st["retries"], st["dropped"])
        # durable-spool ledger deltas (forward/spool.py): spilled /
        # replayed / expired metric points per interval — expiry is the
        # spool's visibly-accounted loss channel, so it must reach
        # dashboards, not just /debug/vars
        sp = fw.spool_stats() if (fw is not None and
                                  hasattr(fw, "spool_stats")) else None
        if sp is not None:
            prev = self._spool_reported
            for key in ("spilled_points", "replayed_points",
                        "expired_points", "dropped_points"):
                delta = sp[key] - prev.get(key, 0)
                if delta > 0:
                    statsd.count(
                        f"forward.spool.{key.split('_')[0]}_total",
                        delta)
            pending = sp["pending_records"]
            statsd.gauge("forward.spool.pending_records",
                         float(pending))
            self._spool_reported = sp
        # egress data-plane ledger deltas (veneur_tpu/egress/) for the
        # series with no event-site emission: delivered points and the
        # spool's replay/expiry/terminal-drop outcomes.  The failure-
        # side series (egress.retries/spilled/dropped/queue_full) are
        # emitted sink- and cause-tagged at their event sites in the
        # lanes — exactly once per event, never re-summed here.
        eg = self.egress.stats()
        prev_eg = self._egress_reported
        for key in ("flushed", "replayed", "expired", "spool_dropped"):
            delta = eg[key] - prev_eg.get(key, 0)
            if delta > 0:
                statsd.count(f"egress.{key}_total", delta)
        eg_pending = eg["pending"]
        statsd.gauge("egress.pending_records", float(eg_pending))
        self._egress_reported = {
            k: eg[k] for k in ("flushed", "replayed", "expired",
                               "spool_dropped")}
        # straggler classification (flusher.go:553-566 heritage, same
        # per-interval semantics as the old in-lock fan-out deadline):
        # a sink whose CURRENT delivery has been running longer than
        # one interval counts once per interval — which also catches a
        # sink.flush that never returns at all
        for label, lane_st in eg["per_sink"].items():
            if lane_st["busy_for_s"] > self.config.interval:
                statsd.count("flush.stragglers_total", 1,
                             tags=[f"flush:{label}"])
                logger.warning(
                    "flush straggler: sink %s delivery running %.1fs "
                    "(> %.1fs interval)", label,
                    lane_st["busy_for_s"], self.config.interval)
        statsd.count("spans.received_total", self.ssf_received)
        self.ssf_received = 0
        # per-span-sink ingest accounting (worker.go:603-678)
        for w in self.span_workers:
            ingested, dropped, errors, dur_ns = w.interval_stats()
            stags = [f"sink:{w.sink.name()}"]
            statsd.count("worker.span.ingested_total", ingested, tags=stags)
            statsd.count(sink_mod.SPANS_DROPPED_TOTAL, dropped, tags=stags)
            if errors:
                statsd.count("worker.span.ingest_errors_total", errors,
                             tags=stags)
            statsd.timing(sink_mod.SPAN_INGEST_DURATION, dur_ns, tags=stags)

    def _excluded_tags_for(self, sink_name: str):
        """tags_exclude keys applying to this sink (global ∪ sink-scoped);
        None when no rules are configured (fast path)."""
        per_sink = self._tags_exclude_by_sink.get(sink_name)
        if per_sink is None:
            return self._tags_exclude_global or None
        return self._tags_exclude_global | per_sink

    def _forward_safely(self, forward: list[sm.ForwardMetric],
                        parent=None, traced: bool = False,
                        epoch: Optional[int] = None) -> None:
        """Forward with sub-timings on a child span
        (flusher.go:516-576: export/grpc parts + error cause).  When the
        interval is `traced`, the forward client gets the child span as
        trace parent: each attempt becomes its own span and the attempt
        context rides the RPC metadata to the proxy.  `epoch` (the
        flush interval, checkpoint-stable across restarts) becomes the
        interval half of every chunk's exactly-once identity."""
        from veneur_tpu import scopedstatsd
        from veneur_tpu import ssf as ssf_mod
        statsd = scopedstatsd.ensure(self.statsd)
        grpc_start = time.perf_counter()
        fspan = (parent.child("flush.forward") if parent is not None
                 else self.trace_client.span("flush.forward"))
        try:
            fspan.add(
                ssf_mod.gauge("forward.metrics_total",
                              float(len(forward))),
                ssf_mod.count("forward.post_metrics_total",
                              float(len(forward))))
            kwargs = {}
            if epoch is not None and getattr(self.forwarder,
                                             "accepts_epoch", False):
                kwargs["epoch"] = epoch
            if traced and getattr(self.forwarder, "accepts_trace",
                                  False):
                self.forwarder(forward, trace_parent=fspan, **kwargs)
            else:
                self.forwarder(forward, **kwargs)
            fspan.add(ssf_mod.count("forward.error_total", 0))
        except TimeoutError:
            fspan.add(ssf_mod.count("forward.error_total", 1,
                                    tags={"cause": "deadline_exceeded"}))
            statsd.count("forward.error_total", 1,
                         tags=["cause:deadline_exceeded"])
            logger.error("forward deadline exceeded")
        except Exception as e:
            cause = "send"
            msg = str(e)
            # transient connection rebalancing isn't an error worth paging
            # on (flusher.go:556-563)
            if "UNAVAILABLE" in msg or "transport is closing" in msg:
                cause = "transient_unavailable"
            else:
                logger.error("forward failed: %s", e)
            fspan.add(ssf_mod.count("forward.error_total", 1,
                                    tags={"cause": cause}))
            statsd.count("forward.error_total", 1, tags=[f"cause:{cause}"])
        finally:
            wall = time.perf_counter() - grpc_start
            if wall > self.config.interval:
                # the old in-lock fan-out wait classified a forward
                # running past one interval as a straggler; keep the
                # signal, now stamped at completion like the sink lanes
                statsd.count("flush.stragglers_total", 1,
                             tags=["flush:forward"])
                logger.warning("forward straggler: ran %.1fs "
                               "(> %.1fs interval)", wall,
                               self.config.interval)
            fspan.add(ssf_mod.timing(
                "forward.duration_ns", wall, tags={"part": "grpc"}))
            fspan.finish()
            self._forward_slots.release()

    # per-sink delivery (filtering, flushed_metrics accounting, bounded
    # retries, HTTP phase self-metrics) lives on the egress lanes now:
    # veneur_tpu/egress/plane.py SinkLane._deliver_metric /
    # _deliver_span_flush

    # -- lifecycle ---------------------------------------------------------

    def serve(self) -> None:
        """Blocking ticker loop (server.go:830-867)."""
        interval = self.config.interval
        if self.config.synchronize_with_interval:
            now = time.time()
            time.sleep(interval - (now % interval))
        next_tick = time.time() + interval
        while not self._shutdown.is_set():
            timeout = max(0.0, next_tick - time.time())
            if self._shutdown.wait(timeout):
                break
            next_tick += interval
            try:
                self.flush()
            except Exception as e:
                logger.exception("flush failed: %s", e)

    # longest the watchdog will attribute an overdue flush to an XLA
    # compile before terminating anyway (a guard that never exits is a
    # wedged runtime, which IS the hang class the watchdog exists for)
    COMPILE_GRACE_S = 900.0

    def _watchdog(self) -> None:
        """FlushWatchdog (server.go:877-912): die if flushes stop so a
        supervisor can restart us."""
        interval = self.config.interval
        missed = self.config.flush_watchdog_missed_flushes
        compile_hold_since = None
        while not self._shutdown.is_set():
            if self._shutdown.wait(interval / 2):
                return
            overdue = time.time() - self.last_flush_unix
            if overdue > missed * interval:
                if self.aggregator.compile_in_progress.is_set():
                    # a first-bucket XLA compile is progress, not a hang
                    # (VERDICT r3: a compile stall must not look like
                    # one) — but only for a bounded grace: a compile
                    # that never returns is a wedged device runtime
                    if compile_hold_since is None:
                        compile_hold_since = time.time()
                    held = time.time() - compile_hold_since
                    if held < self.COMPILE_GRACE_S:
                        logger.warning(
                            "flush watchdog: flush %.1fs overdue but an "
                            "XLA compile is in progress (%.0fs); holding "
                            "fire", overdue, held)
                        continue
                    logger.critical(
                        "flush watchdog: compile in progress for %.0fs "
                        "(> %.0fs grace); treating as a hang", held,
                        self.COMPILE_GRACE_S)
                else:
                    compile_hold_since = None
                logger.critical(
                    "flush watchdog: no flush for %.1fs (> %d intervals); "
                    "terminating", overdue, missed)
                self.shutdown_hook()
                return
            else:
                compile_hold_since = None

    def shutdown(self) -> None:
        """server.go:1417-1435.  A crash() teardown skips the graceful
        exits (final flush, shutdown checkpoint, spool drain) — the
        revived instance recovers from disk instead."""
        if self.config.flush_on_shutdown and not self._crashed:
            try:
                self.flush()
            except Exception:
                logger.exception("final flush failed")
        if self.config.checkpoint_dir and not self._crashed:
            # SIGTERM/graceful-exit snapshot: the supervisor's restart
            # resumes from here (cli/veneur.py routes SIGTERM through
            # this path)
            try:
                self.checkpoint_now()
            except Exception:
                logger.exception("shutdown checkpoint failed")
        self._shutdown.set()
        self._readers_stop.set()
        for source in self.sources:
            try:
                source.stop()
            except Exception:
                logger.exception("source stop failed")
        # drain the egress lanes BEFORE the statsd client closes (the
        # final interval's per-sink accounting still needs it) and
        # before sinks close further down.  A crash skips the drain:
        # queued jobs die with the process and the per-sink spools keep
        # their on-disk records for the revived instance's replayers.
        try:
            self.egress.close(drain=not self._crashed,
                              timeout_s=max(2.0, self.config.interval))
        except Exception:
            logger.exception("egress close failed")
        if not self._crashed:
            # flush() no longer waits on its forward future (the old
            # in-lock fan-out wait covered it): give the final
            # interval's in-flight forwards a bounded window to land
            # before the channel is torn down below
            deadline = time.time() + max(2.0, self.config.interval)
            while (time.time() < deadline
                   and self._forward_slots._value
                   < self.FORWARD_MAX_IN_FLIGHT):
                time.sleep(0.02)
        if self.diagnostics is not None:
            self.diagnostics.stop()
        if self.statsd is not None:
            self.statsd.close()
        try:
            self.trace_client.close()
        except Exception:
            pass
        if self.native is not None:
            # join the C++ reader threads BEFORE closing their fds — a
            # recycled fd number must never be readable by a stale reader
            try:
                self.native.stop()
                self.native.close()
            except Exception:
                logger.exception("native ingest shutdown failed")
        for sock in self._listeners:
            try:
                sock.close()
            except OSError:
                pass
        for lock_path, lock_f in self._socket_locks:
            try:
                lock_f.close()
                os.unlink(lock_path)
            except OSError:
                pass
        self._socket_locks = []
        # unblock reader threads parked in recv on accepted streams
        with self._stream_conns_lock:
            conns = list(self._stream_conns)
            self._stream_conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self.grpc_import is not None:
            self.grpc_import.stop()
        for srv in self.grpc_ingest_listeners:
            try:
                srv.stop()
            except Exception:
                logger.exception("grpc ingest listener stop failed")
        if self.forwarder is not None and hasattr(self.forwarder, "close"):
            try:
                if getattr(self.forwarder, "spool", None) is not None:
                    self.forwarder.close(drain_spool=not self._crashed)
                else:
                    self.forwarder.close()
            except Exception:
                pass
        ret = getattr(self.aggregator, "retention", None)
        if ret is not None:
            # stop the compaction worker first — crash discards its
            # queue (those cuts were never checkpointed) so a dying
            # server can't keep spilling into a directory its revival
            # reopened.  Then graceful exit settles the active tier
            # segment to disk; a crash leaves it as-is — the revived
            # store re-indexes the durable segments (torn tail
            # truncated, CRC-failing records rejected) exactly like
            # the forward spool
            try:
                ret.close(drain=not self._crashed)
            except Exception:
                logger.exception("retention worker close failed")
            if ret.store is not None:
                try:
                    ret.store.close(drain=not self._crashed)
                except Exception:
                    logger.exception("retention store close failed")
        for _, sink in self.metric_sinks:
            if hasattr(sink, "close"):
                try:
                    sink.close()
                except Exception:
                    logger.exception("sink close failed")
        for sink in self.span_sinks:
            if hasattr(sink, "close"):
                try:
                    sink.close()
                except Exception:
                    logger.exception("span sink close failed")
        self._flush_pool.shutdown(wait=False)
