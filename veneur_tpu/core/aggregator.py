"""MetricAggregator: ingest/import/flush over the batched arenas.

This is the TPU-native fusion of the reference's Worker
(`worker.go:348-459`: ProcessMetric / ImportMetric scope dispatch) and
flusher (`flusher.go:26-122,286-415`: tally + InterMetric generation with
the local/global flush duality).  Instead of N worker goroutines each
walking per-key sampler maps, one aggregator owns the arenas and every
flush evaluates all keys in a handful of batched XLA calls.

Flush duality (`flusher.go:57-74`):
  - a *local* instance emits histogram aggregates from local-sample
    scalars and NO percentiles for mixed-scope keys (those forward their
    digests to the global tier), but full percentiles for local-only keys;
  - a *global* instance emits percentiles (and digest-derived aggregates
    for global-scope keys), plus sets and global counters/gauges.

Concurrency: ingest threads append to host staging under `lock`; flush
holds the lock only to sync staging, snapshot the (immutable) device state
and host scalars, and reset — evaluation and InterMetric generation run on
the snapshot outside the lock, so ingest continues during flush exactly
like the reference's swap-maps-under-mutex (`worker.go:462-481`).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
import numpy as np

from veneur_tpu.core import arena as arena_mod
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.samplers.metric_key import MetricKey, MetricScope, UDPMetric
from veneur_tpu.sketches import hll as hll_mod
from veneur_tpu.sketches import tdigest as td


@dataclass
class FlushResult:
    metrics: list[sm.InterMetric] = field(default_factory=list)
    forward: list[sm.ForwardMetric] = field(default_factory=list)
    processed: int = 0
    imported: int = 0
    # HLL estimate of distinct timeseries this interval, or None when
    # count_unique_timeseries is off (flusher.go:42-44)
    unique_ts: Optional[int] = None


class MetricAggregator:
    def __init__(self,
                 percentiles: Optional[list[float]] = None,
                 aggregates: sm.HistogramAggregates = sm.HistogramAggregates(),
                 compression: float = td.DEFAULT_COMPRESSION,
                 set_precision: int = hll_mod.DEFAULT_PRECISION,
                 count_unique_timeseries: bool = False,
                 mesh=None, ingest_lanes: Optional[int] = None,
                 is_local: bool = True):
        self.percentiles = percentiles if percentiles is not None else [0.5]
        self.aggregates = aggregates
        self.lock = threading.Lock()
        self.mesh = mesh
        self.digests = arena_mod.DigestArena(
            compression=compression, mesh=mesh, n_lanes=ingest_lanes)
        self.sets = arena_mod.SetArena(precision=set_precision)
        self.counters = arena_mod.CounterArena()
        self.gauges = arena_mod.GaugeArena()
        self.status = arena_mod.StatusArena()
        self.processed = 0
        self.imported = 0
        self.count_unique_timeseries = count_unique_timeseries
        self.unique_ts = hll_mod.HLLSketch() if count_unique_timeseries else None
        self.is_local = is_local

    # -- ingest (ProcessMetric, worker.go:348-396) -------------------------

    def process_metric(self, m: UDPMetric) -> None:
        with self.lock:
            self._process_locked(m)

    def process_batch(self, ms: list[UDPMetric]) -> None:
        with self.lock:
            for m in ms:
                self._process_locked(m)

    def _process_locked(self, m: UDPMetric) -> None:
        self.processed += 1
        if self.unique_ts is not None:
            self._sample_timeseries(m)
        t = m.type
        if t == sm.TYPE_COUNTER:
            scope = (MetricScope.GLOBAL_ONLY
                     if m.scope == MetricScope.GLOBAL_ONLY
                     else MetricScope.MIXED)
            row = self.counters.row_for(m.key, scope, m.tags)
            self.counters.sample(row, m.value, m.sample_rate)
        elif t == sm.TYPE_GAUGE:
            scope = (MetricScope.GLOBAL_ONLY
                     if m.scope == MetricScope.GLOBAL_ONLY
                     else MetricScope.MIXED)
            row = self.gauges.row_for(m.key, scope, m.tags)
            self.gauges.sample(row, m.value)
        elif t in (sm.TYPE_HISTOGRAM, sm.TYPE_TIMER):
            row = self.digests.row_for(m.key, m.scope, m.tags)
            self.digests.sample(row, m.value, m.sample_rate)
        elif t == sm.TYPE_SET:
            scope = (MetricScope.LOCAL_ONLY
                     if m.scope == MetricScope.LOCAL_ONLY
                     else MetricScope.MIXED)
            row = self.sets.row_for(m.key, scope, m.tags)
            self.sets.sample(row, str(m.value))
        elif t == sm.TYPE_STATUS:
            row = self.status.row_for(m.key, MetricScope.LOCAL_ONLY, m.tags)
            self.status.sample(row, float(m.value), m.message, m.hostname)
        # unknown types are silently skipped, as in worker.go:393-395

    def _sample_timeseries(self, m: UDPMetric) -> None:
        """Unique-timeseries HLL counting (worker.go:301-345): sample iff
        the series is finalized on this instance — always on a global
        instance (worker.go:310-314), else only non-forwarded types."""
        if not self.is_local:
            self.unique_ts.insert(m.digest.to_bytes(8, "little"))
            return
        local_types = {
            sm.TYPE_COUNTER: m.scope != MetricScope.GLOBAL_ONLY,
            sm.TYPE_GAUGE: m.scope != MetricScope.GLOBAL_ONLY,
            sm.TYPE_HISTOGRAM: m.scope == MetricScope.LOCAL_ONLY,
            sm.TYPE_SET: m.scope == MetricScope.LOCAL_ONLY,
            sm.TYPE_TIMER: m.scope == MetricScope.LOCAL_ONLY,
            sm.TYPE_STATUS: True,
        }
        if local_types.get(m.type, False):
            self.unique_ts.insert(m.digest.to_bytes(8, "little"))

    # -- import (ImportMetric, worker.go:402-459) --------------------------

    def import_metric(self, fm: sm.ForwardMetric) -> None:
        scope = MetricScope(fm.scope)
        if fm.kind in (sm.TYPE_COUNTER, sm.TYPE_GAUGE):
            scope = MetricScope.GLOBAL_ONLY
        if scope == MetricScope.LOCAL_ONLY:
            raise ValueError("gRPC import does not accept local metrics")
        key = MetricKey(fm.name, fm.kind, ",".join(sorted(fm.tags)))
        with self.lock:
            self.imported += 1
            if fm.kind == sm.TYPE_COUNTER:
                row = self.counters.row_for(key, MetricScope.GLOBAL_ONLY,
                                            fm.tags)
                self.counters.merge(row, fm.counter_value)
            elif fm.kind == sm.TYPE_GAUGE:
                row = self.gauges.row_for(key, MetricScope.GLOBAL_ONLY,
                                          fm.tags)
                self.gauges.merge(row, fm.gauge_value)
            elif fm.kind == sm.TYPE_SET:
                row = self.sets.row_for(key, MetricScope.MIXED, fm.tags)
                self.sets.merge(row, fm.hll)
            elif fm.kind in (sm.TYPE_HISTOGRAM, sm.TYPE_TIMER):
                cls = (MetricScope.GLOBAL_ONLY
                       if scope == MetricScope.GLOBAL_ONLY
                       else MetricScope.MIXED)
                row = self.digests.row_for(key, cls, fm.tags)
                self.digests.merge_digest(
                    row, fm.digest_means or [], fm.digest_weights or [],
                    fm.digest_min, fm.digest_max, fm.digest_rsum)
            else:
                raise ValueError(f"unknown metric kind {fm.kind!r}")

    # -- flush -------------------------------------------------------------

    def flush(self, is_local: bool, now: Optional[int] = None) -> FlushResult:
        now = int(now if now is not None else time.time())
        res = FlushResult()

        with self.lock:
            snap = self._snapshot_and_reset()
            res.processed, res.imported = snap.pop("counts")
        if "unique_ts" in snap:
            res.unique_ts = snap["unique_ts"].estimate()

        self._emit_counters(res, snap, is_local, now)
        self._emit_gauges(res, snap, is_local, now)
        self._emit_status(res, snap, now)
        self._emit_sets(res, snap, is_local, now)
        self._emit_digests(res, snap, is_local, now)
        return res

    def _snapshot_and_reset(self) -> dict:
        """Under lock: sync staging, snapshot state+metadata of touched
        rows, reset.  Device tensors are immutable so the snapshot is a
        reference; host arrays are fancy-index copies."""
        d, s, c, g, st = (self.digests, self.sets, self.counters,
                          self.gauges, self.status)
        d.sync()
        s.sync()
        snap = {"counts": (self.processed, self.imported)}
        self.processed = 0
        self.imported = 0
        if self.unique_ts is not None:
            snap["unique_ts"] = self.unique_ts
            self.unique_ts = hll_mod.HLLSketch()

        for name, ar in (("counters", c), ("gauges", g), ("status", st)):
            rows = ar.touched_rows()
            snap[name] = {
                "rows": rows,
                "meta": [ar.meta[r] for r in rows],
                "values": ar.values[rows].copy(),
            }
        snap["status"]["messages"] = {
            int(r): st.messages.get(int(r), "")
            for r in snap["status"]["rows"]}
        snap["status"]["hostnames"] = {
            int(r): st.hostnames.get(int(r), "")
            for r in snap["status"]["rows"]}

        srows = s.touched_rows()
        snap["sets"] = {
            "rows": srows,
            "meta": [s.meta[r] for r in srows],
            "regs": s.regs[srows].copy(),
        }

        drows = d.touched_rows()
        snap["digests"] = {
            "rows": drows,
            "meta": [d.meta[r] for r in drows],
            # immutable device refs + scalar uploads for the SPMD flush
            "lanes": d.snapshot_lanes(),
            "flush_fn": d.flush_fn,
            "l_weight": d.l_weight[drows].copy(),
            "l_min": d.l_min[drows].copy(),
            "l_max": d.l_max[drows].copy(),
            "l_sum": d.l_sum[drows].copy(),
            "l_rsum": d.l_rsum[drows].copy(),
            "d_min": d.d_min[drows].copy(),
            "d_max": d.d_max[drows].copy(),
            "d_rsum": d.d_rsum[drows].copy(),
        }

        for ar, rows in ((c, snap["counters"]["rows"]),
                         (g, snap["gauges"]["rows"]),
                         (st, snap["status"]["rows"]),
                         (s, srows), (d, drows)):
            ar.reset_rows(rows)
            ar.end_interval()
        return snap

    # -- emitters ----------------------------------------------------------

    def _emit_counters(self, res, snap, is_local, now):
        part = snap["counters"]
        for row, meta, val in zip(part["rows"], part["meta"],
                                  part["values"]):
            if meta.scope == MetricScope.GLOBAL_ONLY:
                if is_local:
                    res.forward.append(sm.ForwardMetric(
                        name=meta.key.name, tags=meta.tags,
                        kind=sm.TYPE_COUNTER,
                        scope=MetricScope.GLOBAL_ONLY,
                        counter_value=int(val)))
                    continue
            res.metrics.append(sm.InterMetric(
                name=meta.key.name, timestamp=now, value=float(val),
                tags=meta.tags, type=sm.COUNTER))

    def _emit_gauges(self, res, snap, is_local, now):
        part = snap["gauges"]
        for row, meta, val in zip(part["rows"], part["meta"],
                                  part["values"]):
            if meta.scope == MetricScope.GLOBAL_ONLY:
                if is_local:
                    res.forward.append(sm.ForwardMetric(
                        name=meta.key.name, tags=meta.tags,
                        kind=sm.TYPE_GAUGE,
                        scope=MetricScope.GLOBAL_ONLY,
                        gauge_value=float(val)))
                    continue
            res.metrics.append(sm.InterMetric(
                name=meta.key.name, timestamp=now, value=float(val),
                tags=meta.tags, type=sm.GAUGE))

    def _emit_status(self, res, snap, now):
        part = snap["status"]
        for row, meta, val in zip(part["rows"], part["meta"],
                                  part["values"]):
            res.metrics.append(sm.InterMetric(
                name=meta.key.name, timestamp=now, value=float(val),
                tags=meta.tags, type=sm.STATUS,
                message=part["messages"][int(row)],
                hostname=part["hostnames"][int(row)]))

    def _emit_sets(self, res, snap, is_local, now):
        part = snap["sets"]
        if len(part["rows"]) == 0:
            return
        ests = np.asarray(hll_mod.estimate(jnp.asarray(part["regs"])))
        for i, (row, meta) in enumerate(zip(part["rows"], part["meta"])):
            if meta.scope == MetricScope.MIXED:
                if is_local:
                    res.forward.append(sm.ForwardMetric(
                        name=meta.key.name, tags=meta.tags,
                        kind=sm.TYPE_SET, scope=MetricScope.MIXED,
                        hll=hll_mod.marshal(part["regs"][i])))
                    continue
            res.metrics.append(sm.InterMetric(
                name=meta.key.name, timestamp=now, value=float(ests[i]),
                tags=meta.tags, type=sm.GAUGE))

    def _emit_digests(self, res, snap, is_local, now):
        part = snap["digests"]
        rows = part["rows"]
        if len(rows) == 0:
            return
        # One SPMD program call evaluates every key: lane reduce (replica-
        # axis all_gather when meshed) -> batched compress -> quantiles.
        # This IS the serving path of the north-star flush (flusher.go:26-122
        # + worker.go:402-459 as one device program).
        pl = list(self.percentiles)
        out = part["flush_fn"](
            *part["lanes"], jnp.asarray([0.5] + pl, jnp.float32))
        qs = np.asarray(out.quantiles)
        counts = np.asarray(out.counts)
        sums = np.asarray(out.sums)
        mean_np = np.asarray(out.mean)
        weight_np = np.asarray(out.weight)

        aggs = self.aggregates.value
        A = sm.Aggregate
        for i, (row, meta) in enumerate(zip(rows, part["meta"])):
            cls = meta.scope  # MIXED / GLOBAL_ONLY / LOCAL_ONLY row class
            kind = meta.key.type
            if cls == MetricScope.MIXED:
                if is_local:
                    # forward the digest; emit aggregates from local scalars
                    occ = weight_np[row] > 0
                    res.forward.append(sm.ForwardMetric(
                        name=meta.key.name, tags=meta.tags, kind=kind,
                        scope=MetricScope.MIXED,
                        digest_means=mean_np[row][occ].tolist(),
                        digest_weights=weight_np[row][occ].tolist(),
                        digest_min=float(part["d_min"][i]),
                        digest_max=float(part["d_max"][i]),
                        digest_sum=float(sums[row]),
                        digest_rsum=float(part["d_rsum"][i]),
                        digest_compression=self.digests.compression))
                    row_pcts = []
                else:
                    row_pcts = pl
                use_global = False
            elif cls == MetricScope.GLOBAL_ONLY:
                if is_local:
                    occ = weight_np[row] > 0
                    res.forward.append(sm.ForwardMetric(
                        name=meta.key.name, tags=meta.tags, kind=kind,
                        scope=MetricScope.GLOBAL_ONLY,
                        digest_means=mean_np[row][occ].tolist(),
                        digest_weights=weight_np[row][occ].tolist(),
                        digest_min=float(part["d_min"][i]),
                        digest_max=float(part["d_max"][i]),
                        digest_sum=float(sums[row]),
                        digest_rsum=float(part["d_rsum"][i]),
                        digest_compression=self.digests.compression))
                    continue  # nothing emitted locally for global-only
                row_pcts = pl
                use_global = True
            else:  # LOCAL_ONLY: flushed fully here, never forwarded
                row_pcts = pl
                use_global = False

            self._emit_histo_row(
                res, meta, now, aggs, A, use_global,
                l_weight=part["l_weight"][i], l_min=part["l_min"][i],
                l_max=part["l_max"][i], l_sum=part["l_sum"][i],
                l_rsum=part["l_rsum"][i],
                d_min=part["d_min"][i], d_max=part["d_max"][i],
                d_rsum=part["d_rsum"][i],
                d_count=counts[row], d_sum=sums[row],
                median=qs[row, 0],
                pct_values={p: qs[row, 1 + pl.index(p)] for p in row_pcts})

    def _emit_histo_row(self, res, meta, now, aggs, A, use_global, *,
                        l_weight, l_min, l_max, l_sum, l_rsum,
                        d_min, d_max, d_rsum, d_count, d_sum,
                        median, pct_values):
        """One histogram row's InterMetrics, mirroring Histo.Flush
        (samplers/samplers.go:359-514): local-scalar aggregates with
        sparse-emission guards, digest-backed values when global."""
        name = meta.key.name
        tags = meta.tags
        out = res.metrics

        def emit(suffix, value, mtype=sm.GAUGE):
            out.append(sm.InterMetric(
                name=meta.flush_name(suffix), timestamp=now,
                value=float(value), tags=tags, type=mtype))

        if aggs & A.MAX and (np.isfinite(l_max) or use_global):
            emit(".max", d_max if use_global else l_max)
        if aggs & A.MIN and (np.isfinite(l_min) or use_global):
            emit(".min", d_min if use_global else l_min)
        if aggs & A.SUM and (l_sum != 0 or use_global):
            emit(".sum", d_sum if use_global else l_sum)
        if aggs & A.AVERAGE and (use_global or (l_sum != 0 and l_weight != 0)):
            emit(".avg", (d_sum / d_count) if use_global
                 else (l_sum / l_weight))
        if aggs & A.COUNT and (l_weight != 0 or use_global):
            emit(".count", d_count if use_global else l_weight, sm.COUNTER)
        if aggs & A.MEDIAN:
            # emitted unconditionally when configured (samplers.go:466-479)
            emit(".median", median)
        if aggs & A.HARMONIC_MEAN and (use_global or
                                       (l_rsum != 0 and l_weight != 0)):
            emit(".hmean", (d_count / d_rsum) if use_global
                 else (l_weight / l_rsum))
        for p, v in pct_values.items():
            # reference naming: int(p*100), samplers.go:495-507
            emit(f".{int(p * 100)}percentile", v)
